"""Operator-overload sugar on Variable (reference: layers/math_op_patch.py)."""
from __future__ import annotations

import numpy as np


def binary(var, other, op_type: str, reverse: bool = False):
    from ..framework import Variable
    from ..layer_helper import LayerHelper

    helper = LayerHelper(op_type)
    if not isinstance(other, Variable):
        # scalar -> fill_constant of var's dtype, broadcastable shape [1]
        val = float(other)
        tmp = helper.create_variable_for_type_inference(dtype=var.dtype)
        helper.append_op("fill_constant", outputs={"Out": tmp},
                         attrs={"shape": [1], "dtype": var.dtype, "value": val})
        other = tmp
    x, y = (other, var) if reverse else (var, other)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(op_type, inputs={"X": x, "Y": y}, outputs={"Out": out},
                     attrs={"axis": -1})
    return out
