"""LR schedulers as in-program ops (reference:
python/paddle/fluid/layers/learning_rate_scheduler.py:53-441 — noam,
exponential, natural_exp, inverse_time, polynomial, piecewise, cosine,
linear warmup).

Same design as the reference: a persistable global-step counter is
incremented each step and the decayed LR is computed by ops inside the main
program, so the whole schedule compiles into the train step."""
from __future__ import annotations

import math

from .. import unique_name
from ..framework import default_main_program, default_startup_program
from ..layer_helper import LayerHelper
from . import nn, tensor

__all__ = ["noam_decay", "exponential_decay", "natural_exp_decay",
           "inverse_time_decay", "polynomial_decay", "piecewise_decay",
           "cosine_decay", "linear_lr_warmup"]

LR_COUNTER_NAME = "@LR_DECAY_COUNTER@"


def _decay_step_counter(begin=0):
    main = default_main_program().global_block
    startup = default_startup_program().global_block
    if not main.has_var(LR_COUNTER_NAME):
        main.create_var(name=LR_COUNTER_NAME, shape=(1,), dtype="float32",
                        persistable=True, stop_gradient=True)
        startup.create_var(name=LR_COUNTER_NAME, shape=(1,), dtype="float32",
                           persistable=True)
        # init to begin-1: the prepended increment runs before first use, so
        # the first step observes `begin` (reference autoincreased_step_counter)
        startup.append_op("fill_constant", outputs={"Out": LR_COUNTER_NAME},
                          attrs={"shape": [1], "dtype": "float32",
                                 "value": float(begin) - 1.0})
        # lr_sched role: pruned by clone(for_test=True) so inference runs
        # don't advance the schedule (reference OpRole.LRSched)
        main.prepend_op("increment", inputs={"X": LR_COUNTER_NAME},
                        outputs={"Out": LR_COUNTER_NAME},
                        attrs={"step": 1.0, "__op_role__": "lr_sched"})
    return main.var(LR_COUNTER_NAME)


def _const(value):
    return tensor.fill_constant([1], "float32", float(value))


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    """lr = lr0 * d_model^-0.5 * min(step^-0.5, step*warmup^-1.5)."""
    step = _decay_step_counter(begin=1)
    a = step ** -0.5
    b = step * float(warmup_steps ** -1.5)
    lr = nn.elementwise_min(a, b)
    return nn.scale(lr, scale=float(learning_rate) * d_model ** -0.5)


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    step = _decay_step_counter()
    ratio = nn.scale(step, scale=1.0 / decay_steps)
    if staircase:
        ratio = nn.floor(ratio)
    return nn.scale(_const(decay_rate) ** ratio,
                    scale=float(learning_rate))


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    step = _decay_step_counter()
    ratio = nn.scale(step, scale=1.0 / decay_steps)
    if staircase:
        ratio = nn.floor(ratio)
    return nn.scale(nn.exp(nn.scale(ratio, scale=-decay_rate)),
                    scale=float(learning_rate))


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    step = _decay_step_counter()
    ratio = nn.scale(step, scale=1.0 / decay_steps)
    if staircase:
        ratio = nn.floor(ratio)
    denom = nn.scale(ratio, scale=decay_rate, bias=1.0)
    return nn.elementwise_div(_const(learning_rate), denom)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    step = _decay_step_counter()
    if cycle:
        div = nn.ceil(nn.scale(step, scale=1.0 / decay_steps))
        # at step 0, div must be 1
        one = _const(1.0)
        zero = _const(0.0)
        is_zero = nn.cast(nn.equal(step, zero), "float32")
        div = nn.elementwise_add(div, is_zero)
        total = nn.scale(div, scale=float(decay_steps))
    else:
        total = _const(decay_steps)
        step = nn.elementwise_min(step, total)
    frac = nn.elementwise_div(step, total)
    base = nn.scale(frac, scale=-1.0, bias=1.0) ** power
    return nn.scale(base, scale=float(learning_rate - end_learning_rate),
                    bias=float(end_learning_rate))


def piecewise_decay(boundaries, values):
    assert len(values) == len(boundaries) + 1
    step = _decay_step_counter()
    lr = _const(values[-1])
    # evaluate from the last boundary backwards: where(step<b_i, v_i, lr)
    for b, v in zip(reversed(boundaries), reversed(values[:-1])):
        cond = nn.less_than(step, _const(b))
        lr = nn.where(cond, _const(v), lr)
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    """lr = 0.5 * lr0 * (cos(epoch * pi / epochs) + 1)"""
    step = _decay_step_counter()
    epoch = nn.floor(nn.scale(step, scale=1.0 / step_each_epoch))
    cosv = nn.cos(nn.scale(epoch, scale=math.pi / epochs))
    return nn.scale(nn.scale(cosv, scale=1.0, bias=1.0),
                    scale=0.5 * learning_rate)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    step = _decay_step_counter()
    warm = nn.scale(step, scale=float(end_lr - start_lr) / warmup_steps,
                    bias=float(start_lr))
    in_warmup = nn.less_than(step, _const(warmup_steps))
    if not hasattr(learning_rate, "name"):  # python float
        learning_rate = _const(learning_rate)
    return nn.where(in_warmup, warm, learning_rate)
