"""Detection layers (reference python/paddle/fluid/layers/detection.py,
28 functions — the structural subset over ops/detection.py)."""
from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = ["prior_box", "anchor_generator", "iou_similarity", "box_coder",
           "box_clip", "yolo_box", "multiclass_nms", "roi_align", "roi_pool"]


def _one_out(helper, dtype="float32", stop_gradient=False):
    return helper.create_variable_for_type_inference(dtype, stop_gradient)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    helper = LayerHelper("prior_box", name=name)
    boxes = _one_out(helper, input.dtype, True)
    var = _one_out(helper, input.dtype, True)
    helper.append_op("prior_box", inputs={"Input": input, "Image": image},
                     outputs={"Boxes": boxes, "Variances": var},
                     attrs={"min_sizes": list(min_sizes),
                            "max_sizes": list(max_sizes or []),
                            "aspect_ratios": list(aspect_ratios),
                            "variances": list(variance), "flip": flip,
                            "clip": clip, "step_w": steps[0],
                            "step_h": steps[1], "offset": offset,
                            "min_max_aspect_ratios_order":
                                min_max_aspect_ratios_order})
    return boxes, var


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None, offset=0.5,
                     name=None):
    helper = LayerHelper("anchor_generator", name=name)
    anchors = _one_out(helper, input.dtype, True)
    var = _one_out(helper, input.dtype, True)
    helper.append_op(
        "anchor_generator", inputs={"Input": input},
        outputs={"Anchors": anchors, "Variances": var},
        attrs={"anchor_sizes": list(anchor_sizes or [64., 128., 256., 512.]),
               "aspect_ratios": list(aspect_ratios or [0.5, 1.0, 2.0]),
               "variances": list(variance),
               "stride": list(stride or [16.0, 16.0]), "offset": offset})
    return anchors, var


def iou_similarity(x, y, box_normalized=True, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = _one_out(helper, x.dtype, True)
    helper.append_op("iou_similarity", inputs={"X": x, "Y": y},
                     outputs={"Out": out},
                     attrs={"box_normalized": box_normalized})
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    helper = LayerHelper("box_coder", name=name)
    out = _one_out(helper, target_box.dtype)
    ins = {"PriorBox": prior_box, "TargetBox": target_box}
    if prior_box_var is not None:
        ins["PriorBoxVar"] = prior_box_var
    helper.append_op("box_coder", inputs=ins, outputs={"OutputBox": out},
                     attrs={"code_type": code_type,
                            "box_normalized": box_normalized, "axis": axis})
    return out


def box_clip(input, im_info, name=None):
    helper = LayerHelper("box_clip", name=name)
    out = _one_out(helper, input.dtype)
    helper.append_op("box_clip", inputs={"Input": input, "ImInfo": im_info},
                     outputs={"Output": out})
    return out


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, name=None):
    helper = LayerHelper("yolo_box", name=name)
    boxes = _one_out(helper, x.dtype, True)
    scores = _one_out(helper, x.dtype, True)
    helper.append_op("yolo_box", inputs={"X": x, "ImgSize": img_size},
                     outputs={"Boxes": boxes, "Scores": scores},
                     attrs={"anchors": list(anchors),
                            "class_num": int(class_num),
                            "conf_thresh": float(conf_thresh),
                            "downsample_ratio": int(downsample_ratio)})
    return boxes, scores


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None):
    helper = LayerHelper("multiclass_nms", name=name)
    out = _one_out(helper, bboxes.dtype, True)
    helper.append_op("multiclass_nms",
                     inputs={"BBoxes": bboxes, "Scores": scores},
                     outputs={"Out": out},
                     attrs={"background_label": background_label,
                            "score_threshold": float(score_threshold),
                            "nms_top_k": int(nms_top_k),
                            "nms_threshold": float(nms_threshold),
                            "nms_eta": float(nms_eta),
                            "keep_top_k": int(keep_top_k),
                            "normalized": normalized})
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, rois_batch_idx=None,
              name=None):
    helper = LayerHelper("roi_align", name=name)
    out = _one_out(helper, input.dtype)
    ins = {"X": input, "ROIs": rois}
    if rois_batch_idx is not None:
        ins["RoisBatchIdx"] = rois_batch_idx
    helper.append_op("roi_align", inputs=ins, outputs={"Out": out},
                     attrs={"spatial_scale": float(spatial_scale),
                            "pooled_height": int(pooled_height),
                            "pooled_width": int(pooled_width),
                            "sampling_ratio": int(sampling_ratio)})
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_batch_idx=None, name=None):
    helper = LayerHelper("roi_pool", name=name)
    out = _one_out(helper, input.dtype)
    ins = {"X": input, "ROIs": rois}
    if rois_batch_idx is not None:
        ins["RoisBatchIdx"] = rois_batch_idx
    helper.append_op("roi_pool", inputs=ins, outputs={"Out": out},
                     attrs={"spatial_scale": float(spatial_scale),
                            "pooled_height": int(pooled_height),
                            "pooled_width": int(pooled_width)})
    return out
