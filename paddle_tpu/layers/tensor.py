"""Tensor creation layers (reference: python/paddle/fluid/layers/tensor.py)."""
from __future__ import annotations

import numpy as np

from ..core.types import canonical_dtype
from ..framework import Variable
from ..layer_helper import LayerHelper

__all__ = ["create_tensor", "create_global_var", "fill_constant",
           "fill_constant_batch_size_like", "assign", "cast", "zeros", "ones",
           "zeros_like", "ones_like", "range", "linspace", "scale",
           "uniform_random", "gaussian_random"]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_global_variable(shape=[1], dtype=dtype,
                                         persistable=persistable, name=name)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(shape=shape, dtype=dtype,
                                        persistable=persistable, name=name)
    helper.startup_program.global_block.create_var(
        name=var.name, shape=tuple(shape), dtype=dtype, persistable=persistable)
    helper.startup_program.global_block.append_op(
        "fill_constant", outputs={"Out": var.name},
        attrs={"shape": list(shape), "dtype": canonical_dtype(dtype),
               "value": float(value)})
    return var


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    if out is None:
        out = helper.create_variable_for_type_inference(canonical_dtype(dtype))
    helper.append_op("fill_constant", outputs={"Out": out},
                     attrs={"shape": list(shape),
                            "dtype": canonical_dtype(dtype),
                            "value": float(value)})
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(canonical_dtype(dtype))
    helper.append_op("fill_constant_batch_size_like",
                     inputs={"Input": input}, outputs={"Out": out},
                     attrs={"shape": list(shape),
                            "dtype": canonical_dtype(dtype),
                            "value": float(value),
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("assign", inputs={"X": input},
                         outputs={"Out": output})
    else:
        arr = np.asarray(input)
        if output is None:
            output = helper.create_variable_for_type_inference(
                canonical_dtype(arr.dtype))
        helper.append_op("assign_value", outputs={"Out": output},
                         attrs={"shape": list(arr.shape),
                                "dtype": canonical_dtype(arr.dtype),
                                "values": [v.item() for v in arr.flat]})
    return output


def cast(x, dtype):
    from .nn import cast as _cast

    return _cast(x, dtype)


def zeros(shape, dtype="float32", force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def ones(shape, dtype="float32", force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros_like(x, out=None):
    helper = LayerHelper("fill_zeros_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fill_zeros_like", inputs={"X": x}, outputs={"Out": out})
    return out


def ones_like(x, out=None):
    helper = LayerHelper("ones_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("scale", inputs={"X": x}, outputs={"Out": out},
                     attrs={"scale": 0.0, "bias": 1.0})
    return out


def range(start, end, step, dtype="float32"):
    if isinstance(start, Variable) or isinstance(end, Variable) \
            or isinstance(step, Variable):
        raise ValueError(
            "layers.range requires numeric bounds: XLA compiles static "
            "shapes, so a tensor-valued range length cannot be lowered")
    helper = LayerHelper("range")
    out = helper.create_variable_for_type_inference(canonical_dtype(dtype))
    helper.append_op("range", outputs={"Out": out},
                     attrs={"start": float(start), "end": float(end),
                            "step": float(step),
                            "dtype": canonical_dtype(dtype),
                            "use_attrs": True})
    return out


def linspace(start, stop, num, dtype="float32"):
    step = (stop - start) / max(num - 1, 1)
    return range(start, stop + step / 2, step, dtype)


def scale(x, **kwargs):
    from .nn import scale as _scale

    return _scale(x, **kwargs)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_variable_for_type_inference(canonical_dtype(dtype))
    helper.append_op("uniform_random", outputs={"Out": out},
                     attrs={"shape": list(shape),
                            "dtype": canonical_dtype(dtype),
                            "min": float(min), "max": float(max),
                            "seed": seed})
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(canonical_dtype(dtype))
    helper.append_op("gaussian_random", outputs={"Out": out},
                     attrs={"shape": list(shape),
                            "dtype": canonical_dtype(dtype),
                            "mean": float(mean), "std": float(std),
                            "seed": seed})
    return out
