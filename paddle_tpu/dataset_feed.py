"""Dataset over the native C++ data-feed engine.

Reference: python/paddle/fluid/dataset.py (DatasetFactory :22,
QueueDataset/InMemoryDataset) configuring the C++ Dataset/MultiSlotDataFeed
(framework/data_set.h, data_feed.h) that `exe.train_from_dataset` consumes.

Here the same MultiSlot text protocol is parsed by
paddle_tpu/native/datafeed.cpp on GIL-free threads into a bounded blocking
queue; ``iter_batches`` drains it as {slot: ndarray} feeds for exe.run.
With no C++ toolchain the pure-Python parser below keeps behaviour
identical (slower; a warning is recorded in ``using_native``).
"""
from __future__ import annotations

import ctypes
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["MultiSlotDataset", "DatasetFactory"]


class MultiSlotDataset:
    """use_var-style config: slots are (name, dtype, length) with dtype
    'float32' or 'int64' (the reference's two MultiSlot types)."""

    def __init__(self):
        self._slots: List[Tuple[str, str, int]] = []
        self._files: List[str] = []
        self._threads = 1
        self._batch = 1
        self._capacity = 1024

    # -- reference Dataset config surface --------------------------------
    def set_use_var(self, slots: Sequence[Tuple[str, str, int]]):
        self._slots = []  # replace, not append (reference set_use_var)
        for name, dtype, length in slots:
            if dtype not in ("float32", "int64"):
                raise ValueError(f"slot '{name}': dtype must be float32 or "
                                 f"int64 (MultiSlot protocol), got {dtype}")
            if ":" in name or "," in name:
                raise ValueError(
                    f"slot name '{name}' may not contain ':' or ',' (they "
                    f"delimit the native engine's spec string)")
            self._slots.append((name, dtype, int(length)))

    def set_filelist(self, files: Sequence[str]):
        self._files = list(files)

    def set_thread(self, n: int):
        self._threads = max(1, int(n))

    def set_batch_size(self, n: int):
        self._batch = max(1, int(n))

    def set_queue_capacity(self, n: int):
        self._capacity = max(2, int(n))

    # -- consumption ------------------------------------------------------
    @property
    def using_native(self) -> bool:
        from . import native

        return native.load_datafeed() is not None

    def iter_batches(self) -> Iterator[Dict[str, np.ndarray]]:
        if not self._slots:
            raise RuntimeError("set_use_var first")
        if not self._files:
            raise RuntimeError("set_filelist first")
        from . import native

        lib = native.load_datafeed()
        if lib is None:
            yield from self._iter_python()
            return
        spec = ",".join(f"{n}:{'f' if d == 'float32' else 'i'}:{l}"
                        for n, d, l in self._slots)
        h = lib.df_create(spec.encode())
        if not h:
            raise RuntimeError(f"bad slot spec: {spec}")
        try:
            lib.df_set_capacity(h, self._capacity)
            for f in self._files:
                lib.df_add_file(h, f.encode())
            if lib.df_start(h, self._threads) != 0:
                raise RuntimeError("datafeed already started")
            fslots = [(n, l) for n, d, l in self._slots if d == "float32"]
            islots = [(n, l) for n, d, l in self._slots if d == "int64"]
            while True:
                fbufs = [np.empty((self._batch, l), np.float32)
                         for _, l in fslots]
                ibufs = [np.empty((self._batch, l), np.int64)
                         for _, l in islots]
                fptrs = (ctypes.c_void_p * max(1, len(fbufs)))(
                    *[b.ctypes.data for b in fbufs] or [None])
                iptrs = (ctypes.c_void_p * max(1, len(ibufs)))(
                    *[b.ctypes.data for b in ibufs] or [None])
                rows = lib.df_next(h, self._batch, fptrs, iptrs)
                if rows <= 0:
                    break
                batch = {}
                for (n, _), b in zip(fslots, fbufs):
                    batch[n] = b[:rows]
                for (n, _), b in zip(islots, ibufs):
                    batch[n] = b[:rows]
                yield batch
                if rows < self._batch:
                    break
        finally:
            lib.df_stop_join(h)  # race-free: producers joined before read
            self._parse_errors = int(lib.df_parse_errors(h))
            lib.df_destroy(h)

    def parse_errors(self) -> int:
        """Malformed rows skipped during the LAST completed iteration."""
        return getattr(self, "_parse_errors", 0)

    # -- pure-Python fallback (no toolchain) ------------------------------
    def _iter_python(self) -> Iterator[Dict[str, np.ndarray]]:
        rows: List[List[np.ndarray]] = []

        def flush(rows):
            batch = {}
            for i, (n, d, l) in enumerate(self._slots):
                batch[n] = np.stack([r[i] for r in rows])
            return batch

        self._parse_errors = 0
        for path in self._files:
            with open(path) as f:
                for line in f:
                    toks = line.split()
                    if not toks:
                        continue
                    try:
                        vals, pos = [], 0
                        for n, d, l in self._slots:
                            cnt = int(toks[pos]); pos += 1
                            if cnt != l:
                                raise ValueError("slot length mismatch")
                            dt = np.float32 if d == "float32" else np.int64
                            vals.append(np.array(toks[pos:pos + cnt], dt))
                            if len(vals[-1]) != cnt:
                                raise ValueError("truncated line")
                            pos += cnt
                    except (ValueError, IndexError):
                        # skip malformed rows like the native engine
                        self._parse_errors += 1
                        continue
                    rows.append(vals)
                    if len(rows) == self._batch:
                        yield flush(rows)
                        rows = []
        if rows:
            yield flush(rows)


class DatasetFactory:
    """reference dataset.py:22 DatasetFactory.create_dataset."""

    def create_dataset(self, datafeed_class: str = "QueueDataset"):
        if datafeed_class in ("QueueDataset", "InMemoryDataset",
                              "MultiSlotDataset"):
            return MultiSlotDataset()
        raise ValueError(f"unknown dataset class {datafeed_class}")
