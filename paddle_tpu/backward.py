"""append_backward: registry-driven autodiff as a Program->Program transform.

Reference: python/paddle/fluid/backward.py:916 append_backward, :303
_addup_repetitive_outputs_ (sum-dedup of multi-consumer grads), :385
no-grad-branch pruning, with per-op grad descs produced by C++
GradOpDescMakers (grad_op_desc_maker.h:36).

TPU-native twist: the default grad "desc maker" is generic — it emits a
``<type>_grad`` op carrying the forward op's inputs, outputs and attrs; its
lowering recomputes the forward rule under jax.vjp (see lowering.py). Ops can
still register custom makers/lowerings. The program-level semantics the
reference guarantees (grad accumulation via sum ops, stop_gradient fences,
parameter_list filtering) are reproduced here at the desc level, NOT via
jax.grad over the whole block — so a serialized program contains its own
backward, exactly like a Fluid ProgramDesc.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

from .core import registry
from .core.types import is_floating
from .framework import (GRAD_VAR_SUFFIX, Operator, Parameter, Program,
                        Variable, grad_var_name)
from .lowering import EMPTY_VAR_NAME

__all__ = ["append_backward", "gradients", "calc_gradient"]


def _find_op_path(block, target_names: Sequence[str]) -> List[int]:
    """Reverse reachability from the targets (reference backward.py:1137)."""
    needed: Set[str] = set(target_names)
    path: List[int] = []
    for idx in range(len(block.ops) - 1, -1, -1):
        op = block.ops[idx]
        if any(n in needed for n in op.output_arg_names):
            path.append(idx)
            needed.update(n for n in op.input_arg_names if n != EMPTY_VAR_NAME)
    path.reverse()
    return path


def _var_can_carry_grad(block, name: str) -> bool:
    if name == EMPTY_VAR_NAME or not block.has_var_recursive(name):
        return False
    v = block._var_recursive(name)
    return not v.stop_gradient and is_floating(v.dtype)


class _GradAccumulator:
    """Tracks grad contributions per forward var and inserts sum ops when a
    var has several consumers (reference _addup_repetitive_outputs_)."""

    def __init__(self, block):
        self.block = block
        self.contribs: Dict[str, List[str]] = {}

    def new_contrib_name(self, fwd_name: str) -> str:
        lst = self.contribs.setdefault(fwd_name, [])
        name = grad_var_name(fwd_name) if not lst else (
            f"{grad_var_name(fwd_name)}@RENAME@{len(lst)}")
        lst.append(name)
        return name

    def resolve(self, fwd_name: str) -> Optional[str]:
        """Final grad name for fwd_name, inserting a sum op if needed."""
        lst = self.contribs.get(fwd_name)
        if not lst:
            return None
        if len(lst) == 1:
            return lst[0]
        target = grad_var_name(fwd_name)
        self._create_grad_var(target, fwd_name)
        self.block.append_op("sum", inputs={"X": list(lst)},
                             outputs={"Out": target})
        self.contribs[fwd_name] = [target]
        return target

    def _create_grad_var(self, grad_name: str, fwd_name: str):
        if self.block.has_var(grad_name):
            return self.block.var(grad_name)
        fwd = self.block._var_recursive(fwd_name)
        return self.block.create_var(name=grad_name, shape=fwd.shape,
                                     dtype=fwd.dtype, stop_gradient=True)


def _make_grad_op(op, out_grad: Dict[str, List[str]],
                  in_grad: Dict[str, List[str]]) -> dict:
    """Generic grad-op desc (consumed by lowering._lower_generic_grad)."""
    inputs: Dict[str, List[str]] = {}
    opdef = registry.get_op_def(op.type)
    needed = set(s.name for s in opdef.inputs) - set(opdef.no_need_buffer)
    for slot, names in op.inputs.items():
        if slot in needed:
            inputs[slot] = list(names)
    for slot, names in op.outputs.items():
        inputs["__out__" + slot] = list(names)
    inputs.update(out_grad)
    attrs = dict(op.attrs)
    # the grad op must get ITS OWN role/uid stamps — inheriting the
    # forward's '__op_role__' would make clone(for_test=True) keep grad ops
    attrs.pop("__op_role__", None)
    attrs.pop("__uid__", None)
    attrs["__fwd_type__"] = op.type
    attrs["__fwd_uid__"] = op.attrs.get("__uid__", 0)
    return {"type": op.type + "_grad", "inputs": inputs,
            "outputs": in_grad, "attrs": attrs}


def _append_backward_core(block, targets: Sequence[Variable],
                          target_gradients, no_grad: Set[str]):
    """Shared reverse sweep used by append_backward and calc_gradient."""
    path = _find_op_path(block, [t.name for t in targets])
    acc = _GradAccumulator(block)

    # seed cotangents: user-provided grads or 1.0 (reference: fill_constant)
    target_gradients = target_gradients or [None] * len(targets)
    for tgt, tg in zip(targets, target_gradients):
        seed = acc.new_contrib_name(tgt.name)
        acc._create_grad_var(seed, tgt.name)
        if tg is None:
            block.append_op(
                "fill_constant", outputs={"Out": seed},
                attrs={"shape": list(tgt.shape if tgt.shape is not None else [1]),
                       "dtype": tgt.dtype, "value": 1.0})
        else:
            block.append_op("assign", inputs={"X": tg}, outputs={"Out": seed})

    for idx in reversed(path):
        op = block.ops[idx]
        if not registry.has_op(op.type):
            continue
        opdef = registry.get_op_def(op.type)
        if opdef.grad is None:
            continue

        # cotangents available for this op's outputs?
        out_grad: Dict[str, List[str]] = {}
        any_out_grad = False
        for slot, names in op.outputs.items():
            gnames = []
            for n in names:
                g = acc.resolve(n) if n != EMPTY_VAR_NAME else None
                gnames.append(g if g is not None else EMPTY_VAR_NAME)
                any_out_grad = any_out_grad or g is not None
            out_grad[slot + "@GRAD"] = gnames
        if not any_out_grad:
            continue

        # this op's grad consumes the cotangents of its outputs; clear them
        # BEFORE registering in_grad contributions so an EARLIER producer of
        # the same name (in-place update, e.g. a while writing its own
        # input) doesn't re-sum them — the earlier producer's cotangent is
        # exactly the in_grad contribution this op registers below
        # (reference _addup_repetitive_outputs_ reaches the same effect by
        # renaming repeated outputs)
        for names in op.outputs.values():
            for n in names:
                if n != EMPTY_VAR_NAME and acc.contribs.get(n):
                    acc.contribs[n] = []

        # which inputs get grads?
        in_grad: Dict[str, List[str]] = {}
        any_in_grad = False
        for slot, names in op.inputs.items():
            spec = opdef.input_spec(slot)
            if spec is not None and spec.no_grad:
                continue
            gnames = []
            produce_any = False
            for n in names:
                if n in no_grad or not _var_can_carry_grad(block, n):
                    gnames.append(EMPTY_VAR_NAME)
                else:
                    gname = acc.new_contrib_name(n)
                    acc._create_grad_var(gname, n)
                    gnames.append(gname)
                    produce_any = True
            if produce_any:
                in_grad[slot + "@GRAD"] = gnames
                any_in_grad = True
        if not any_in_grad:
            continue

        if callable(opdef.grad):
            for desc in opdef.grad(op, block, out_grad, in_grad):
                block.append_op(desc["type"], inputs=desc["inputs"],
                                outputs=desc["outputs"], attrs=desc["attrs"])
        else:
            desc = _make_grad_op(op, out_grad, in_grad)
            block.append_op(desc["type"], inputs=desc["inputs"],
                            outputs=desc["outputs"], attrs=desc["attrs"])

    return acc


def append_backward(loss: Variable, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Append grad ops for every op on the loss's path; returns
    [(param, grad_var), ...] like the reference (backward.py:916)."""
    block = loss.block
    program: Program = block.program
    with program._op_role_guard("backward"):
        acc = _append_backward_core(block, [loss], None,
                                    set(no_grad_set or ()))

        params = (program.all_parameters() if parameter_list is None else [
            block._var_recursive(p) if isinstance(p, str) else p
            for p in parameter_list
        ])
        result = []
        for p in params:
            if isinstance(p, Parameter) and not p.trainable:
                continue
            # resolve() may append a grad-accumulation sum op (multi-use
            # params, e.g. a tied embedding); it must carry the backward
            # role or clone(for_test=True) would keep it dangling after
            # its @GRAD inputs are pruned
            g = acc.resolve(p.name)
            if g is not None:
                result.append((p, block.var(g)))
    return result


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference backward.py:1177 — grads of targets wrt arbitrary inputs,
    optionally seeded with user cotangents."""
    targets = list(targets) if isinstance(targets, (list, tuple)) else [targets]
    inputs = list(inputs) if isinstance(inputs, (list, tuple)) else [inputs]
    if target_gradients is not None and not isinstance(target_gradients,
                                                       (list, tuple)):
        target_gradients = [target_gradients]
    if target_gradients is not None and len(target_gradients) != len(targets):
        raise ValueError("target_gradients length must match targets")
    block = targets[0].block
    acc = _append_backward_core(block, targets, target_gradients,
                                set(no_grad_set or ()))
    outs = []
    for iv in inputs:
        g = acc.resolve(iv.name)
        outs.append(block.var(g) if g is not None else None)
    return outs


gradients = calc_gradient
