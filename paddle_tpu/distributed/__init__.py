"""Multi-process distributed runtime: env contract + JAX bootstrap.

TPU-native replacement for the reference's NCCL2 bootstrap path: the
``gen_nccl_id`` op's TCP exchange of ncclUniqueId
(reference: paddle/fluid/operators/distributed_ops/gen_nccl_id_op.cc:162) and
the transpiler's nccl2 mode (transpiler/distribute_transpiler.py:308) collapse
into one ``jax.distributed.initialize`` call; XLA then runs collectives over
ICI/DCN directly. The PADDLE_* environment contract is kept verbatim from the
reference launcher (python/paddle/distributed/launch.py:147) so reference
cluster tooling works unchanged:

  PADDLE_TRAINER_ID         this process's rank            (int)
  PADDLE_TRAINERS_NUM       world size                     (int)
  PADDLE_CURRENT_ENDPOINT   this process's ip:port
  PADDLE_TRAINER_ENDPOINTS  comma-separated all endpoints; [0] doubles as the
                            jax.distributed coordinator address
  PADDLE_DIST_BACKEND       optional: "cpu" forces the CPU backend with gloo
                            collectives (multi-host simulation on one host);
                            unset -> real TPU backend
  PADDLE_LOCAL_DEVICES      optional: devices per process on the cpu backend
"""
from __future__ import annotations

import os
from typing import List, Optional

__all__ = ["ParallelEnv", "init_parallel_env", "get_rank", "get_world_size",
           "is_initialized", "barrier", "all_gather_object"]


class ParallelEnv:
    """Reference dygraph/parallel.py:54 Env: the cluster env-var view."""

    def __init__(self):
        self.trainer_id = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self.nranks = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        self.current_endpoint = os.getenv("PADDLE_CURRENT_ENDPOINT", "")
        eps = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
        self.trainer_endpoints: List[str] = [e for e in eps.split(",") if e]
        self.backend = os.getenv("PADDLE_DIST_BACKEND", "")
        self.local_devices = int(os.getenv("PADDLE_LOCAL_DEVICES", "0"))

    @property
    def rank(self) -> int:
        return self.trainer_id

    @property
    def world_size(self) -> int:
        return self.nranks

    @property
    def dev_id(self) -> int:
        return int(os.getenv("FLAGS_selected_tpus",
                             os.getenv("FLAGS_selected_gpus", "0")))


_initialized = False


def force_cpu_device_count(n: int) -> None:
    """Pin the CPU backend to ``n`` virtual devices across jax generations:
    newer jax has the ``jax_num_cpu_devices`` config; 0.4.x only honours
    the XLA_FLAGS env var, which must land before the backend initializes
    (both paths require that — backend init freezes the topology)."""
    import jax

    try:
        jax.config.update("jax_num_cpu_devices", int(n))
    except AttributeError:  # jax 0.4.x
        import re

        # replace (not append after) an inherited count — a pytest parent's
        # 8-virtual-device XLA_FLAGS must not leak into a 1-device worker
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       os.environ.get("XLA_FLAGS", "")).strip()
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={int(n)}"
        ).strip()


def is_initialized() -> bool:
    return _initialized


def init_parallel_env(coordinator_address: Optional[str] = None) -> ParallelEnv:
    """Bootstrap the multi-process runtime from the PADDLE_* env contract.

    Single-process (PADDLE_TRAINERS_NUM absent or 1) is a no-op, so the same
    training script runs standalone or under the launcher — the reference's
    transpile-if-distributed pattern without the transpiler.

    Must run before any JAX computation (backend init freezes the topology,
    like NCCL comm init in the reference).
    """
    global _initialized
    env = ParallelEnv()
    if env.nranks <= 1 or _initialized:
        return env

    import jax

    if env.backend == "cpu":
        # multi-host simulation: CPU backend, gloo collectives over TCP
        backends = getattr(jax._src.xla_bridge, "_backends", None)
        if backends:
            raise RuntimeError(
                "init_parallel_env must run before JAX initializes a backend")
        jax.config.update("jax_platforms", "cpu")
        force_cpu_device_count(env.local_devices or 1)
        jax.config.update("jax_cpu_collectives_implementation", "gloo")

    coord = coordinator_address or (
        env.trainer_endpoints[0] if env.trainer_endpoints else None)
    if coord is None:
        raise RuntimeError(
            "init_parallel_env: no coordinator — set PADDLE_TRAINER_ENDPOINTS "
            "or pass coordinator_address")
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=env.nranks,
                               process_id=env.trainer_id)
    _initialized = True
    return env


def get_rank() -> int:
    return ParallelEnv().trainer_id


def get_world_size() -> int:
    return ParallelEnv().nranks


def barrier() -> None:
    """Host-level sync via the coordination service (reference: the barrier
    semantics of listen_and_serv's RunSyncLoop, minus the parameter server)."""
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("paddle_tpu_barrier")


def all_gather_object(arr):
    """Gather a numpy array from every process; returns a list indexed by
    rank (debug/metrics aggregation across trainers)."""
    import jax
    import numpy as np

    if jax.process_count() <= 1:
        return [np.asarray(arr)]
    from jax.experimental import multihost_utils

    stacked = multihost_utils.process_allgather(np.asarray(arr))
    return [np.asarray(s) for s in stacked]


def allgather_mean_tree(tree: dict) -> dict:
    """Average a {key: ndarray} tree across processes in ONE collective
    (identity single-process). Shared by LocalSGD and dygraph DataParallel
    — the coalesced-allreduce primitive of the reference's collective
    transpiler."""
    import jax
    import numpy as np

    if jax.process_count() <= 1:
        return dict(tree)
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(
        {k: np.asarray(v) for k, v in tree.items()}, tiled=False)
    return {k: jax.numpy.asarray(np.mean(np.asarray(gathered[k]), axis=0))
            for k in tree}
