"""Multi-process launcher: ``python -m paddle_tpu.distributed.launch``.

Reference: python/paddle/distributed/launch.py:281 (one trainer process per
GPU with PADDLE_* env vars; :147 start_procs, :141 terminate_procs). The TPU
shape is one process per HOST (JAX owns every local chip in-process), so
--nproc_per_node defaults to 1 on real hardware; >1 is the multi-host
simulation mode on the CPU backend (--backend cpu) used by the distributed
tests — the role the reference's test_dist_base localhost subprocesses play.

Usage:
  python -m paddle_tpu.distributed.launch --nproc_per_node 2 \
      --backend cpu train.py --my-flag ...
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time

__all__ = ["launch", "find_free_ports"]


def _parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--cluster_node_ips", type=str, default="127.0.0.1",
                   help="comma-separated node ips (reference flag)")
    p.add_argument("--node_ip", type=str, default="127.0.0.1",
                   help="this node's ip")
    p.add_argument("--started_port", type=int, default=0,
                   help="first endpoint port; 0 picks free ports")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="trainer processes on this node")
    p.add_argument("--backend", type=str, default="",
                   choices=["", "cpu", "tpu"],
                   help="cpu = multi-host simulation with gloo collectives")
    p.add_argument("--local_devices", type=int, default=1,
                   help="devices per process on the cpu backend")
    p.add_argument("--log_dir", type=str, default=None,
                   help="redirect each rank's output to {log_dir}/workerlog.N")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def find_free_ports(n: int) -> list:
    """Bind-then-release to reserve n distinct free ports (the reference's
    dist_test.sh retried on conflicts; reserving up front avoids the retry)."""
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def launch(args=None) -> int:
    args = args or _parse_args()
    node_ips = [ip for ip in args.cluster_node_ips.split(",") if ip]
    nproc = args.nproc_per_node
    if args.started_port:
        ports = [args.started_port + i for i in range(nproc)]
    else:
        if len(node_ips) > 1:
            # auto-discovered ports are LOCAL: other nodes would pick
            # different ones and the cross-node endpoint lists (and the
            # rank-0 coordinator address) would disagree
            raise ValueError(
                "multi-node launch (cluster_node_ips has "
                f"{len(node_ips)} nodes) requires an explicit "
                "--started_port so every node builds the same endpoint "
                "list; port auto-discovery only works single-node")
        ports = find_free_ports(nproc)
    # endpoints for ALL nodes; this launcher starts only this node's procs
    endpoints = []
    for ip in node_ips:
        endpoints += [f"{ip}:{p}" for p in ports]
    node_rank = node_ips.index(args.node_ip)

    procs, log_files = [], []
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
    for local_rank in range(nproc):
        rank = node_rank * nproc + local_rank
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(len(node_ips) * nproc),
            "PADDLE_CURRENT_ENDPOINT": f"{args.node_ip}:{ports[local_rank]}",
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "FLAGS_selected_tpus": str(local_rank),
        })
        if args.backend:
            env["PADDLE_DIST_BACKEND"] = args.backend
            env["PADDLE_LOCAL_DEVICES"] = str(args.local_devices)
        cmd = [sys.executable, "-u", args.training_script] \
            + args.training_script_args
        out = None
        if args.log_dir:
            out = open(os.path.join(args.log_dir, f"workerlog.{local_rank}"),
                       "w")
            log_files.append(out)
        procs.append(subprocess.Popen(cmd, env=env, stdout=out,
                                      stderr=subprocess.STDOUT if out else None))

    rc = 0
    try:
        alive = set(range(nproc))
        while alive:
            for i in list(alive):
                r = procs[i].poll()
                if r is None:
                    continue
                alive.discard(i)
                if r != 0:
                    rc = r
                    # one trainer died: kill the rest (reference
                    # terminate_procs — a hung collective never recovers)
                    for j in alive:
                        procs[j].send_signal(signal.SIGTERM)
                    for j in alive:
                        try:
                            procs[j].wait(timeout=10)
                        except subprocess.TimeoutExpired:
                            procs[j].kill()
                    alive.clear()
            time.sleep(0.2)
    finally:
        for f in log_files:
            f.close()
    return rc


if __name__ == "__main__":
    sys.exit(launch())
