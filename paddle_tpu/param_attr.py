"""ParamAttr (reference: python/paddle/fluid/param_attr.py)."""
from __future__ import annotations

from typing import Optional


class ParamAttr:
    def __init__(self, name: Optional[str] = None, initializer=None,
                 learning_rate: float = 1.0, regularizer=None,
                 trainable: bool = True, do_model_average: bool = True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average

    @staticmethod
    def _to_attr(arg) -> "ParamAttr":
        if arg is None:
            return ParamAttr()
        if isinstance(arg, (list, tuple)):
            return [ParamAttr._to_attr(a) for a in arg]
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, bool):
            return ParamAttr() if arg else False
        raise TypeError(f"cannot convert {arg!r} to ParamAttr")

    def _to_kwargs(self, with_initializer: bool = False) -> dict:
        kwargs = {
            "name": self.name,
            "optimize_attr": {"learning_rate": self.learning_rate},
            "regularizer": self.regularizer,
            "trainable": self.trainable,
            "do_model_average": self.do_model_average,
        }
        if with_initializer:
            kwargs["initializer"] = self.initializer
        return kwargs


WeightNormParamAttr = ParamAttr  # placeholder parity alias
