"""Python-side metrics (reference: python/paddle/fluid/metrics.py:58-695)."""
from __future__ import annotations

import numpy as np

__all__ = ["MetricBase", "Accuracy", "Precision", "Recall", "Auc",
           "CompositeMetric", "ChunkEvaluator", "EditDistance"]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError

    def get_config(self):
        return {"name": self._name}


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1.0):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("Accuracy: no updates yet")
        return self.value / self.weight


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def eval(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def eval(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(MetricBase):
    """Histogram AUC matching the auc op's binning."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        nt = self._num_thresholds
        self._stat_pos = np.zeros(nt + 1, np.int64)
        self._stat_neg = np.zeros(nt + 1, np.int64)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        p1 = preds[:, 1] if preds.ndim == 2 and preds.shape[1] == 2 \
            else preds.reshape(-1)
        bins = np.clip((p1 * self._num_thresholds).astype(np.int64), 0,
                       self._num_thresholds)
        pos_mask = labels.astype(bool)
        np.add.at(self._stat_pos, bins[pos_mask], 1)
        np.add.at(self._stat_neg, bins[~pos_mask], 1)

    def eval(self):
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tot_pos, tot_neg = tp[-1], fp[-1]
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr))


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class ChunkEvaluator(MetricBase):
    """F1 over chunk counts (reference metrics.py ChunkEvaluator)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer = 0
        self.num_label = 0
        self.num_correct = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer += int(np.asarray(num_infer_chunks).reshape(-1)[0])
        self.num_label += int(np.asarray(num_label_chunks).reshape(-1)[0])
        self.num_correct += int(np.asarray(num_correct_chunks).reshape(-1)[0])

    def eval(self):
        precision = self.num_correct / self.num_infer if self.num_infer else 0
        recall = self.num_correct / self.num_label if self.num_label else 0
        f1 = (2 * precision * recall / (precision + recall)
              if precision + recall else 0)
        return precision, recall, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances).reshape(-1)
        self.total_distance += float(distances.sum())
        self.seq_num += int(seq_num)
        self.instance_error += int((distances > 0).sum())

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("EditDistance: no updates yet")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class DetectionMAP(MetricBase):
    """Mean average precision for detection (reference metrics.py:695
    DetectionMAP; math follows the detection_map op's '11point'/'integral'
    modes). Host-side accumulation: update() takes per-image detections
    [[label, score, x0, y0, x1, y1], ...] and ground truths
    [[label, x0, y0, x1, y1], ...] (difficult GTs may append a 7th/6th
    flag column)."""

    def __init__(self, name=None, overlap_threshold=0.5,
                 evaluate_difficult=False, ap_version="11point",
                 class_num=None):
        super().__init__(name)
        self.overlap_threshold = overlap_threshold
        self.evaluate_difficult = evaluate_difficult
        if ap_version not in ("11point", "integral"):
            raise ValueError("ap_version must be '11point' or 'integral'")
        self.ap_version = ap_version
        self.reset()

    def reset(self):
        self._dets = []   # (img_id, label, score, box)
        self._gts = []    # (img_id, label, box, difficult)
        self._img = 0

    @staticmethod
    def _iou(a, b):
        ix0, iy0 = max(a[0], b[0]), max(a[1], b[1])
        ix1, iy1 = min(a[2], b[2]), min(a[3], b[3])
        inter = max(ix1 - ix0, 0) * max(iy1 - iy0, 0)
        ua = ((a[2] - a[0]) * (a[3] - a[1]) +
              (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    def update(self, detections, gts):
        img = self._img
        self._img += 1
        for d in np.asarray(detections, np.float64).reshape(-1, 6):
            if d[0] < 0:
                continue  # -1 padding rows from multiclass_nms
            self._dets.append((img, int(d[0]), float(d[1]), d[2:6]))
        for g in np.asarray(gts, np.float64):
            diff = bool(g[5]) if len(g) > 5 else False
            self._gts.append((img, int(g[0]), g[1:5], diff))

    def eval(self):
        labels = sorted({l for _, l, _, _ in self._gts})
        if not labels:
            raise ValueError("DetectionMAP: no ground truths")
        aps = []
        for cls in labels:
            gts = [(i, b, d) for i, l, b, d in self._gts if l == cls]
            n_pos = sum(1 for _, _, d in gts
                        if self.evaluate_difficult or not d)
            dets = sorted((d for d in self._dets if d[1] == cls),
                          key=lambda d: -d[2])
            matched = set()
            tp, fp = [], []
            for img, _, score, box in dets:
                cand = [(k, self._iou(box, b))
                        for k, (gi, b, _) in enumerate(gts) if gi == img]
                k_best, iou_best = max(cand, key=lambda kv: kv[1],
                                       default=(None, 0.0))
                if k_best is not None and iou_best >= self.overlap_threshold:
                    _, _, difficult = gts[k_best]
                    if difficult and not self.evaluate_difficult:
                        continue  # difficult GT: detection neither tp nor fp
                    if k_best in matched:
                        fp.append(1); tp.append(0)
                    else:
                        matched.add(k_best)
                        tp.append(1); fp.append(0)
                else:
                    fp.append(1); tp.append(0)
            if n_pos == 0:
                continue
            tp = np.cumsum(tp, dtype=np.float64)
            fp = np.cumsum(fp, dtype=np.float64)
            rec = tp / n_pos
            prec = tp / np.maximum(tp + fp, 1e-12)
            if self.ap_version == "11point":
                ap = 0.0
                for t in np.linspace(0, 1, 11):
                    p = prec[rec >= t].max() if (rec >= t).any() else 0.0
                    ap += p / 11.0
            else:  # integral / VOC2010-style
                mrec = np.concatenate([[0.0], rec, [1.0]])
                mpre = np.concatenate([[0.0], prec, [0.0]])
                for i in range(len(mpre) - 2, -1, -1):
                    mpre[i] = max(mpre[i], mpre[i + 1])
                idx = np.where(mrec[1:] != mrec[:-1])[0]
                ap = float(((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]).sum())
            aps.append(ap)
        return float(np.mean(aps)) if aps else 0.0


__all__.append("DetectionMAP")
