"""Python-side metrics (reference: python/paddle/fluid/metrics.py:58-695)."""
from __future__ import annotations

import numpy as np

__all__ = ["MetricBase", "Accuracy", "Precision", "Recall", "Auc",
           "CompositeMetric", "ChunkEvaluator", "EditDistance"]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError

    def get_config(self):
        return {"name": self._name}


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1.0):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("Accuracy: no updates yet")
        return self.value / self.weight


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def eval(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def eval(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(MetricBase):
    """Histogram AUC matching the auc op's binning."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        nt = self._num_thresholds
        self._stat_pos = np.zeros(nt + 1, np.int64)
        self._stat_neg = np.zeros(nt + 1, np.int64)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        p1 = preds[:, 1] if preds.ndim == 2 and preds.shape[1] == 2 \
            else preds.reshape(-1)
        bins = np.clip((p1 * self._num_thresholds).astype(np.int64), 0,
                       self._num_thresholds)
        pos_mask = labels.astype(bool)
        np.add.at(self._stat_pos, bins[pos_mask], 1)
        np.add.at(self._stat_neg, bins[~pos_mask], 1)

    def eval(self):
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tot_pos, tot_neg = tp[-1], fp[-1]
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr))


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class ChunkEvaluator(MetricBase):
    """F1 over chunk counts (reference metrics.py ChunkEvaluator)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer = 0
        self.num_label = 0
        self.num_correct = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer += int(np.asarray(num_infer_chunks).reshape(-1)[0])
        self.num_label += int(np.asarray(num_label_chunks).reshape(-1)[0])
        self.num_correct += int(np.asarray(num_correct_chunks).reshape(-1)[0])

    def eval(self):
        precision = self.num_correct / self.num_infer if self.num_infer else 0
        recall = self.num_correct / self.num_label if self.num_label else 0
        f1 = (2 * precision * recall / (precision + recall)
              if precision + recall else 0)
        return precision, recall, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances).reshape(-1)
        self.total_distance += float(distances.sum())
        self.seq_num += int(seq_num)
        self.instance_error += int((distances > 0).sum())

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("EditDistance: no updates yet")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)
