"""Decode-step flash attention over a paged/block KV cache (Pallas TPU).

The autoregressive-serving counterpart of ``flash_attention.py``: a short
*chunk* of query tokens per sequence (1 <= q_len <= 8) attends against that
sequence's KV cache. q_len == 1 is the classic decode step; q_len > 1 is
the chunked-prefill slice and the speculative-verify chunk (ISSUE 20),
where query row ``i`` is the token at cache position ``length - 1 + i`` and
may see exactly ``length + i`` keys (causal *within* the chunk, since the
chunk's own K rows are appended before the walk). The cache is *paged* —
logically ``[BH, S_max, D]`` where ``S_max = num_pages * page_size`` and
the kernel walks it one page (``block_k = page_size``) at a time with the
same online-softmax recurrence as the prefill kernel, masking key positions
``>= length + row`` per sequence and query row.
Pages past a sequence's length hold stale/garbage rows by design (they are
overwritten when the sequence reaches them); the length mask keeps them out
of the softmax, so cache capacity can be provisioned once and reused across
requests at different positions.

CODA (PAPERS.md, arXiv 2605.19269) motivates folding the decode-step
epilogue work into the fused kernels instead of separate ops:
:func:`flash_attention_decode` therefore also performs the KV APPEND — the
new token's K/V rows are written into the cache at ``position`` before the
attention walk, and the updated caches are returned alongside the output so
the program-IR level sees ONE op that reads and writes the cache at the
same index (which is what lets ``analysis.liveness.safe_donation_set``
prove the cache buffer donatable: its last read is not after its last
write).

Design notes
- q rides in ``[BH, 8, D]`` sublane tiles (Mosaic needs the second-to-last
  dim divisible by 8 for f32; a 1-row tile violates that — see
  ``flash_attention._rows8``). The 8 sublane rows ARE the chunk's query
  rows: rows ``q_len..7`` are padding (replicas of the last real row) whose
  output is discarded, so the q_len=1 decode step and the q_len<=8 chunk
  use one kernel with a per-row length mask ``k_pos < length + row``.
- per-sequence lengths arrive as scalar-prefetch values so the kernel's
  mask needs no extra VMEM traffic; ``lengths[bh // num_heads]`` maps the
  fused B*H grid axis back to its batch row.
- inference-only: no custom VJP (decode never differentiates).
- interpret=True runs the same kernel on CPU for tests/CI parity.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import NEG_INF, CompilerParams, _out_sds

__all__ = ["flash_attention_decode", "paged_kv_append",
           "paged_kv_append_rows", "decode_attention_reference"]


def paged_kv_append(cache, new, positions):
    """Write ``new`` rows into ``cache`` at per-sequence ``positions``.

    cache: [B, ..., S_max, D]; new: [B, ..., L, D]; positions: [B] int —
    the start row per sequence (the page-aligned case L == page_size is
    the prefill bulk write; L == 1 is the decode append). XLA lowers the
    per-sequence ``dynamic_update_slice`` in place when the cache buffer
    is donated — this is the KV-append path the decode op fuses with the
    attention walk. Out-of-range starts clamp (XLA semantics), so a
    retired sequence whose position saturates keeps overwriting the last
    row instead of corrupting a neighbour.
    """
    positions = positions.reshape(positions.shape[0]).astype(jnp.int32)

    def upd(c, n, p):
        start = (jnp.int32(0),) * (c.ndim - 2) + (p, jnp.int32(0))
        return jax.lax.dynamic_update_slice(c, n.astype(c.dtype), start)

    return jax.vmap(upd)(cache, new, positions)


def paged_kv_append_rows(cache, new, positions):
    """Chunked KV write with PER-ROW clamping: row ``i`` of ``new``
    ([B, ..., C, D]) lands at ``min(positions + i, S_max - 1)``. Unlike
    :func:`paged_kv_append` (one ``dynamic_update_slice`` of the whole
    block, whose out-of-range START shifts backwards over real rows), a
    chunk whose tail crosses the cache end collapses its overflow rows
    onto the LAST row — and the last row is never inside a live length
    mask (the serving layer caps ``prompt + max_new <= S_max`` and the
    final generated token is never appended), so overflow is unreadable
    garbage, not corruption."""
    S = cache.shape[-2]
    C = new.shape[-2]
    positions = positions.reshape(positions.shape[0]).astype(jnp.int32)
    for i in range(C):
        row_pos = jnp.minimum(positions + i, S - 1)
        cache = paged_kv_append(cache, new[..., i:i + 1, :], row_pos)
    return cache


def decode_attention_reference(q, k_cache, v_cache, lengths, scale):
    """Primitive oracle: masked softmax attention of a chunk of query rows
    per sequence against its cache. q: [BH, Sq, D]; caches: [BH, S, D];
    lengths: [BH] (already expanded per head) — the number of keys visible
    to query row 0; row ``i`` sees ``lengths + i`` keys (causal within the
    chunk, whose K rows were appended before the attention). Sq == 1 is
    the classic decode step. Matches the kernel semantics exactly; also
    the op's off-TPU lowering."""
    prec = "highest" if q.dtype == jnp.float32 else "default"
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k_cache.astype(jnp.float32), precision=prec) * scale
    k_pos = jnp.arange(k_cache.shape[1])[None, None, :]
    row = jnp.arange(q.shape[1])[None, :, None]
    s = jnp.where(k_pos < lengths[:, None, None] + row, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bqk,bkd->bqd", p, v_cache.astype(jnp.float32),
                   precision=prec)
    return o.astype(q.dtype)


def _decode_kernel(scale, num_heads, scal_ref, q_ref, k_ref, v_ref,
                   o_ref, m_scr, l_scr, acc):
    bh, ik = pl.program_id(0), pl.program_id(1)
    num_k = pl.num_programs(1)
    block_k = k_ref.shape[1]

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc[:] = jnp.zeros_like(acc)

    length = scal_ref[bh // num_heads]
    q = q_ref[0]                                    # [8, D] (chunk rows)
    k = k_ref[0]                                    # [block_k, D]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    k_pos = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    # per-row causal length: query row i (the token at cache position
    # length - 1 + i) sees length + i keys; padding rows past the real
    # chunk see more keys, but their output is sliced away by the caller
    row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    s = jnp.where(k_pos < length + row, s, NEG_INF)

    m_prev = m_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    alive = m_new > NEG_INF * 0.5
    m_safe = jnp.where(alive, m_new, 0.0)
    corr = jnp.exp(m_prev - m_safe)
    p = jnp.exp(s - m_safe)
    l_new = corr * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
    pv = jax.lax.dot_general(p.astype(v_ref.dtype), v_ref[0],
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    acc[:] = acc[:] * corr + pv
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == num_k - 1)
    def _finish():
        l = l_scr[:, :1]
        o_ref[0] = (acc[:] / jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


def flash_attention_decode(q, k_cache, v_cache, lengths, *,
                           scale=None, num_heads: int = 1,
                           page_size: int = 128,
                           interpret: bool = False):
    """One decode/verify chunk: q [BH, Sq, D] (1 <= Sq <= 8) against paged
    caches [BH, S_max, D].

    ``lengths`` is per-BATCH ([B] int, B = BH // num_heads): the number of
    valid key rows visible to query row 0; row ``i`` sees ``lengths + i``
    keys (causal within the chunk — the chunk's K rows are appended to the
    cache before the walk). Sq == 1 is the classic decode step; Sq > 1 is
    the chunked-prefill / speculative-verify shape riding the same 8-row
    sublane tile (rows past Sq are padding, sliced off the output).
    ``page_size`` is the kernel's k-block — the cache page granularity;
    ``S_max`` must divide into whole pages
    (``flash_attention.classify_shapes`` refuses otherwise). Returns
    o [BH, Sq, D]. Inference-only (no VJP).
    """
    BH, Sq, D = q.shape
    Sk = k_cache.shape[1]
    if not 1 <= Sq <= 8:
        raise ValueError(
            f"flash_attention_decode is the q_len<=8 chunk path (one "
            f"sublane tile), got q_len={Sq}; use flash_attention for "
            f"prefill/full-sequence shapes")
    bk = min(page_size, Sk)
    if Sk % bk:
        raise ValueError(
            f"decode cache length S_max={Sk} must divide into whole pages "
            f"of page_size={bk}")
    scale = float(scale if scale is not None else D ** -0.5)
    lengths = jnp.asarray(lengths).reshape(-1).astype(jnp.int32)
    if lengths.shape[0] * num_heads != BH:
        raise ValueError(
            f"lengths has {lengths.shape[0]} rows but q has BH={BH} with "
            f"num_heads={num_heads} (expected {BH // num_heads})")
    # pad the chunk to one full sublane tile: [BH, Sq, D] -> [BH, 8, D]
    # (replicas of the last real row; their output is sliced away)
    if Sq == 8:
        q8 = q
    else:
        q8 = jnp.concatenate(
            [q, jnp.broadcast_to(q[:, -1:, :], (BH, 8 - Sq, D))], axis=1)
    nk = Sk // bk
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(BH, nk),
        in_specs=[
            pl.BlockSpec((1, 8, D), lambda bh, ik, s: (bh, 0, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, ik, s: (bh, ik, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, ik, s: (bh, ik, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 8, D), lambda bh, ik, s: (bh, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((8, 128), jnp.float32),     # running max
            pltpu.VMEM((8, 128), jnp.float32),     # running denom
            pltpu.VMEM((8, D), jnp.float32),       # numerator acc
        ],
    )
    (o8,) = pl.pallas_call(
        functools.partial(_decode_kernel, scale, int(num_heads)),
        grid_spec=grid_spec,
        out_shape=[_out_sds((BH, 8, D), q.dtype, q, k_cache, v_cache)],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(lengths, q8, k_cache, v_cache)
    return o8[:, :Sq, :]
