"""Pallas TPU kernels — the reference's `operators/jit/` + `operators/fused/`
role (xbyak runtime codegen and hand-fused kernels) rebuilt as Mosaic
kernels. Everything here must also run under `interpret=True` on CPU (minus
PRNG-dependent paths) so numerics are testable without hardware."""
from .flash_attention import (flash_attention, flash_attention_with_lse,
                              supports_shapes)

__all__ = ["flash_attention", "flash_attention_with_lse", "supports_shapes"]
