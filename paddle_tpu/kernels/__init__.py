"""Pallas TPU kernels — the reference's `operators/jit/` + `operators/fused/`
role (xbyak runtime codegen and hand-fused kernels) rebuilt as Mosaic
kernels. Everything here must also run under `interpret=True` on CPU (minus
PRNG-dependent paths) so numerics are testable without hardware."""
from .flash_attention import (classify_shapes, flash_attention,
                              flash_attention_with_lse, supports_shapes)
from .decode_attention import (decode_attention_reference,
                               flash_attention_decode, paged_kv_append,
                               paged_kv_append_rows)
from .fused_gemm import (classify_gemm, fused_gemm, fused_gemm_reference,
                         supports_gemm)

__all__ = ["flash_attention", "flash_attention_with_lse", "supports_shapes",
           "classify_shapes", "flash_attention_decode", "paged_kv_append",
           "paged_kv_append_rows",
           "decode_attention_reference", "fused_gemm", "classify_gemm",
           "supports_gemm", "fused_gemm_reference"]
