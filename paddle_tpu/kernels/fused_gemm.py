"""GEMM-epilogue fusion as a Pallas TPU kernel family — the CODA rewrite.

The flash-attention kernel (kernels/flash_attention.py) fused softmax into
the attention matmuls because whole-graph XLA fusion cannot keep the [S, S]
score matrix out of HBM. This module applies the same move to the OTHER
matmul-shaped hot path: the ``mul``/``matmul`` → bias-add → activation →
residual-add → layer_norm chains every fc/FFN layer builds. XLA fuses the
elementwise tail *after* the matmul writes its result to HBM; the Pallas
kernel applies the whole epilogue on the f32 accumulator tile while it is
still in VMEM, so the fused chain costs one HBM round-trip instead of one
per epilogue op (CODA, PAPERS.md arXiv 2605.19269: transformer blocks as
GEMM-epilogue programs recover most of the lost MXU utilisation).

Design notes
- The GEMM view is strictly 2-D: ``[M, K] @ [K, N]`` (the ``mul`` op already
  reshapes to 2-D; the fusion pass only matches epilogues expressible in
  this view — a 1-D ``[N]`` bias, an ``[M, N]`` residual, row-wise
  layer_norm).
- Grid is ``(M/bm, N/bn, K/bk)`` with the k axis innermost ("arbitrary" —
  TPU grid steps run sequentially per core, so the f32 accumulator lives in
  VMEM scratch across k steps, flash-attention style). The epilogue runs on
  the final k step only.
- layer_norm needs the WHOLE output row to compute its row statistics, so
  it requires ``bn == N`` (one n-block). ``classify_gemm`` refuses loudly
  otherwise — callers fall back to the dense path, never a silent wrong
  tiling.
- ``interpret=True`` runs the identical kernel on CPU for parity tests.
- Accumulation is f32 with the epilogue applied in f32 before one final
  cast to the output dtype. This is *more* accurate than the unfused chain
  under bf16 (which round-trips through bf16 between ops), which is why the
  fusion pass's fidelity witness compares against a declared per-epilogue
  tolerance on the kernel route and exact bits on the dense route.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_gemm", "classify_gemm", "supports_gemm",
           "fused_gemm_reference", "DEFAULT_BLOCKS", "EPILOGUE_ACTIVATIONS"]

DEFAULT_BLOCKS = (128, 128, 128)          # (block_m, block_n, block_k)
EPILOGUE_ACTIVATIONS = ("none", "relu", "gelu")

# largest bm*N f32 row-tile the layer_norm epilogue may hold in VMEM
# (one accumulator tile; v5e VMEM is 128 MiB but Mosaic wants headroom)
_LN_MAX_ROW_BYTES = 4 << 20


@dataclasses.dataclass(frozen=True)
class _Cfg:
    """Static kernel configuration (hashable)."""

    block_m: int
    block_n: int
    block_k: int
    has_bias: bool
    activation: str            # 'none' | 'relu' | 'gelu'
    gelu_approximate: bool
    has_residual: bool
    layer_norm: bool
    ln_eps: float
    has_ln_scale: bool
    has_ln_bias: bool
    interpret: bool
    precision: str             # 'highest' for f32 inputs, 'default' for bf16


def classify_gemm(m: int, n: int, k: int, *, layer_norm: bool = False,
                  block_m: int = 128, block_n: int = 128,
                  block_k: int = 128) -> Tuple[str, str]:
    """Classify a fused-GEMM shape for the kernel layer.

    Returns ``(kind, reason)`` with ``kind`` one of ``'supported'`` /
    ``'unsupported'``; ``reason`` names exactly which constraint failed so
    callers can refuse loudly (``FLAGS_use_fused_gemm=always``) or fall
    back to the dense path with the why on record. Constraints are the
    real Mosaic tiling rules: whole blocks in every dim, f32 tile geometry
    (sublanes % 8, lanes % 128), and for layer_norm one n-block covering
    the full row (the row statistics need the whole row in VMEM).
    """
    bm, bn, bk = min(block_m, m), min(block_n, n), min(block_k, k)
    if layer_norm:
        bn = n
    bad = []
    if m % bm:
        bad.append(f"m={m} % block_m={bm}")
    if n % bn:
        bad.append(f"n={n} % block_n={bn}")
    if k % bk:
        bad.append(f"k={k} % block_k={bk}")
    if bad:
        return ("unsupported",
                f"GEMM dims must divide into whole kernel blocks: "
                f"{', '.join(bad)} != 0 (pad the operand or pick block "
                f"sizes that divide it)")
    if bm % 8:
        return ("unsupported",
                f"block_m={bm} is not a multiple of 8 (f32 sublane tile)")
    if bn % 128:
        return ("unsupported",
                f"block_n={bn} is not a multiple of 128 (lane tile)")
    if bk % 128:
        return ("unsupported",
                f"block_k={bk} is not a multiple of 128 (lane tile of the "
                f"X block / sublane-aligned K of the Y block)")
    if layer_norm and bm * n * 4 > _LN_MAX_ROW_BYTES:
        return ("unsupported",
                f"layer_norm epilogue needs the whole row in VMEM: "
                f"block_m={bm} x n={n} f32 is "
                f"{bm * n * 4 >> 20} MiB > {_LN_MAX_ROW_BYTES >> 20} MiB "
                f"(shrink block_m)")
    return ("supported",
            f"{m // bm} x {n // bn} x {k // bk} blocks of "
            f"({bm}, {bn}, {bk})" + (" with whole-row layer_norm"
                                     if layer_norm else ""))


def supports_gemm(m: int, n: int, k: int, *, layer_norm: bool = False,
                  block_m: int = 128, block_n: int = 128,
                  block_k: int = 128) -> bool:
    return classify_gemm(m, n, k, layer_norm=layer_norm, block_m=block_m,
                         block_n=block_n, block_k=block_k)[0] == "supported"


def _rows8(v):
    """[N] row vector -> [8, N] sublane-replicated (Mosaic block shapes
    need sublanes % 8; a 1-D operand cannot tile)."""
    return jnp.broadcast_to(v[None, :], (8, v.shape[0]))


def _apply_activation(acc, cfg: _Cfg):
    if cfg.activation == "relu":
        return jnp.maximum(acc, 0.0)
    if cfg.activation == "gelu":
        return jax.nn.gelu(acc, approximate=cfg.gelu_approximate)
    return acc


def _kernel(cfg: _Cfg, *refs):
    idx = 0
    x_ref = refs[idx]; idx += 1
    y_ref = refs[idx]; idx += 1
    b_ref = r_ref = s_ref = lb_ref = None
    if cfg.has_bias:
        b_ref = refs[idx]; idx += 1
    if cfg.has_residual:
        r_ref = refs[idx]; idx += 1
    if cfg.has_ln_scale:
        s_ref = refs[idx]; idx += 1
    if cfg.has_ln_bias:
        lb_ref = refs[idx]; idx += 1
    o_ref, acc = refs[idx], refs[idx + 1]

    kk = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(kk == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    acc[:] += jax.lax.dot_general(
        x_ref[...], y_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=cfg.precision)

    @pl.when(kk == num_k - 1)
    def _epilogue():
        a = acc[...]
        if cfg.has_bias:
            a = a + b_ref[0].astype(jnp.float32)[None, :]
        a = _apply_activation(a, cfg)
        if cfg.has_residual:
            a = a + r_ref[...].astype(jnp.float32)
        if cfg.layer_norm:
            # whole row in this tile by construction (bn == N)
            mean = jnp.mean(a, axis=1, keepdims=True)
            var = jnp.mean(jnp.square(a - mean), axis=1, keepdims=True)
            a = (a - mean) / jnp.sqrt(var + cfg.ln_eps)
            if cfg.has_ln_scale:
                a = a * s_ref[0].astype(jnp.float32)[None, :]
            if cfg.has_ln_bias:
                a = a + lb_ref[0].astype(jnp.float32)[None, :]
        o_ref[...] = a.astype(o_ref.dtype)


def fused_gemm(x, y, bias=None, residual=None, ln_scale=None, ln_bias=None,
               activation: str = "none", gelu_approximate: bool = False,
               layer_norm: bool = False, ln_eps: float = 1e-5,
               block_m: int = 128, block_n: int = 128, block_k: int = 128,
               out_dtype=None, interpret: bool = False):
    """``epilogue(x @ y)`` with the epilogue applied on the in-VMEM f32
    accumulator tile: optional bias-add (``bias`` [N]), activation
    (``relu``/``gelu``), residual-add (``residual`` [M, N]) and row-wise
    layer_norm (``ln_scale``/``ln_bias`` [N]), in that order — the order
    the fusion pass matched them in the Program IR.

    ``x`` [M, K], ``y`` [K, N]; raises ``ValueError`` with the
    ``classify_gemm`` reason on unsupported tilings (callers decide
    between loud refusal and the dense fallback *before* calling).
    """
    if x.ndim != 2 or y.ndim != 2 or x.shape[1] != y.shape[0]:
        raise ValueError(
            f"fused_gemm is strictly 2-D [M,K]@[K,N]: got x{x.shape} "
            f"y{y.shape}")
    if activation not in EPILOGUE_ACTIVATIONS:
        raise ValueError(f"unknown epilogue activation {activation!r} — "
                         f"one of {EPILOGUE_ACTIVATIONS}")
    m, k = x.shape
    n = y.shape[1]
    kind, reason = classify_gemm(m, n, k, layer_norm=layer_norm,
                                 block_m=block_m, block_n=block_n,
                                 block_k=block_k)
    if kind != "supported":
        raise ValueError(f"fused_gemm has no kernel tiling for "
                         f"(m={m}, n={n}, k={k}): {reason}")
    bm, bn, bk = min(block_m, m), (n if layer_norm else min(block_n, n)), \
        min(block_k, k)
    out_dtype = out_dtype or x.dtype
    cfg = _Cfg(block_m=bm, block_n=bn, block_k=bk,
               has_bias=bias is not None,
               activation=activation,
               gelu_approximate=bool(gelu_approximate),
               has_residual=residual is not None,
               layer_norm=bool(layer_norm), ln_eps=float(ln_eps),
               has_ln_scale=ln_scale is not None,
               has_ln_bias=ln_bias is not None,
               interpret=bool(interpret),
               precision=("highest" if x.dtype == jnp.float32 else "default"))

    in_specs = [
        pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
        pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
    ]
    args = [x, y]
    rowspec = pl.BlockSpec((8, bn), lambda i, j, kk: (0, j))
    if bias is not None:
        in_specs.append(rowspec)
        args.append(_rows8(bias))
    if residual is not None:
        in_specs.append(pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)))
        args.append(residual)
    if ln_scale is not None:
        in_specs.append(rowspec)
        args.append(_rows8(ln_scale))
    if ln_bias is not None:
        in_specs.append(rowspec)
        args.append(_rows8(ln_bias))

    # jax renamed TPUCompilerParams -> CompilerParams around 0.5 (see
    # flash_attention.py) — accept both
    CompilerParams = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    out = pl.pallas_call(
        functools.partial(_kernel, cfg),
        grid=(m // bm, n // bn, k // bk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=cfg.interpret,
    )(*args)
    return out


def fused_gemm_reference(x, y, bias=None, residual=None, ln_scale=None,
                         ln_bias=None, activation: str = "none",
                         gelu_approximate: bool = False,
                         layer_norm: bool = False, ln_eps: float = 1e-5,
                         out_dtype=None):
    """Dense oracle with the KERNEL's numerics (f32 accumulate + epilogue,
    one final cast): what the kernel must match in parity tests. The
    *op-level* dense fallback (ops/fused_gemm.py) instead replays the
    original unfused op rules so it is bit-exact against the unfused
    program — two different fidelity contracts, both tested."""
    acc = jax.lax.dot_general(
        x, y, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=("highest" if x.dtype == jnp.float32 else "default"))
    if bias is not None:
        acc = acc + bias.astype(jnp.float32)[None, :]
    if activation == "relu":
        acc = jnp.maximum(acc, 0.0)
    elif activation == "gelu":
        acc = jax.nn.gelu(acc, approximate=bool(gelu_approximate))
    if residual is not None:
        acc = acc + residual.astype(jnp.float32)
    if layer_norm:
        mean = jnp.mean(acc, axis=1, keepdims=True)
        var = jnp.mean(jnp.square(acc - mean), axis=1, keepdims=True)
        acc = (acc - mean) / jnp.sqrt(var + ln_eps)
        if ln_scale is not None:
            acc = acc * ln_scale.astype(jnp.float32)[None, :]
        if ln_bias is not None:
            acc = acc + ln_bias.astype(jnp.float32)[None, :]
    return acc.astype(out_dtype or x.dtype)
