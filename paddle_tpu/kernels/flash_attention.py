"""Flash attention as a Pallas TPU kernel — the `jit/` + `fused/` role.

This is the TPU-native analogue of the reference's runtime-codegen fused
kernels (reference: paddle/fluid/operators/jit/kernel_base.h xbyak JIT
framework; paddle/fluid/operators/fused/fused_embedding_fc_lstm_op.cc etc.):
the one place SURVEY §7 reserves hand-written kernels because whole-graph XLA
fusion cannot produce them. The kernel computes

    O = dropout(softmax(Q K^T * scale + bias + causal_mask)) V

blockwise with the online-softmax recurrence, never materialising the
[S, S] score matrix in HBM: scores live in VMEM one (block_q, block_k)
tile at a time, accumulators persist in VMEM scratch across the innermost
grid dimension (TPU grid steps execute sequentially per core, so scratch
carries state the way the reference's xbyak kernels carry registers).

Design notes
- Layout is [B*H, S, D] (head-major): one grid axis ranges over fused
  batch*heads, blocks tile the sequence. D (head_dim) rides the lane
  dimension; 64/128 both work (64 pads lanes — bert-base's 768/12).
- The backward is the standard two-kernel flash split: dQ with the q-block
  as the outer tile, dK/dV with the k-block outer, both recomputing
  P = exp(S - lse) from the saved log-sum-exp rather than storing probs.
- The function also RETURNS lse, and its VJP accepts a cotangent for it:
  d lse_i / d S_ij = P_ij, so the lse cotangent just joins the
  `(dP - delta)` term. This is what lets ring attention combine per-block
  kernel results across ICI steps and still differentiate end-to-end.
- Dropout uses the on-core PRNG (`pltpu.prng_seed` / `prng_random_bits`),
  reseeded per (bh, q-block, k-block) so the backward kernels regenerate
  bit-identical keep masks. The PRNG has no interpret-mode lowering, so
  dropout>0 requires a real TPU; callers fall back to the primitive path
  elsewhere (ops/fused_attention.py).
- Masked-out rows (a fully-padded query) produce O=0 and lse=-inf; the
  backward guards exp(s - lse) with a finite sentinel so their grads are
  exactly zero.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "flash_attention_with_lse", "supports_shapes",
           "classify_shapes"]

NEG_INF = -1e30          # finite sentinel: (-inf) - (-inf) would NaN

# jax renamed TPUCompilerParams -> CompilerParams around 0.5; accept both so
# the kernels load on either side of the rename
CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams
# odd mixing constants for per-block reseeding, pre-wrapped to int32 range
# (jax int32 multiply wraps, which is exactly the mixing we want)
_SEED_MIX_BH = -1640532047   # int32(0x9E3779B1)
_SEED_MIX_Q = -2048144777    # int32(0x85EBCA77)
_SEED_MIX_K = -1028477379    # int32(0xC2B2AE3D)


@dataclasses.dataclass(frozen=True)
class _Cfg:
    """Static kernel configuration (hashable: custom_vjp nondiff arg)."""

    causal: bool
    scale: float
    dropout: float
    block_q: int
    block_k: int
    num_heads: int       # for bias [B, Sk] indexing from the fused B*H axis
    has_bias: bool
    interpret: bool
    # 'highest' for f32 inputs (true f32 multiplies), 'default' for bf16
    # (native MXU one-pass mode)
    precision: str


def classify_shapes(sq: int, sk: int, block_q: int = 128,
                    block_k: int = 128):
    """Classify an attention shape for the kernel layer.

    Returns ``(kind, reason)`` where ``kind`` is one of:

    * ``'prefill'`` — full-sequence shapes the blockwise kernel tiles
      (both sequence lengths divide into whole blocks);
    * ``'decode'`` — the q_len == 1 autoregressive step against a
      block/page-tiled KV cache (``decode_attention.flash_attention_decode``;
      ``block_k`` is the page size and the cache must hold whole pages);
    * ``'unsupported'`` — no kernel tiling fits; ``reason`` says exactly
      which divisibility failed so callers can refuse loudly instead of
      falling through to the dense path silently.
    """
    if sq == 1:
        bk = min(block_k, sk)
        if sk % bk == 0:
            return ("decode",
                    f"q_len=1 against a block-KV cache of {sk // bk} "
                    f"page(s) x {bk}")
        return ("unsupported",
                f"decode shape (q_len=1) but the KV cache length sk={sk} "
                f"does not divide into whole pages of page_size={bk}; pad "
                f"the cache capacity to a multiple of the page size")
    bq, bk = min(block_q, sq), min(block_k, sk)
    bad = []
    if sq % bq:
        bad.append(f"sq={sq} % block_q={bq}")
    if sk % bk:
        bad.append(f"sk={sk} % block_k={bk}")
    if bad:
        return ("unsupported",
                f"sequence lengths must divide into whole kernel blocks: "
                f"{', '.join(bad)} != 0 (pad the sequence or pick block "
                f"sizes that divide it)")
    return ("prefill", f"{sq // bq} q-block(s) x {sk // bk} k-block(s)")


def supports_shapes(sq: int, sk: int, block_q: int = 128,
                    block_k: int = 128) -> bool:
    """Whether a kernel tiling (prefill or decode) covers these shapes.
    ``classify_shapes`` carries the which-and-why."""
    return classify_shapes(sq, sk, block_q, block_k)[0] != "unsupported"


def _out_sds(shape, dtype, *like):
    """ShapeDtypeStruct for pallas outputs; under shard_map (check_vma=True)
    outputs must declare which mesh axes they vary over — the union of the
    operands'."""
    vma = set()
    for t in like:
        try:
            v = getattr(jax.typeof(t), "vma", None)
        except Exception:
            v = None
        if v:
            vma |= set(v)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))
    return jax.ShapeDtypeStruct(shape, dtype)


def _rows8(x):
    """[N, S] row vector -> [N, 8, S], replicated over the sublane dim.
    Mosaic block shapes need their second-to-last dim divisible by 8 (f32);
    a (1, block) tile of a 2-D array violates that, a (1, 8, block) tile of
    the replicated form doesn't. XLA materialises the broadcast lazily."""
    return jnp.broadcast_to(x[:, None, :], (x.shape[0], 8, x.shape[1]))


def _dropout_keep(seed, bh, iq, ik, shape, rate):
    """Deterministic per-block keep mask from the on-core PRNG."""
    mix = (seed + bh * _SEED_MIX_BH + iq * _SEED_MIX_Q + ik * _SEED_MIX_K)
    pltpu.prng_seed(mix)
    # raw bits are int32; Mosaic has no uint32->f32 cast, so mask to the
    # low 23 bits (non-negative in int32) -> uniform [0, 1)
    bits = pltpu.prng_random_bits(shape) & 0x007FFFFF
    u = bits.astype(jnp.float32) * (1.0 / (1 << 23))
    return u >= rate


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _fwd_kernel(cfg: _Cfg, scal_ref, *refs):
    if cfg.has_bias:
        q_ref, k_ref, v_ref, b_ref, o_ref, lse_ref, m_scr, l_scr, acc = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc = refs
        b_ref = None
    bh, iq, ik = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc[:] = jnp.zeros_like(acc)

    q = q_ref[0]                                   # [bq, D]
    k = k_ref[0]                                   # [bk, D]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=cfg.precision)
    s = s * cfg.scale                              # [bq, bk] f32
    if cfg.has_bias:
        s = s + b_ref[0, 0].astype(jnp.float32)[None, :]
    if cfg.causal:
        q_pos = (scal_ref[0] + iq * cfg.block_q
                 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0))
        k_pos = (scal_ref[1] + ik * cfg.block_k
                 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1))
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)

    m_prev = m_scr[:, :1]                          # [bq, 1]
    m_cur = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alive = m_new > NEG_INF * 0.5
    m_safe = jnp.where(alive, m_new, 0.0)
    corr = jnp.exp(m_prev - m_safe)                # underflows to 0 if dead
    p = jnp.exp(s - m_safe)                        # masked s -> exp(-1e30)=0
    l_new = corr * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
    if cfg.dropout > 0.0:
        keep = _dropout_keep(scal_ref[2], bh, iq, ik, s.shape, cfg.dropout)
        p = jnp.where(keep, p / (1.0 - cfg.dropout), 0.0)
    pv = jax.lax.dot_general(p.astype(v_ref.dtype), v_ref[0],
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32,
                            precision=cfg.precision)
    acc[:] = acc[:] * corr + pv
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == num_k - 1)
    def _finish():
        l = l_scr[:, :1]
        o_ref[0] = (acc[:] / jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)
        lse_row = jnp.where(l[:, 0] > 0.0,
                            m_scr[:, 0] + jnp.log(l[:, 0]), -jnp.inf)
        # row vectors are stored sublane-replicated [8, block_q]: Mosaic
        # requires block sublanes divisible by 8 (see _rows8)
        lse_ref[0] = jnp.broadcast_to(lse_row[None, :], lse_ref.shape[1:])


def _fwd(cfg: _Cfg, q, k, v, bias, scalars):
    BH, Sq, D = q.shape
    Sk = k.shape[1]
    nq, nk = Sq // cfg.block_q, Sk // cfg.block_k
    in_specs = [
        pl.BlockSpec((1, cfg.block_q, D), lambda bh, iq, ik, s: (bh, iq, 0)),
        pl.BlockSpec((1, cfg.block_k, D), lambda bh, iq, ik, s: (bh, ik, 0)),
        pl.BlockSpec((1, cfg.block_k, D), lambda bh, iq, ik, s: (bh, ik, 0)),
    ]
    args = [q, k, v]
    if cfg.has_bias:
        H = cfg.num_heads
        in_specs.append(pl.BlockSpec((1, 8, cfg.block_k),
                                     lambda bh, iq, ik, s: (bh // H, 0, ik)))
        args.append(_rows8(bias))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(BH, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, cfg.block_q, D),
                         lambda bh, iq, ik, s: (bh, iq, 0)),
            pl.BlockSpec((1, 8, cfg.block_q),
                         lambda bh, iq, ik, s: (bh, 0, iq)),
        ],
        scratch_shapes=[
            pltpu.VMEM((cfg.block_q, 128), jnp.float32),   # running max
            pltpu.VMEM((cfg.block_q, 128), jnp.float32),   # running denom
            pltpu.VMEM((cfg.block_q, D), jnp.float32),     # numerator acc
        ],
    )
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, cfg),
        grid_spec=grid_spec,
        out_shape=[
            _out_sds((BH, Sq, D), q.dtype, q, k, v),
            _out_sds((BH, 8, Sq), jnp.float32, q, k, v),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=cfg.interpret,
    )(scalars, *args)
    return o, lse[:, 0, :]


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------

def _recompute_p(cfg, scal_ref, q, k, b_ref, lse, iq, ik):
    """P = exp(S - lse) for one tile, shared by both backward kernels."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=cfg.precision) * cfg.scale
    if cfg.has_bias:
        s = s + b_ref[0, 0].astype(jnp.float32)[None, :]
    if cfg.causal:
        q_pos = (scal_ref[0] + iq * cfg.block_q
                 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0))
        k_pos = (scal_ref[1] + ik * cfg.block_k
                 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1))
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    lse_safe = jnp.where(jnp.isfinite(lse), lse, -NEG_INF)  # dead rows: p=0
    return jnp.exp(s - lse_safe[:, None])


def _dq_kernel(cfg: _Cfg, scal_ref, *refs):
    if cfg.has_bias:
        (q_ref, k_ref, v_ref, b_ref, do_ref, lse_ref, dl_ref, dq_ref,
         dq_acc) = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dq_ref, dq_acc = refs
        b_ref = None
    bh, iq, ik = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    p = _recompute_p(cfg, scal_ref, q_ref[0], k_ref[0], b_ref,
                     lse_ref[0, 0], iq, ik)
    do = do_ref[0]
    dp = jax.lax.dot_general(do, v_ref[0], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32,
                            precision=cfg.precision)
    if cfg.dropout > 0.0:
        keep = _dropout_keep(scal_ref[2], bh, iq, ik, p.shape, cfg.dropout)
        dp = jnp.where(keep, dp / (1.0 - cfg.dropout), 0.0)
    ds = p * (dp - dl_ref[0, 0].astype(jnp.float32)[:, None])
    dq_acc[:] += cfg.scale * jax.lax.dot_general(
        ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
                            precision=cfg.precision)

    @pl.when(ik == num_k - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(cfg: _Cfg, scal_ref, *refs):
    if cfg.has_bias:
        (q_ref, k_ref, v_ref, b_ref, do_ref, lse_ref, dl_ref, dk_ref,
         dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dk_ref, dv_ref,
         dk_acc, dv_acc) = refs
        b_ref = None
    bh, ik, iq = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    num_q = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q = q_ref[0]
    p = _recompute_p(cfg, scal_ref, q, k_ref[0], b_ref, lse_ref[0, 0],
                     iq, ik)
    do = do_ref[0]
    dp = jax.lax.dot_general(do, v_ref[0], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32,
                            precision=cfg.precision)
    p_used = p
    if cfg.dropout > 0.0:
        keep = _dropout_keep(scal_ref[2], bh, iq, ik, p.shape, cfg.dropout)
        inv = 1.0 / (1.0 - cfg.dropout)
        p_used = jnp.where(keep, p * inv, 0.0)
        dp = jnp.where(keep, dp * inv, 0.0)
    # dV = P_dropped^T @ dO
    dv_acc[:] += jax.lax.dot_general(
        p_used.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
                            precision=cfg.precision)
    ds = p * (dp - dl_ref[0, 0].astype(jnp.float32)[:, None])
    dk_acc[:] += cfg.scale * jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
                            precision=cfg.precision)

    @pl.when(iq == num_q - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(cfg: _Cfg, q, k, v, bias, scalars, do, lse, delta):
    BH, Sq, D = q.shape
    Sk = k.shape[1]
    nq, nk = Sq // cfg.block_q, Sk // cfg.block_k
    qspec = pl.BlockSpec((1, cfg.block_q, D),
                         lambda bh, iq, ik, s: (bh, iq, 0))
    kspec = pl.BlockSpec((1, cfg.block_k, D),
                         lambda bh, iq, ik, s: (bh, ik, 0))
    rowspec = pl.BlockSpec((1, 8, cfg.block_q),
                           lambda bh, iq, ik, s: (bh, 0, iq))
    args = [q, k, v]
    common = [qspec, kspec, kspec]
    if cfg.has_bias:
        H = cfg.num_heads
        common.append(pl.BlockSpec((1, 8, cfg.block_k),
                                   lambda bh, iq, ik, s: (bh // H, 0, ik)))
        args.append(_rows8(bias))
    common += [qspec, rowspec, rowspec]            # do, lse, delta
    args += [do, _rows8(lse), _rows8(delta)]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, cfg),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(BH, nq, nk),
            in_specs=common,
            out_specs=[qspec],
            scratch_shapes=[pltpu.VMEM((cfg.block_q, D), jnp.float32)],
        ),
        out_shape=[_out_sds((BH, Sq, D), q.dtype, q, k, v, do)],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=cfg.interpret,
    )(scalars, *args)[0]

    # k-outer grid: swap the roles of the q/k grid axes in the index maps
    qspec2 = pl.BlockSpec((1, cfg.block_q, D),
                          lambda bh, ik, iq, s: (bh, iq, 0))
    kspec2 = pl.BlockSpec((1, cfg.block_k, D),
                          lambda bh, ik, iq, s: (bh, ik, 0))
    rowspec2 = pl.BlockSpec((1, 8, cfg.block_q),
                            lambda bh, ik, iq, s: (bh, 0, iq))
    common2 = [qspec2, kspec2, kspec2]
    if cfg.has_bias:
        H = cfg.num_heads
        common2.append(pl.BlockSpec((1, 8, cfg.block_k),
                                    lambda bh, ik, iq, s: (bh // H, 0, ik)))
    common2 += [qspec2, rowspec2, rowspec2]
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, cfg),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(BH, nk, nq),
            in_specs=common2,
            out_specs=[kspec2, kspec2],
            scratch_shapes=[pltpu.VMEM((cfg.block_k, D), jnp.float32),
                            pltpu.VMEM((cfg.block_k, D), jnp.float32)],
        ),
        out_shape=[_out_sds((BH, Sk, D), k.dtype, q, k, v, do),
                   _out_sds((BH, Sk, D), v.dtype, q, k, v, do)],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=cfg.interpret,
    )(scalars, *args)
    return dq, dk, dv


# --------------------------------------------------------------------------
# custom-vjp wrapper
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash(cfg: _Cfg, q, k, v, bias, scalars):
    return _fwd(cfg, q, k, v, bias, scalars)


def _flash_fwd_rule(cfg, q, k, v, bias, scalars):
    o, lse = _fwd(cfg, q, k, v, bias, scalars)
    return (o, lse), (q, k, v, bias, scalars, o, lse)


def _flash_bwd_rule(cfg, res, cts):
    q, k, v, bias, scalars, o, lse = res
    do, dlse = cts
    # delta_i = sum_d dO_id * O_id  = rowsum(P_dropped * dP); the lse
    # cotangent enters the same P-weighted term (d lse/dS = P), so it folds
    # in by subtraction.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = delta - dlse.astype(jnp.float32)
    dq, dk, dv = _bwd(cfg, q, k, v, bias, scalars, do, lse, delta)
    return dq, dk, dv, None, None


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention_with_lse(q, k, v, bias: Optional[jax.Array] = None,
                             causal: bool = False,
                             scale: Optional[float] = None,
                             dropout_rate: float = 0.0,
                             seed=0,
                             q_offset=0, k_offset=0,
                             num_heads: int = 1,
                             block_q: int = 128, block_k: int = 128,
                             interpret: bool = False):
    """Flash attention over [B*H, S, D] tensors; returns (O, lse).

    ``bias`` is an additive [B, Sk] key bias (the padding-mask encoding —
    models/bert.py builds (mask-1)*10000 exactly like this); ``num_heads``
    tells the kernel how the leading B*H axis factors so bias rows map to
    batches. ``q_offset``/``k_offset`` (may be traced scalars) shift the
    causal comparison to GLOBAL positions for ring attention. ``lse`` is the
    per-row log-sum-exp; its cotangent is honoured, so blockwise
    combinations that re-weight through lse differentiate correctly.
    """
    BH, Sq, D = q.shape
    Sk = k.shape[1]
    bq, bk = min(block_q, Sq), min(block_k, Sk)
    if Sq % bq or Sk % bk:
        raise ValueError(
            f"flash_attention needs seq lengths divisible by block sizes: "
            f"Sq={Sq} bq={bq} Sk={Sk} bk={bk}")
    if dropout_rate > 0.0 and interpret:
        raise NotImplementedError(
            "in-kernel dropout uses the TPU PRNG which has no interpret-"
            "mode lowering; use the primitive fallback path off-TPU")
    cfg = _Cfg(causal=bool(causal),
               scale=float(scale if scale is not None else D ** -0.5),
               dropout=float(dropout_rate),
               block_q=bq, block_k=bk,
               num_heads=int(num_heads), has_bias=bias is not None,
               interpret=bool(interpret),
               precision=("highest" if q.dtype == jnp.float32
                          else "default"))
    scalars = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                         jnp.asarray(k_offset, jnp.int32),
                         jnp.asarray(seed, jnp.int32)])
    return _flash(cfg, q, k, v,
                  bias if bias is None else bias.astype(jnp.float32),
                  scalars)


def flash_attention(q, k, v, bias: Optional[jax.Array] = None,
                    causal: bool = False, scale: Optional[float] = None,
                    dropout_rate: float = 0.0, seed=0,
                    num_heads: int = 1, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False):
    """Like :func:`flash_attention_with_lse` but returns only O."""
    o, _ = flash_attention_with_lse(
        q, k, v, bias=bias, causal=causal, scale=scale,
        dropout_rate=dropout_rate, seed=seed, num_heads=num_heads,
        block_q=block_q, block_k=block_k, interpret=interpret)
    return o
