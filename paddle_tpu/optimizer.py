"""Optimizer family (reference: python/paddle/fluid/optimizer.py:53 Optimizer
base, :634-2360 the 13 concrete optimizers).

Same architecture as the reference: ``minimize`` = ``append_backward`` +
``apply_gradients``; each optimizer appends per-param update OPS to the main
program and creates accumulator vars (persistable) initialised in the startup
program. Because the whole step compiles to one XLA executable, the
reference's fuse_optimizer_ops/coalesce_grad_tensor passes are unnecessary.
"""
from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

from . import unique_name
from .backward import append_backward
from .clip import append_gradient_clip_ops, error_clip_callback
from .framework import (Parameter, Program, Variable, default_main_program,
                        default_startup_program, program_guard)
from .initializer import Constant
from .layer_helper import LayerHelper
from .regularizer import append_regularization_ops

__all__ = [
    "SGD", "Momentum", "Adagrad", "Adam", "Adamax", "AdamW", "DecayedAdagrad",
    "Adadelta", "RMSProp", "Ftrl", "Lamb", "LarsMomentum",
    "SGDOptimizer", "MomentumOptimizer", "AdagradOptimizer", "AdamOptimizer",
    "AdamaxOptimizer", "DecayedAdagradOptimizer", "AdadeltaOptimizer",
    "RMSPropOptimizer", "FtrlOptimizer", "LambOptimizer",
    "LarsMomentumOptimizer", "ExponentialMovingAverage", "ModelAverage",
    "LookaheadOptimizer", "RecomputeOptimizer", "PipelineOptimizer",
    "GradientMergeOptimizer", "DGCMomentumOptimizer",
]


class Optimizer:
    def __init__(self, learning_rate, regularization=None, name=None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._name = name
        self._accumulators: Dict[str, Dict[str, Variable]] = {}
        self._eager_accumulators: Dict[int, dict] = {}  # dygraph-mode state
        self._learning_rate_var: Optional[Variable] = None
        self.type = "optimizer"

    # -- learning rate ---------------------------------------------------
    def _create_global_learning_rate(self):
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_var = self._learning_rate
            return
        if self._learning_rate_var is not None:
            return
        name = unique_name.generate("learning_rate")
        main_block = default_main_program().global_block
        self._learning_rate_var = main_block.create_var(
            name=name, shape=(1,), dtype="float32", persistable=True,
            stop_gradient=True)
        startup = default_startup_program().global_block
        startup.create_var(name=name, shape=(1,), dtype="float32",
                           persistable=True)
        startup.append_op("fill_constant", outputs={"Out": name},
                          attrs={"shape": [1], "dtype": "float32",
                                 "value": float(self._learning_rate)})

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        mult = (param.optimize_attr or {}).get("learning_rate", 1.0)
        if mult == 1.0:
            return self._learning_rate_var
        helper = LayerHelper("param_lr")
        out = helper.create_variable_for_type_inference("float32", True)
        helper.append_op("scale", inputs={"X": self._learning_rate_var},
                         outputs={"Out": out}, attrs={"scale": float(mult)})
        return out

    # -- accumulators ----------------------------------------------------
    def _add_accumulator(self, name: str, param: Parameter, dtype=None,
                         fill_value=0.0, shape=None):
        if name in self._accumulators and param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        shape = list(shape if shape is not None else param.shape)
        dtype = dtype or param.dtype
        var_name = unique_name.generate(f"{name}_{param.name}")
        main_block = default_main_program().global_block
        var = main_block.create_var(name=var_name, shape=tuple(shape),
                                    dtype=dtype, persistable=True,
                                    stop_gradient=True)
        # marks the var as shardable optimizer state for ZeRO-1
        # (BuildStrategy.ReduceStrategy.Reduce; ref build_strategy.h:58 kReduce)
        var.is_optimizer_state = True
        if (getattr(param, "is_distributed", False)
                and list(shape[:1]) == list(param.shape[:1])):
            # accumulators of a sharded embedding table shard with it
            var.is_distributed = True
        startup = default_startup_program().global_block
        startup.create_var(name=var_name, shape=tuple(shape), dtype=dtype,
                           persistable=True)
        startup.append_op("fill_constant", outputs={"Out": var_name},
                          attrs={"shape": shape, "dtype": dtype,
                                 "value": float(fill_value)})
        self._accumulators.setdefault(name, {})[param.name] = var
        return var

    def _get_accumulator(self, name: str, param: Parameter):
        return self._accumulators[name][param.name]

    # -- hooks implemented by subclasses ---------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block, params_grads):
        pass

    # -- public API ------------------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return append_backward(loss, parameter_list, no_grad_set, callbacks)

    def apply_gradients(self, params_grads):
        # Guard on the program that owns the params, not whatever the global
        # default happens to be (reference optimizer.py apply_optimize wraps
        # in program_guard(loss.block.program, startup)).
        program = params_grads[0][0].block.program
        with program_guard(program), program._op_role_guard("optimize"):
            # current_block, not global: lets wrappers (AMP skip-update)
            # run the whole update inside a conditional sub-block
            block = program.current_block()
            params_grads = sorted(params_grads, key=lambda pg: pg[0].name)
            params_grads = append_gradient_clip_ops(params_grads)
            params_grads = append_regularization_ops(params_grads,
                                                     self.regularization)
            self._create_global_learning_rate()
            self._create_accumulators(block, [pg[0] for pg in params_grads])
            optimize_ops = []
            for pg in params_grads:
                optimize_ops.append(self._append_optimize_op(block, pg))
            self._finish_update(block, params_grads)
        return optimize_ops

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from .dygraph import base as dy

        if dy.in_dygraph_mode():
            return self._dygraph_minimize(loss, parameter_list)
        program = loss.block.program
        with program_guard(program, startup_program):
            params_grads = self.backward(loss, startup_program,
                                         parameter_list, no_grad_set)
            optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads

    # -- dygraph (eager) path --------------------------------------------
    def _dygraph_minimize(self, loss, parameter_list=None):
        """Apply the update rule eagerly on (param, param._grad) pairs
        (reference dygraph minimize: optimizer ops run immediately on the
        grad twins). Reuses the SAME registry update-rule lowerings as the
        compiled path. Call loss.backward() first."""
        import jax.numpy as jnp

        from .core import registry
        from .dygraph import base as dy
        from .lowering import LowerCtx

        if parameter_list is None:
            raise ValueError(
                "dygraph minimize needs parameter_list (e.g. "
                "model.parameters()); the tape does not own the params")
        if type(self)._eager_slots is Optimizer._eager_slots and \
                self.type not in ("sgd",):
            raise NotImplementedError(
                f"{type(self).__name__} has no eager (dygraph) update path "
                f"yet — supported: SGD, Momentum, Adam/AdamW/Lamb")
        params = [p for p in parameter_list if p._grad is not None]
        if not params:
            raise RuntimeError(
                "no gradients found — call loss.backward() before minimize")
        clipped = self._eager_clip_grads(params)
        lr = self._current_lr()
        ctx = LowerCtx()
        updated = []
        for p in params:
            # static-path order (reference _create_optimization_pass):
            # clip first, then fold regularization into the clipped grad
            base_grad = clipped[id(p)] if clipped is not None else p._grad
            grad = self._eager_regularized_grad(p, base_grad)
            slots = self._eager_slots(p)
            ins = {"Param": [p.value],
                   "Grad": [grad],
                   "LearningRate": [jnp.asarray([lr], p.value.dtype)]}
            for k, v in slots.items():
                ins[k] = [v]
            outs = registry.get_op_def(self.type).lower(
                ctx, ins, self._eager_attrs())
            p.set_value(outs["ParamOut"][0])
            self._eager_store(p, outs)
            updated.append(p)
        return updated, [(p, p._grad) for p in params]

    def _eager_clip_grads(self, params):
        """Apply set_gradient_clip eagerly (the static path's
        append_gradient_clip_ops, over jnp values): returns {id(p): grad}
        or None when no clip is installed."""
        import jax.numpy as jnp

        from .clip import (GradientClipByGlobalNorm, GradientClipByNorm,
                           GradientClipByValue, _clip_attr)

        clip = _clip_attr.get("__global__")
        if clip is None:
            return None
        grads = {id(p): p._grad for p in params}
        if isinstance(clip, GradientClipByValue):
            return {k: jnp.clip(g, clip.min, clip.max)
                    for k, g in grads.items()}
        if isinstance(clip, GradientClipByNorm):
            out = {}
            for k, g in grads.items():
                norm = jnp.sqrt(jnp.sum(jnp.square(g)))
                s = jnp.minimum(clip.clip_norm / jnp.maximum(norm, 1e-12),
                                1.0)
                out[k] = g * s
            return out
        if isinstance(clip, GradientClipByGlobalNorm):
            total = sum(jnp.sum(jnp.square(g)) for g in grads.values())
            gnorm = jnp.sqrt(total)
            scale = clip.clip_norm / jnp.maximum(gnorm, clip.clip_norm)
            return {k: g * scale for k, g in grads.items()}
        raise NotImplementedError(
            f"dygraph clip for {type(clip).__name__}")

    def _eager_regularized_grad(self, p, g=None):
        """L1/L2 weight decay folded into the grad, matching the static
        append_regularization_ops semantics."""
        import jax.numpy as jnp

        from .regularizer import L1DecayRegularizer, L2DecayRegularizer

        g = p._grad if g is None else g
        reg = self.regularization
        if reg is None:
            return g
        if isinstance(reg, L2DecayRegularizer):
            return g + reg._coeff * p.value
        if isinstance(reg, L1DecayRegularizer):
            return g + reg._coeff * jnp.sign(p.value)
        raise NotImplementedError(
            f"dygraph regularization for {type(reg).__name__}")

    def _current_lr(self) -> float:
        lr = self._learning_rate
        from .dygraph.learning_rate_scheduler import LearningRateDecay

        if isinstance(lr, LearningRateDecay):
            return lr()  # evaluates current rate, advances step_num
        if isinstance(lr, Variable):
            raise TypeError("dygraph mode needs a float learning rate or a "
                            "dygraph LearningRateDecay scheduler")
        return float(lr)

    def _eager_state(self, p) -> dict:
        # keyed per optimizer INSTANCE (like the static _accumulators) and
        # by the VarBase's stable uid — id(p) could be recycled after GC
        # and hand a new parameter a dead one's moments
        st = self._eager_accumulators.setdefault(p.uid, {})
        return st

    def _eager_slots(self, p) -> dict:
        """Extra input slots (accumulators) for this rule; default none."""
        return {}

    def _eager_store(self, p, outs) -> None:
        """Persist accumulator outputs after the update; default none."""

    def _eager_attrs(self) -> dict:
        return {}


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "sgd",
            inputs={"Param": p, "Grad": g,
                    "LearningRate": self._create_param_lr(param_and_grad)},
            outputs={"ParamOut": p})


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, use_nesterov=False,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "momentum"
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        vel = self._get_accumulator("velocity", p)
        return block.append_op(
            "momentum",
            inputs={"Param": p, "Grad": g, "Velocity": vel,
                    "LearningRate": self._create_param_lr(param_and_grad)},
            outputs={"ParamOut": p, "VelocityOut": vel},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov})

    def _eager_attrs(self):
        return {"mu": self._momentum, "use_nesterov": self._use_nesterov}

    def _eager_slots(self, p):
        import jax.numpy as jnp

        st = self._eager_state(p)
        if "velocity" not in st:
            st["velocity"] = jnp.zeros_like(p.value)
        return {"Velocity": st["velocity"]}

    def _eager_store(self, p, outs):
        self._eager_state(p)["velocity"] = outs["VelocityOut"][0]


class LarsMomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, lars_coeff=0.001,
                 lars_weight_decay=0.0005, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "lars_momentum"
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        vel = self._get_accumulator("velocity", p)
        return block.append_op(
            "lars_momentum",
            inputs={"Param": p, "Grad": g, "Velocity": vel,
                    "LearningRate": self._create_param_lr(param_and_grad)},
            outputs={"ParamOut": p, "VelocityOut": vel},
            attrs={"mu": self._momentum, "lars_coeff": self._lars_coeff,
                   "lars_weight_decay": self._lars_weight_decay})


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, regularization=None,
                 name=None, initial_accumulator_value=0.0):
        super().__init__(learning_rate, regularization, name)
        self.type = "adagrad"
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, fill_value=self._initial)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        return block.append_op(
            "adagrad",
            inputs={"Param": p, "Grad": g, "Moment": m,
                    "LearningRate": self._create_param_lr(param_and_grad)},
            outputs={"ParamOut": p, "MomentOut": m},
            attrs={"epsilon": self._epsilon})


class DecayedAdagradOptimizer(Optimizer):
    """reference optimizer.py DecayedAdagrad: moment tracks a DECAYED average
    of grad^2 (decayed_adagrad_op.h), not adagrad's monotone sum."""

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "decayed_adagrad"
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        return block.append_op(
            "decayed_adagrad",
            inputs={"Param": p, "Grad": g, "Moment": m,
                    "LearningRate": self._create_param_lr(param_and_grad)},
            outputs={"ParamOut": p, "MomentOut": m},
            attrs={"decay": self._decay, "epsilon": self._epsilon})


class AdamOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None,
                 lazy_mode=False):
        super().__init__(learning_rate, regularization, name)
        self.type = "adam"
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, shape=[1],
                                  fill_value=self._beta1)
            self._add_accumulator("beta2_pow_acc", p, shape=[1],
                                  fill_value=self._beta2)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        b2p = self._get_accumulator("beta2_pow_acc", p)
        return block.append_op(
            self.type if self.type in ("adam", "lamb") else "adam",
            inputs={"Param": p, "Grad": g,
                    "LearningRate": self._create_param_lr(param_and_grad),
                    "Moment1": m1, "Moment2": m2,
                    "Beta1Pow": b1p, "Beta2Pow": b2p},
            outputs={"ParamOut": p, "Moment1Out": m1, "Moment2Out": m2,
                     "Beta1PowOut": b1p, "Beta2PowOut": b2p},
            attrs=self._op_attrs())

    def _op_attrs(self):
        return {"beta1": self._beta1, "beta2": self._beta2,
                "epsilon": self._epsilon}

    def _eager_attrs(self):
        return self._op_attrs()

    def _eager_slots(self, p):
        import jax.numpy as jnp

        st = self._eager_state(p)
        if "moment1" not in st:
            st["moment1"] = jnp.zeros_like(p.value)
            st["moment2"] = jnp.zeros_like(p.value)
            st["beta1_pow"] = jnp.asarray([self._beta1], p.value.dtype)
            st["beta2_pow"] = jnp.asarray([self._beta2], p.value.dtype)
        return {"Moment1": st["moment1"], "Moment2": st["moment2"],
                "Beta1Pow": st["beta1_pow"], "Beta2Pow": st["beta2_pow"]}

    def _eager_store(self, p, outs):
        st = self._eager_state(p)
        st["moment1"] = outs["Moment1Out"][0]
        st["moment2"] = outs["Moment2Out"][0]
        st["beta1_pow"] = outs["Beta1PowOut"][0]
        st["beta2_pow"] = outs["Beta2PowOut"][0]


class AdamWOptimizer(AdamOptimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, weight_decay=0.01, regularization=None,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, regularization,
                         name)
        self.type = "adamw"
        self._weight_decay = weight_decay

    def _op_attrs(self):
        a = super()._op_attrs()
        a["weight_decay"] = self._weight_decay
        return a

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        b2p = self._get_accumulator("beta2_pow_acc", p)
        return block.append_op(
            "adamw",
            inputs={"Param": p, "Grad": g,
                    "LearningRate": self._create_param_lr(param_and_grad),
                    "Moment1": m1, "Moment2": m2,
                    "Beta1Pow": b1p, "Beta2Pow": b2p},
            outputs={"ParamOut": p, "Moment1Out": m1, "Moment2Out": m2,
                     "Beta1PowOut": b1p, "Beta2PowOut": b2p},
            attrs=self._op_attrs())


class LambOptimizer(AdamOptimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, regularization=None,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, regularization,
                         name)
        self.type = "lamb"
        self._weight_decay = lamb_weight_decay

    def _op_attrs(self):
        a = super()._op_attrs()
        a["weight_decay"] = self._weight_decay
        return a


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "adamax"
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, shape=[1],
                                  fill_value=self._beta1)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "adamax",
            inputs={"Param": p, "Grad": g,
                    "LearningRate": self._create_param_lr(param_and_grad),
                    "Moment": self._get_accumulator("moment", p),
                    "InfNorm": self._get_accumulator("inf_norm", p),
                    "Beta1Pow": self._get_accumulator("beta1_pow_acc", p)},
            outputs={"ParamOut": p,
                     "MomentOut": self._get_accumulator("moment", p),
                     "InfNormOut": self._get_accumulator("inf_norm", p)},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})

    def _finish_update(self, block, params_grads):
        for p, _ in params_grads:
            b1p = self._get_accumulator("beta1_pow_acc", p)
            block.append_op("scale", inputs={"X": b1p},
                            outputs={"Out": b1p},
                            attrs={"scale": self._beta1})


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "adadelta"
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "adadelta",
            inputs={"Param": p, "Grad": g,
                    "AvgSquaredGrad": self._get_accumulator("avg_squared_grad", p),
                    "AvgSquaredUpdate": self._get_accumulator("avg_squared_update", p)},
            outputs={"ParamOut": p,
                     "AvgSquaredGradOut": self._get_accumulator("avg_squared_grad", p),
                     "AvgSquaredUpdateOut": self._get_accumulator("avg_squared_update", p)},
            attrs={"epsilon": self._epsilon, "rho": self._rho})


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "rmsprop"
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("mean_square", p)
            self._add_accumulator("mean_grad", p)
            self._add_accumulator("momentum", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "rmsprop",
            inputs={"Param": p, "Grad": g,
                    "MeanSquare": self._get_accumulator("mean_square", p),
                    "MeanGrad": self._get_accumulator("mean_grad", p),
                    "Moment": self._get_accumulator("momentum", p),
                    "LearningRate": self._create_param_lr(param_and_grad)},
            outputs={"ParamOut": p,
                     "MomentOut": self._get_accumulator("momentum", p),
                     "MeanSquareOut": self._get_accumulator("mean_square", p),
                     "MeanGradOut": self._get_accumulator("mean_grad", p)},
            attrs={"decay": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum, "centered": self._centered})


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "ftrl"
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "ftrl",
            inputs={"Param": p, "Grad": g,
                    "SquaredAccumulator": self._get_accumulator("squared", p),
                    "LinearAccumulator": self._get_accumulator("linear", p),
                    "LearningRate": self._create_param_lr(param_and_grad)},
            outputs={"ParamOut": p,
                     "SquaredAccumOut": self._get_accumulator("squared", p),
                     "LinearAccumOut": self._get_accumulator("linear", p)},
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power})


# ---------------------------------------------------------------------------
# Meta optimizers / averaging (reference optimizer.py:2361-3367)
# ---------------------------------------------------------------------------

class ExponentialMovingAverage:
    """EMA of params (reference optimizer.py:2551). Maintains shadow vars
    updated by ops appended to the main program; apply()/restore() are
    context managers swapping params <-> shadow in the scope."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._name = name or ""
        self._ema_vars: Dict[str, Variable] = {}
        self._params: List[Parameter] = []
        program = default_main_program()
        block = program.global_block
        for p in program.all_parameters():
            if not p.trainable:
                continue
            self._params.append(p)
            ema_name = self._name + p.name + ".ema"
            ema = block.create_var(name=ema_name, shape=p.shape,
                                   dtype=p.dtype, persistable=True,
                                   stop_gradient=True)
            startup = default_startup_program().global_block
            startup.create_var(name=ema_name, shape=p.shape, dtype=p.dtype,
                               persistable=True)
            startup.append_op("fill_constant", outputs={"Out": ema_name},
                              attrs={"shape": list(p.shape),
                                     "dtype": p.dtype, "value": 0.0})
            self._ema_vars[p.name] = ema
            # ema = decay*ema + (1-decay)*param
            tmp = block.create_var(
                name=unique_name.generate(ema_name + ".tmp"),
                shape=p.shape, dtype=p.dtype, stop_gradient=True)
            block.append_op("scale", inputs={"X": ema}, outputs={"Out": tmp},
                            attrs={"scale": decay})
            tmp2 = block.create_var(
                name=unique_name.generate(ema_name + ".tmp"),
                shape=p.shape, dtype=p.dtype, stop_gradient=True)
            block.append_op("scale", inputs={"X": p}, outputs={"Out": tmp2},
                            attrs={"scale": 1.0 - decay})
            block.append_op("sum", inputs={"X": [tmp, tmp2]},
                            outputs={"Out": ema})

    def update(self):
        pass  # updates are appended into the main program at construction

    @contextlib.contextmanager
    def apply(self, executor, need_restore=True):
        from .executor import global_scope

        scope = global_scope()
        # validate BEFORE mutating so a missing shadow var can't leave the
        # scope half-swapped with no restore
        for p in self._params:
            if scope.find_var(self._ema_vars[p.name].name) is None:
                raise RuntimeError(
                    f"EMA shadow var '{self._ema_vars[p.name].name}' is not "
                    f"in the scope — construct ExponentialMovingAverage "
                    f"before training and run the startup+main programs that "
                    f"contain its ops")
        saved = {}
        for p in self._params:
            saved[p.name] = scope.find_var(p.name)
            scope.set_var(p.name, scope.find_var(self._ema_vars[p.name].name))
        try:
            yield
        finally:
            if need_restore:
                for name, v in saved.items():
                    scope.set_var(name, v)

    def restore(self, executor):
        pass


class ModelAverage(Optimizer):
    """reference optimizer.py:2361 — TRUE windowed average of params via the
    average_accumulates op (reference average_accumulates_op.h), not EMA.

    Must be constructed AFTER minimize() but BEFORE training runs: like the
    reference, construction appends accumulation ops to the main program, so
    the sums only exist if the accumulating program is what trains. apply()
    raises if the accumulators never ran."""

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000, regularization=None, name=None):
        super().__init__(0.0, regularization, name)
        self._avg_window_rate = average_window_rate
        self._min_window = min_average_window
        self._max_window = max_average_window
        self._params: List[Parameter] = []
        self._acc_names: Dict[str, Dict[str, str]] = {}
        program = default_main_program()
        block = program.global_block
        startup = default_startup_program().global_block
        for p in program.all_parameters():
            if not p.trainable or getattr(p, "do_model_average", None) is False:
                continue
            self._params.append(p)
            names = {}
            for slot, shape, dtype in (
                    ("sum_1", p.shape, p.dtype), ("sum_2", p.shape, p.dtype),
                    ("sum_3", p.shape, p.dtype),
                    ("num_accumulates", (1,), "int64"),
                    ("old_num_accumulates", (1,), "int64"),
                    ("num_updates", (1,), "int64")):
                vname = unique_name.generate(f"{p.name}.{slot}")
                names[slot] = vname
                block.create_var(name=vname, shape=tuple(shape), dtype=dtype,
                                 persistable=True, stop_gradient=True)
                startup.create_var(name=vname, shape=tuple(shape), dtype=dtype,
                                   persistable=True)
                startup.append_op("fill_constant", outputs={"Out": vname},
                                  attrs={"shape": list(shape), "dtype": dtype,
                                         "value": 0.0})
            self._acc_names[p.name] = names
            block.append_op(
                "average_accumulates",
                inputs={"Param": p.name, "InSum1": names["sum_1"],
                        "InSum2": names["sum_2"], "InSum3": names["sum_3"],
                        "InNumAccumulates": names["num_accumulates"],
                        "InOldNumAccumulates": names["old_num_accumulates"],
                        "InNumUpdates": names["num_updates"]},
                outputs={"OutSum1": names["sum_1"], "OutSum2": names["sum_2"],
                         "OutSum3": names["sum_3"],
                         "OutNumAccumulates": names["num_accumulates"],
                         "OutOldNumAccumulates": names["old_num_accumulates"],
                         "OutNumUpdates": names["num_updates"]},
                attrs={"average_window": average_window_rate,
                       "min_average_window": min_average_window,
                       "max_average_window": max_average_window})

    def minimize(self, loss, **kw):
        raise RuntimeError("ModelAverage wraps a trained program; call apply()")

    @contextlib.contextmanager
    def apply(self, executor, need_restore=True):
        """Swap params to (sum_1+sum_2+sum_3)/(num+old_num) in the scope."""
        import numpy as np

        from .executor import global_scope

        scope = global_scope()
        # compute every average BEFORE mutating the scope so a missing or
        # empty accumulator can't leave params half-swapped with no restore
        averaged = {}
        for p in self._params:
            names = self._acc_names[p.name]
            s1 = scope.find_var(names["sum_1"])
            if s1 is None:
                raise RuntimeError(
                    f"ModelAverage accumulator '{names['sum_1']}' is not in "
                    f"the scope — the accumulating program never ran. "
                    f"Construct ModelAverage before training (after "
                    f"optimizer.minimize) and train the SAME program.")
            s2 = scope.find_var(names["sum_2"])
            s3 = scope.find_var(names["sum_3"])
            n = int(np.asarray(scope.find_var(names["num_accumulates"]))[0])
            old_n = int(np.asarray(
                scope.find_var(names["old_num_accumulates"]))[0])
            total = n + old_n
            if total == 0:
                raise RuntimeError(
                    "ModelAverage.apply: zero accumulated steps — train "
                    "before applying the average")
            averaged[p.name] = (
                np.asarray(s1) + np.asarray(s2) + np.asarray(s3)) / total
        saved = {}
        for p in self._params:
            saved[p.name] = scope.find_var(p.name)
            scope.set_var(p.name, averaged[p.name].astype(
                np.asarray(saved[p.name]).dtype))
        try:
            yield
        finally:
            if need_restore:
                for name, v in saved.items():
                    scope.set_var(name, v)

    def restore(self, executor):
        pass


class LookaheadOptimizer:
    """reference optimizer.py:3367: slow/fast weights. slow_k sync period."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k

    def minimize(self, loss, startup_program=None):
        ops, pgs = self.inner_optimizer.minimize(
            loss, startup_program=startup_program)
        program = default_main_program()
        block = program.global_block
        startup = default_startup_program().global_block
        # step counter
        step_name = unique_name.generate("lookahead_step")
        block.create_var(name=step_name, shape=(1,), dtype="float32",
                         persistable=True, stop_gradient=True)
        startup.create_var(name=step_name, shape=(1,), dtype="float32",
                           persistable=True)
        startup.append_op("fill_constant", outputs={"Out": step_name},
                          attrs={"shape": [1], "dtype": "float32", "value": 0.0})
        block.append_op("increment", inputs={"X": step_name},
                        outputs={"Out": step_name}, attrs={"step": 1.0})
        for p, _ in pgs:
            slow_name = p.name + ".slow"
            block.create_var(name=slow_name, shape=p.shape, dtype=p.dtype,
                             persistable=True, stop_gradient=True)
            startup.create_var(name=slow_name, shape=p.shape, dtype=p.dtype,
                               persistable=True)
            # initialize slow = fast initial value: copy via assign after init
            startup.append_op("assign", inputs={"X": p.name},
                              outputs={"Out": slow_name})
            # every k steps: slow += alpha*(fast-slow); fast = slow.
            # branch-free gate: frac(step/k) == 0
            helper = LayerHelper("lookahead")
            inv = helper.create_variable_for_type_inference("float32", True)
            block.append_op("scale", inputs={"X": step_name},
                            outputs={"Out": inv}, attrs={"scale": 1.0 / self.k})
            flo = helper.create_variable_for_type_inference("float32", True)
            block.append_op("floor", inputs={"X": inv}, outputs={"Out": flo})
            frac = helper.create_variable_for_type_inference("float32", True)
            block.append_op("elementwise_sub", inputs={"X": inv, "Y": flo},
                            outputs={"Out": frac}, attrs={"axis": -1})
            # is_sync = 1 if frac == 0
            iszero = helper.create_variable_for_type_inference("bool", True)
            zero = helper.create_variable_for_type_inference("float32", True)
            block.append_op("fill_constant", outputs={"Out": zero},
                            attrs={"shape": [1], "dtype": "float32",
                                   "value": 0.0})
            block.append_op("equal", inputs={"X": frac, "Y": zero},
                            outputs={"Out": iszero})
            gate = helper.create_variable_for_type_inference("float32", True)
            block.append_op("cast", inputs={"X": iszero},
                            outputs={"Out": gate},
                            attrs={"in_dtype": "bool", "out_dtype": "float32"})
            # new_slow = slow + gate*alpha*(fast - slow)
            diff = helper.create_variable_for_type_inference(p.dtype, True)
            block.append_op("elementwise_sub", inputs={"X": p.name,
                                                       "Y": slow_name},
                            outputs={"Out": diff}, attrs={"axis": -1})
            sdiff = helper.create_variable_for_type_inference(p.dtype, True)
            block.append_op("scale", inputs={"X": diff}, outputs={"Out": sdiff},
                            attrs={"scale": self.alpha})
            gated = helper.create_variable_for_type_inference(p.dtype, True)
            block.append_op("elementwise_mul", inputs={"X": sdiff, "Y": gate},
                            outputs={"Out": gated}, attrs={"axis": 0})
            block.append_op("sum", inputs={"X": [slow_name, gated]},
                            outputs={"Out": slow_name})
            # new_fast = gate*slow + (1-gate)*fast
            #          = fast + gate*(slow - fast)
            diff2 = helper.create_variable_for_type_inference(p.dtype, True)
            block.append_op("elementwise_sub", inputs={"X": slow_name,
                                                       "Y": p.name},
                            outputs={"Out": diff2}, attrs={"axis": -1})
            gated2 = helper.create_variable_for_type_inference(p.dtype, True)
            block.append_op("elementwise_mul", inputs={"X": diff2, "Y": gate},
                            outputs={"Out": gated2}, attrs={"axis": 0})
            block.append_op("sum", inputs={"X": [p.name, gated2]},
                            outputs={"Out": p.name})
        return ops, pgs


class GradientMergeOptimizer:
    """Microbatched gradient accumulation (reference
    ir/multi_batch_merge_pass.cc: repeat fwd/bwd k times before one
    update): the forward+backward ops run under a lax.scan over
    num_microbatches slices of every feed, accumulating parameter
    gradients; the optimizer step then runs once on the average
    (executor.make_pipeline_step_fn). With a mean loss this is numerically
    the plain step on the full batch — it trades peak activation memory
    for steps."""

    def __init__(self, optimizer, num_microbatches=2, k_steps=None,
                 avg=True):
        self._optimizer = optimizer
        self._num_microbatches = int(k_steps or num_microbatches)
        self._avg = bool(avg)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        result = self._optimizer.minimize(loss, startup_program,
                                          parameter_list, no_grad_set)
        program = loss.block.program
        _, params_grads = result
        program._pipeline_microbatches = self._num_microbatches
        program._grad_merge_avg = self._avg  # False: SUM like ref avg=False
        program._pipeline_param_grads = [(p.name, g.name)
                                         for p, g in params_grads]
        program._bump_version()
        return result


class PipelineOptimizer(GradientMergeOptimizer):
    """Reference optimizer.py:2781 PipelineOptimizer: cut the program into
    device-placed sections run by SectionWorker threads passing scopes
    through queues (trainer.h:110 PipelineTrainer, device_worker.h:267).

    TPU-native split of that job into its two halves:

    - the MICROBATCH SCHEDULE (this class, via GradientMergeOptimizer):
      fwd/bwd scan over microbatch slices with gradient accumulation —
      numerically identical to pipelining, minus inter-stage concurrency;
    - real STAGE PLACEMENT over a 'pp' mesh axis: author the repeated
      stage with ``layers.PipelineRegion`` — its [num_stages, ...]-stacked
      params shard one slice per pp rank and the `pipeline` op runs the
      GPipe schedule with lax.ppermute'd activations
      (ops/pipeline_op.py, parallel/pipeline.py).

    ``cut_list`` names the section-boundary vars of the reference API. A
    program whose repeated section is a PipelineRegion already carries its
    stage structure; for a plain cut-list program the cuts are recorded on
    the program (``_pipeline_cut_names``) and the schedule is gradient
    accumulation — placement of heterogeneous hand-cut sections has no
    faithful single-program GSPMD encoding."""

    def __init__(self, optimizer, cut_list=None, num_microbatches=2,
                 start_cpu_core_id=0):
        super().__init__(optimizer, num_microbatches=num_microbatches)
        self._cut_list = cut_list

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        result = super().minimize(loss, startup_program, parameter_list,
                                  no_grad_set)
        program = loss.block.program
        if self._cut_list:
            names = []
            for cut in self._cut_list:
                for v in (cut if isinstance(cut, (list, tuple)) else [cut]):
                    names.append(v if isinstance(v, str) else v.name)
            missing = [n for n in names
                       if not program.global_block.has_var(n)]
            if missing:
                raise ValueError(
                    f"PipelineOptimizer cut_list names unknown vars: "
                    f"{missing}")
            program._pipeline_cut_names = names
        return result


class RecomputeOptimizer:
    """Gradient checkpointing (reference optimizer.py:3074 RecomputeOptimizer,
    backward.py:555 _append_backward_ops_with_checkpoints_).

    Before the backward is appended, forward ops up to each user checkpoint
    collapse into ``recompute_segment`` ops lowered under jax.checkpoint —
    activations between checkpoints are never saved across the fwd/bwd gap;
    the backward rebuilds them from the checkpoint tensors (see
    ops/recompute.py for the trade against the reference's op-duplication)."""

    def __init__(self, optimizer):
        self._optimizer = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        if not isinstance(checkpoints, (list, tuple)):
            raise TypeError("checkpoints must be a list of Variables/names")
        self._checkpoints = list(checkpoints)

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        from .ops.recompute import insert_recompute_segments

        if self._checkpoints:
            insert_recompute_segments(loss, self._checkpoints)
        return self._optimizer.backward(loss, startup_program,
                                        parameter_list, no_grad_set,
                                        callbacks)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        program = loss.block.program
        with program_guard(program, startup_program):
            params_grads = self.backward(loss, startup_program,
                                         parameter_list, no_grad_set)
            optimize_ops = self._optimizer.apply_gradients(params_grads)
        return optimize_ops, params_grads


class DGCMomentumOptimizer(Optimizer):
    """Deep Gradient Compression momentum — intentionally unsupported on
    TPU; this class IS the decision surface (the async-PS/GEO pattern).

    The reference (operators/optimizers/dgc_momentum_op / framework/details/
    sparse_all_reduce_op_handle.h:30) sparsifies each gradient to its top-k
    entries before all-reduce to save NETWORK bandwidth on commodity
    interconnects, trading exactness plus host-side encode/decode for fewer
    bytes on the wire. On a TPU pod the economics invert: dense all-reduce
    rides ICI at hundreds of GB/s with zero host involvement, while top-k
    selection + irregular gather/scatter are the expensive part — DGC is a
    pessimization, not an optimization, on this hardware. Momentum
    correction/clipping exist solely to patch DGC's convergence, so there
    is nothing worth keeping.

    Migration: plain ``Momentum`` (dense ICI all-reduce is cheap), or
    ``fleet.DistributedStrategy(use_local_sgd=True)`` when communication
    frequency — not volume — is the constraint (multi-host over DCN).
    """

    def __init__(self, learning_rate, momentum, rampup_begin_step,
                 rampup_step=1, sparsity=(0.999,), use_nesterov=False,
                 local_grad_clip_norm=None, num_trainers=None,
                 regularization=None, name=None):
        raise NotImplementedError(
            "DGCMomentumOptimizer is intentionally unsupported on TPU: "
            "top-k gradient sparsification saves network bytes at the cost "
            "of top-k + irregular scatter compute, which on ICI-connected "
            "chips is slower than the dense all-reduce it replaces. Use "
            "Momentum (dense collectives), or fleet.DistributedStrategy("
            "use_local_sgd=True) to cut communication FREQUENCY instead.")


# canonical short aliases (v2-style names)
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
LarsMomentum = LarsMomentumOptimizer
