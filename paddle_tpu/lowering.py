"""Block -> XLA lowering.

This module replaces the reference's entire execution stack — the per-op
interpreter loop (reference: paddle/fluid/framework/executor.cc:398
RunPreparedContext), kernel dispatch (operator.cc:861 RunImpl) and the op
kernel library — with ONE trace: a program block is interpreted over jax
tracers exactly once, producing a single XLA computation that the compiler
fuses, schedules and tiles for the MXU. This is the whole-block version of the
reference's ngraph subgraph bridge (paddle/fluid/operators/ngraph/ngraph_engine.cc).

Key pieces:
* ``LowerCtx`` — per-op context handed to lowering rules (PRNG key derivation,
  mesh info for collective ops).
* ``lower_block`` — env-threaded sequential interpretation of ops. Writes to a
  var name shadow earlier writes, which reproduces the reference executor's
  in-order scope semantics without SSA bookkeeping.
* generic ``*_grad`` lowering via ``jax.vjp`` — the registry's default grad
  maker (see core/registry.py) emits grad ops that recompute the forward rule
  under vjp; XLA CSE removes the duplicated forward subexpression.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .core import registry
from .core.types import np_dtype

EMPTY_VAR_NAME = "@EMPTY@"


class AmpPolicy:
    """Mixed-precision compute policy applied at lowering time.

    The reference rewrites the ProgramDesc, inserting cast ops around
    white-list ops and keeping fp16 twins of parameters
    (contrib/mixed_precision/decorator.py:27, fp16_lists.py). On TPU the
    idiomatic design is a COMPILE policy, not IR surgery: parameters stay
    fp32 in the scope (master weights for free), and the lowering casts a
    white-list op's float inputs to the compute dtype (bf16 -> MXU) right
    where the op is traced. XLA fuses the casts into neighbouring ops, and
    jax.vjp differentiates through them, so gradients arrive fp32 at the
    optimizer with zero extra machinery.
    """

    def __init__(self, white_list, black_list, compute_dtype="bfloat16"):
        self.white = frozenset(white_list)
        self.black = frozenset(black_list)
        self.compute_dtype = jnp.dtype(compute_dtype)

    def cast_ins(self, op_type: str, ins: Dict[str, List[Any]]):
        if op_type in self.white:
            src, dst = jnp.float32, self.compute_dtype
        elif op_type in self.black:
            src, dst = self.compute_dtype, jnp.float32
        else:
            return ins
        def cast(v):
            if v is not None and hasattr(v, "dtype") and v.dtype == src:
                return v.astype(dst)
            return v
        return {slot: [cast(v) for v in vals] for slot, vals in ins.items()}


def _amp_policy_of(ctx) -> Optional[AmpPolicy]:
    return getattr(ctx.program, "_amp_policy", None) if ctx.program else None


class LowerCtx:
    """Context passed to every op lowering rule."""

    def __init__(self, base_key=None, uid: int = 0, mesh=None, axis_env=None,
                 program=None, nan_checks=None, gemm_blocks=None,
                 num_taps=None):
        self.base_key = base_key
        self.uid = uid
        self.mesh = mesh          # jax.sharding.Mesh when lowering under shard_map
        self.axis_env = axis_env  # dict of mesh axis names usable in collectives
        self.program = program    # owning Program: sub-block lookup for while/cond
        # FLAGS_check_nan_inf: list collecting (label, finite-bool-scalar)
        # per float op output during the trace; the executor fetches the
        # bools and raises with the label on the first non-finite one
        self.nan_checks = nan_checks
        # FLAGS_numerics_witness: list collecting (var name, stats-vector
        # [absmax, min, max, nonfinite-count]) per float op output; the
        # executor stacks them into one (N, 4) fetch per step
        # (monitor.numwitness). Shares nan_checks' tracer-escape rule:
        # sub-block lowerings must null it.
        self.num_taps = num_taps
        # autotuner-chosen fused-GEMM block sizes for THIS compile, bound
        # at step-fn build time (the same values that sit in the compile
        # cache key) — a shared per-Program stamp read lazily at trace
        # time would let a concurrent compile with a different tuned
        # config leak its blocks into this executable
        self.gemm_blocks = gemm_blocks

    def rng(self):
        """PRNG key unique to this op instance; grad ops fold in the forward
        op's uid so recomputation (dropout masks etc.) is bit-identical."""
        if self.base_key is None:
            # shape-inference / eval_shape path: any key works, nothing runs
            return jax.random.key(0)
        return jax.random.fold_in(self.base_key, self.uid)

    def with_uid(self, uid: int) -> "LowerCtx":
        return LowerCtx(self.base_key, uid, self.mesh, self.axis_env,
                        self.program, self.nan_checks, self.gemm_blocks,
                        self.num_taps)


def _gather_inputs(op, env: Dict[str, Any]) -> Dict[str, List[Any]]:
    ins: Dict[str, List[Any]] = {}
    for slot, names in op.inputs.items():
        vals = []
        for n in names:
            if n == EMPTY_VAR_NAME:
                vals.append(None)
            elif n in env:
                vals.append(env[n])
            else:
                raise KeyError(
                    f"op {op.type}: input var '{n}' (slot {slot}) not found in "
                    f"environment — not fed, not initialized, not produced by an "
                    f"earlier op"
                )
        ins[slot] = vals
    return ins


def _op_site(op) -> str:
    site = op.attrs.get("op_callstack", "")
    return f" (created at {site})" if site else ""


def lower_op(op, env: Dict[str, Any], ctx: LowerCtx) -> None:
    """Execute one op's lowering rule against the environment, in place."""
    if op.type in ("feed", "fetch"):  # spliced by the executor, never lowered
        return
    try:
        _lower_op_inner(op, env, ctx)
    except _OpLoweringError:
        raise
    except Exception as e:
        # reference op_call_stack.cc: errors carry the op type and the user
        # line that appended the op
        raise _OpLoweringError(
            f"while lowering op '{op.type}'{_op_site(op)}: "
            f"{type(e).__name__}: {e}") from e
    if ctx.nan_checks is not None:
        for name in op.output_arg_names:
            v = env.get(name)
            if v is not None and hasattr(v, "dtype") and \
                    jnp.issubdtype(jnp.result_type(v), jnp.inexact):
                ctx.nan_checks.append(
                    (f"op '{op.type}' output '{name}'{_op_site(op)}",
                     jnp.isfinite(v).all()))
    if ctx.num_taps is not None:
        for name in op.output_arg_names:
            v = env.get(name)
            if v is not None and hasattr(v, "dtype") and \
                    jnp.issubdtype(jnp.result_type(v), jnp.inexact) and \
                    getattr(v, "size", 0):
                # [absmax, min, max, nonfinite-count] with nonfinite lanes
                # masked out of the range stats (numwitness module doc)
                vf = jnp.ravel(v).astype(jnp.float32)
                finite = jnp.isfinite(vf)
                ctx.num_taps.append((name, jnp.stack([
                    jnp.max(jnp.where(finite, jnp.abs(vf), 0.0)),
                    jnp.min(jnp.where(finite, vf, jnp.inf)),
                    jnp.max(jnp.where(finite, vf, -jnp.inf)),
                    jnp.sum(~finite).astype(jnp.float32)])))


class _OpLoweringError(RuntimeError):
    pass


def _lower_op_inner(op, env: Dict[str, Any], ctx: LowerCtx) -> None:
    if op.type.endswith("_grad") and not registry.has_op(op.type):
        _lower_generic_grad(op, env, ctx)
        return
    opdef = registry.get_op_def(op.type)
    op_ctx = ctx.with_uid(op.attrs.get("__uid__", 0))
    if opdef.raw:
        # control-flow ops interpret their sub-block themselves. Their
        # sub-block ops must NOT append nan checks: tracers created inside
        # a lax.while/cond body cannot escape to the top-level check list —
        # the control-flow op's own outputs are checked at this level.
        if op_ctx.program is None:
            op_ctx.program = op.block.program
        op_ctx.nan_checks = None
        op_ctx.num_taps = None  # same tracer-escape rule as nan_checks
        opdef.lower(op_ctx, op, env)
        return
    ins = _gather_inputs(op, env)
    amp = _amp_policy_of(ctx)
    if amp is not None:
        ins = amp.cast_ins(op.type, ins)
    outs = opdef.lower(op_ctx, ins, op.attrs)
    _write_outputs(op, outs, env)


def _write_outputs(op, outs: Dict[str, List[Any]], env: Dict[str, Any]) -> None:
    outs = outs or {}
    for slot, names in op.outputs.items():
        vals = outs.get(slot)
        if vals is None:
            continue
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        for n, v in zip(names, vals):
            if n != EMPTY_VAR_NAME and v is not None:
                env[n] = v


def lower_block(block, env: Dict[str, Any], ctx: LowerCtx) -> Dict[str, Any]:
    """Interpret all ops of a block over the env (jax tracers at jit time)."""
    for op in block.ops:
        lower_op(op, env, ctx)
    return env


# ---------------------------------------------------------------------------
# Generic gradient lowering (the default grad "kernel" for every op)
# ---------------------------------------------------------------------------

def _is_inexact(x) -> bool:
    return x is not None and jnp.issubdtype(jnp.result_type(x), jnp.inexact)


def _lower_generic_grad(op, env: Dict[str, Any], ctx: LowerCtx) -> None:
    """Lower a ``<fwd>_grad`` op emitted by the generic grad maker.

    Grad-op desc layout (see backward.py make_grad_op):
      inputs:  <slot>            forward inputs, per fwd schema
               __out__<slot>     forward outputs (unused here; kept for parity)
               <slot>@GRAD       cotangents of forward outputs (may be @EMPTY@)
      outputs: <slot>@GRAD       grads of forward inputs (aligned, @EMPTY@ holes)
      attrs:   __fwd_type__, __fwd_uid__ + all forward attrs
    """
    fwd_type = op.attrs["__fwd_type__"]
    fwd_def = registry.get_op_def(fwd_type)
    if fwd_def.grad_lower is not None:
        op_ctx = ctx.with_uid(op.attrs.get("__fwd_uid__", op.attrs.get("__uid__", 0)))
        if fwd_def.raw:
            if op_ctx.program is None:
                op_ctx.program = op.block.program
            # sub-block replays (while_grad/recurrent_grad/recompute) run
            # inside scan/while bodies — their inner ops must not append to
            # the top-level nan-check list (tracer escape)
            op_ctx.nan_checks = None
            op_ctx.num_taps = None
            fwd_def.grad_lower(op_ctx, op, env)
            return
        # NOTE: no AMP cast here — a custom grad rule owns its precision.
        # Casting the gathered inputs would also cast the incoming @GRAD
        # cotangents to bf16 and emit bf16 parameter gradients, breaking the
        # fp32-master-weight guarantee the vjp path preserves by casting
        # inside the vjp'd function only.
        ins = _gather_inputs(op, env)
        outs = fwd_def.grad_lower(op_ctx, ins, op.attrs)
        _write_outputs(op, outs, env)
        return

    fwd_attrs = {k: v for k, v in op.attrs.items() if not k.startswith("__")}
    fwd_attrs["__uid__"] = op.attrs.get("__fwd_uid__", 0)
    fwd_ctx = ctx.with_uid(op.attrs.get("__fwd_uid__", 0))

    # Reconstruct forward inputs from the grad op's inputs.
    fwd_in_slots = [s.name for s in fwd_def.inputs if s.name in op.inputs]
    fwd_ins: Dict[str, List[Any]] = {}
    for slot in fwd_in_slots:
        fwd_ins[slot] = [
            env[n] if n != EMPTY_VAR_NAME else None for n in op.inputs[slot]
        ]

    # Which (slot, idx) positions need a gradient? Those listed as real names
    # in the op's outputs AND holding inexact values.
    diff_pos: List[tuple] = []
    for slot in fwd_in_slots:
        out_names = op.outputs.get(slot + "@GRAD")
        if not out_names:
            continue
        for i, gname in enumerate(out_names):
            if gname != EMPTY_VAR_NAME and i < len(fwd_ins[slot]) and _is_inexact(
                fwd_ins[slot][i]
            ):
                diff_pos.append((slot, i))
    if not diff_pos:
        return

    amp = _amp_policy_of(ctx)

    def fwd_fn(diff_vals):
        ins2 = {s: list(vs) for s, vs in fwd_ins.items()}
        for (slot, i), v in zip(diff_pos, diff_vals):
            ins2[slot][i] = v
        if amp is not None:
            # cast INSIDE the vjp'd function: primals stay fp32, so the
            # returned gradients are fp32 toward the master weights
            ins2 = amp.cast_ins(fwd_type, ins2)
        outs = fwd_def.lower(fwd_ctx, ins2, fwd_attrs)
        # flatten only inexact outputs, in schema order, tracking identity
        flat, keys = [], []
        for ospec in fwd_def.outputs:
            vals = outs.get(ospec.name)
            if vals is None:
                continue
            if not isinstance(vals, (list, tuple)):
                vals = [vals]
            for i, v in enumerate(vals):
                if _is_inexact(v):
                    flat.append(v)
                    keys.append((ospec.name, i))
        fwd_fn._keys = keys
        return flat

    primals = [fwd_ins[slot][i] for slot, i in diff_pos]
    flat_outs, vjp_fn = jax.vjp(fwd_fn, primals)
    keys = fwd_fn._keys

    # Cotangents: out-grad inputs where present, zeros elsewhere.
    cts = []
    for (oslot, i), val in zip(keys, flat_outs):
        gnames = op.inputs.get(oslot + "@GRAD", [])
        g = None
        if i < len(gnames) and gnames[i] != EMPTY_VAR_NAME:
            g = env.get(gnames[i])
        if g is None:
            g = jnp.zeros_like(val)
        else:
            if g.dtype != val.dtype:
                g = g.astype(val.dtype)
            if g.shape != val.shape:
                g = g.reshape(val.shape)  # e.g. [1]-shaped loss grad vs scalar
        cts.append(g)

    (grads,) = vjp_fn(cts)

    # Write input grads.
    grad_map = dict(zip(diff_pos, grads))
    for slot in fwd_in_slots:
        out_names = op.outputs.get(slot + "@GRAD")
        if not out_names:
            continue
        for i, gname in enumerate(out_names):
            if gname == EMPTY_VAR_NAME:
                continue
            g = grad_map.get((slot, i))
            if g is not None:
                env[gname] = g


# ---------------------------------------------------------------------------
# Automatic shape inference via jax.eval_shape (build-time metadata)
# ---------------------------------------------------------------------------

# Two sentinel batch sizes for -1 dims: eval_shape runs twice and an output
# dim is dynamic (-1) iff it differs between the runs — no magic-number
# collisions with genuine static dims.
_BATCH_SENTINELS = (64, 96)


def auto_infer_shape(op, block) -> None:
    """Default infer_shape: run the lowering rule under jax.eval_shape with a
    sentinel batch size substituted for -1 dims, then map the sentinel back.
    Replaces the reference's per-op C++ InferShape (operator.cc:913) with a
    zero-maintenance derivation from the same code path that defines the op's
    runtime semantics. Ops where the mapping is ambiguous (reshape with
    explicit -1) register explicit infer rules."""
    opdef = registry.get_op_def(op.type)
    ctx = LowerCtx(base_key=None, uid=op.attrs.get("__uid__", 0))

    def build_ins(sentinel):
        ins: Dict[str, List[Any]] = {}
        for slot, names in op.inputs.items():
            vals = []
            for n in names:
                if n == EMPTY_VAR_NAME:
                    vals.append(None)
                    continue
                try:
                    v = block._var_recursive(n)
                except KeyError:
                    return None
                if v.shape is None:
                    return None
                shape = tuple(sentinel if d == -1 else d for d in v.shape)
                vals.append(jax.ShapeDtypeStruct(shape, np_dtype(v.dtype)))
            ins[slot] = vals
        return ins

    def f(ins_):
        return opdef.lower(ctx, ins_, op.attrs)

    results = []
    any_dynamic = False
    for sentinel in _BATCH_SENTINELS:
        ins = build_ins(sentinel)
        if ins is None:
            return
        any_dynamic = any_dynamic or any(
            isinstance(v, jax.ShapeDtypeStruct) and sentinel in v.shape
            for vs in ins.values() for v in vs if v is not None)
        try:
            results.append(jax.eval_shape(f, ins))
        except Exception:
            return  # dynamic/unsupported at build time; runtime trace checks
        if not any_dynamic:
            results.append(results[0])  # static inputs: one pass suffices
            break

    outs_a, outs_b = results
    from .core.types import canonical_dtype

    for slot, names in op.outputs.items():
        vals_a = outs_a.get(slot) if outs_a else None
        if vals_a is None:
            continue
        vals_b = outs_b.get(slot)
        if not isinstance(vals_a, (list, tuple)):
            vals_a, vals_b = [vals_a], [vals_b]
        for n, sa, sb in zip(names, vals_a, vals_b):
            if n == EMPTY_VAR_NAME or sa is None:
                continue
            if block.has_var(n):
                var = block.var(n)
                var.shape = tuple(
                    int(da) if da == db else -1
                    for da, db in zip(sa.shape, sb.shape)
                )
                if hasattr(sa, "dtype"):
                    var.dtype = canonical_dtype(np.dtype(sa.dtype))
