"""Reader combinators (reference: python/paddle/reader/decorator.py:37-361 —
cache/map_readers/shuffle/chain/compose/buffered/firstn/xmap_readers/
multiprocess_reader). A reader is a zero-arg callable returning an iterable."""
from __future__ import annotations

import itertools
import queue
import random
import threading
from typing import Callable, Iterable

__all__ = ["cache", "map_readers", "buffered", "compose", "chain", "shuffle",
           "firstn", "xmap_readers", "multiprocess_reader"]


def cache(reader):
    all_data = []
    filled = [False]

    def cached():
        if not filled[0]:
            all_data.extend(reader())
            filled[0] = True
        return iter(all_data)

    return cached


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for items in zip(*rs):
            yield func(*items)

    return reader


def shuffle(reader, buf_size, seed=None):
    """Buffered shuffle. With ``seed`` the order is DETERMINISTIC per
    epoch: epoch k of any run with the same seed shuffles identically
    (a fresh ``random.Random`` derived from ``(seed, epoch)``), and the
    returned reader carries ``state_dict()``/``set_state_dict()`` so the
    checkpoint data cursor (resilience.elastic — meta ``data_cursor``)
    can resume a preempted run on exactly the interrupted sample order.
    Without ``seed`` the legacy process-global ``random.shuffle`` is
    kept (non-resumable, order differs per run)."""
    def _buffered_shuffle(do_shuffle):
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                do_shuffle(buf)
                yield from buf
                buf = []
        if buf:
            do_shuffle(buf)
            yield from buf

    if seed is None:
        def shuffled():
            return _buffered_shuffle(random.shuffle)

        return shuffled

    state = {"seed": int(seed), "epoch": 0}

    def shuffled():
        # int derivation, not a tuple seed (tuple seeding is deprecated
        # and hash-salted — the whole point here is run-to-run stability)
        rng = random.Random((state["seed"] << 32) ^ state["epoch"])
        state["epoch"] += 1
        return _buffered_shuffle(rng.shuffle)

    # state["epoch"] is the index the NEXT reader() call plays; the
    # trainer's cursor realigns it to the epoch being (re-)entered so an
    # interrupted epoch re-shuffles identically on resume
    shuffled.state_dict = lambda: dict(state)
    shuffled.set_state_dict = lambda s: state.update(
        {"seed": int(s.get("seed", state["seed"])),
         "epoch": int(s.get("epoch", state["epoch"]))})
    return shuffled


def chain(*readers):
    def reader():
        return itertools.chain(*[r() for r in readers])

    return reader


def compose(*readers, check_alignment=True):
    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        iterator = zip(*rs) if not check_alignment else \
            itertools.zip_longest(*rs, fillvalue=_STOP)
        for outputs in iterator:
            if check_alignment and _STOP in outputs:
                raise RuntimeError("compose: readers have different lengths")
            yield sum((make_tuple(o) for o in outputs), ())

    return reader


_STOP = object()


def buffered(reader, size):
    """Background-thread prefetch into a bounded queue (the Python analogue
    of reference reader/buffered_reader.cc double-buffering)."""

    class _End:
        pass

    def buffered_reader():
        q: queue.Queue = queue.Queue(maxsize=size)
        err = []

        def fill():
            try:
                for d in reader():
                    q.put(d)
            except Exception as e:  # propagate to consumer
                err.append(e)
            finally:
                q.put(_End)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                if err:
                    raise err[0]
                return
            yield e

    return buffered_reader


def firstn(reader, n):
    def firstn_reader():
        return itertools.islice(reader(), n)

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader using threads (reference xmap_readers)."""

    end = object()

    def xreader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)

        def feed():
            for i, d in enumerate(reader()):
                in_q.put((i, d))
            for _ in range(process_num):
                in_q.put(end)

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, d = item
                out_q.put((i, mapper(d)))

        threading.Thread(target=feed, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()
        finished = 0
        pending = {}
        next_idx = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            if not order:
                yield item[1]
            else:
                pending[item[0]] = item[1]
                while next_idx in pending:
                    yield pending.pop(next_idx)
                    next_idx += 1
        if order:
            for k in sorted(pending):
                yield pending[k]

    return xreader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Thread-based fan-in (TPU hosts feed via threads; kept for API parity
    with the reference's multiprocess_reader)."""
    return chain(*readers) if len(readers) == 1 else _parallel_chain(readers, queue_size)


def _parallel_chain(readers, queue_size):
    def reader():
        q: queue.Queue = queue.Queue(queue_size)
        done = object()

        def run(r):
            for d in r():
                q.put(d)
            q.put(done)

        for r in readers:
            threading.Thread(target=run, args=(r,), daemon=True).start()
        finished = 0
        while finished < len(readers):
            item = q.get()
            if item is done:
                finished += 1
            else:
                yield item

    return reader
