"""Data pipeline: DataLoader / PyReader + reader combinators.

Reference: python/paddle/fluid/reader.py:73 (DataLoader.from_generator),
:298 (GeneratorLoader pushing LoDTensors into a C++ LoDTensorBlockingQueue
read by a create_py_reader op), :569 (PyReader), and the C++ double-buffer
prefetch in paddle/fluid/operators/reader/buffered_reader.cc.

TPU-native redesign: there is no reader op inside the graph. The loader is a
host-side pipeline — background thread runs the user generator, converts
batches to arrays and issues ``jax.device_put`` (async on TPU: the transfer
overlaps compute exactly like BufferedReader's side-stream memcpy), then a
bounded queue hands device-resident batches to the train loop, which passes
them to ``exe.run(feed=...)`` where they are used as-is (no extra copy).
``capacity`` plays the role of the blocking queue depth; >=2 gives double
buffering.
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .decorator import (buffered, cache, chain, compose, firstn,  # noqa: F401
                        map_readers, multiprocess_reader, shuffle,
                        xmap_readers)

__all__ = ["DataLoader", "PyReader", "batch", "cache", "map_readers",
           "buffered", "compose", "chain", "shuffle", "firstn",
           "xmap_readers", "multiprocess_reader"]


def batch(reader, batch_size, drop_last=False):
    """reference python/paddle/batch.py: sample reader -> sample-list
    reader."""

    def batched():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched


class _EndOfEpoch:
    pass


_EOE = _EndOfEpoch()


class DataLoader:
    """reference reader.py:73. Construct via ``from_generator``."""

    def __init__(self, feed_list=None, capacity=4, use_double_buffer=True,
                 iterable=True, return_list=False):
        if feed_list is None:
            raise ValueError("feed_list is required (list of fluid.data vars)")
        self._feed_names = [v if isinstance(v, str) else v.name
                            for v in feed_list]
        self._feed_vars = [v for v in feed_list if not isinstance(v, str)]
        self._capacity = max(2, int(capacity)) if use_double_buffer \
            else max(1, int(capacity))
        self._use_double_buffer = use_double_buffer
        self._iterable = iterable
        self._return_list = return_list
        self._places = None
        self._batch_reader: Optional[Callable] = None
        # deterministic data-order resume (state_dict/set_state_dict)
        self._batches_served = 0
        self._epochs_done = 0
        self._skip_batches = 0
        # non-iterable (start/reset/next) mode: the live epoch iterator
        self._iter = None

    # -- construction (reference DataLoader.from_generator) ---------------
    @staticmethod
    def from_generator(feed_list=None, capacity=4, use_double_buffer=True,
                       iterable=True, return_list=False):
        return DataLoader(feed_list, capacity, use_double_buffer, iterable,
                          return_list)

    # -- generator wiring (reference GeneratorLoader.set_*) ---------------
    def set_sample_generator(self, reader, batch_size, drop_last=True,
                             places=None):
        """reader yields ONE sample (tuple of arrays); loader batches."""

        return self.set_sample_list_generator(
            batch(reader, batch_size, drop_last=drop_last), places)

    def set_sample_list_generator(self, reader, places=None):
        """reader yields a LIST of samples per iteration (a batch)."""

        def batch_reader():
            for samples in reader():
                yield _stack_samples([s if isinstance(s, (list, tuple))
                                      else (s,) for s in samples])

        return self.set_batch_generator(batch_reader, places)

    def set_batch_generator(self, reader, places=None):
        """reader yields ready batches (tuple/list of batched arrays)."""
        self._batch_reader = reader
        self._places = places
        return self

    # -- device staging ----------------------------------------------------
    def _stage(self, batch, places):
        """Convert one batch to device arrays keyed by feed name. device_put
        is asynchronous: the host->device copy of batch N+1 overlaps the
        compute of batch N (BufferedReader's double-buffer, compiler-free).
        ``places`` is the worker thread's snapshot taken at ``__iter__``
        time — the prefetch thread never reads mutable loader state."""
        import jax

        if isinstance(batch, dict):
            items = [(n, batch[n]) for n in self._feed_names]
        else:
            vals = list(batch) if isinstance(batch, (list, tuple)) else [batch]
            if len(vals) != len(self._feed_names):
                raise ValueError(
                    f"generator yielded {len(vals)} arrays but feed_list has "
                    f"{len(self._feed_names)} ({self._feed_names})")
            items = list(zip(self._feed_names, vals))
        dev = None
        if places:
            place = places[0] if isinstance(places, (list, tuple)) \
                else places
            dev = place.jax_device() if hasattr(place, "jax_device") else place
        out = {}
        from ..data_feeder import coerce_feed_array

        for name, v in items:
            arr = np.asarray(v)
            for var in self._feed_vars:
                if var.name == name:
                    arr = coerce_feed_array(var, arr)
                    break
            out[name] = jax.device_put(arr, dev) if dev is not None \
                else jax.device_put(arr)
        return out

    # -- deterministic resume (SURVEY §5 failure/elastic) -----------------
    def state_dict(self) -> dict:
        """Data-order resume point: how many batches this epoch has served
        (plus completed epochs). Restoring via ``set_state_dict`` makes the
        NEXT epoch skip exactly that many batches, so training continues on
        the sample the crash interrupted — provided the underlying reader
        is deterministic (same files, same order, same shuffle seed; the
        reference gets the same guarantee from Dataset checkpointing its
        file cursor, data_set.h)."""
        return {"epoch": self._epochs_done, "batch": self._batches_served}

    def set_state_dict(self, state: dict):
        self._skip_batches = int(state.get("batch", 0))
        self._epochs_done = int(state.get("epoch", 0))

    # -- iteration ---------------------------------------------------------
    def __iter__(self):
        if self._batch_reader is None:
            raise RuntimeError("call set_sample_generator / "
                               "set_sample_list_generator / "
                               "set_batch_generator first")
        q: queue.Queue = queue.Queue(maxsize=self._capacity)
        stop = threading.Event()
        skip = self._skip_batches
        self._skip_batches = 0
        self._batches_served = skip
        # snapshot the mutable loader config BEFORE spawning the worker:
        # Thread.start() is the happens-before edge, and the prefetch
        # thread then only touches its own locals — a set_batch_generator
        # call racing a live iterator can no longer tear the worker's view
        batch_reader = self._batch_reader
        places = self._places

        def worker():
            try:
                for i, batch in enumerate(batch_reader()):
                    if stop.is_set():
                        return
                    if i < skip:
                        continue  # fast-forward: resume mid-epoch
                    q.put(self._stage(batch, places))
                q.put(_EOE)
            except BaseException as e:  # surface in the consumer
                q.put(e)

        t = threading.Thread(target=worker, daemon=True,
                             name="paddle_tpu-dataloader")
        t.start()
        try:
            while True:
                item = q.get()
                if item is _EOE:
                    self._epochs_done += 1
                    self._batches_served = 0
                    return
                if isinstance(item, BaseException):
                    raise item
                self._batches_served += 1
                if self._return_list:
                    yield [item[n] for n in self._feed_names]
                else:
                    yield item
        finally:
            stop.set()
            # drain so the worker unblocks and exits
            while t.is_alive():
                try:
                    q.get_nowait()
                except queue.Empty:
                    t.join(timeout=0.1)

    # -- non-iterable start/reset mode (reference PyReader) ---------------
    def start(self):
        self._iter = iter(self)

    def next(self):
        if self._iter is None:
            raise RuntimeError(
                "DataLoader.next() called without an active epoch — call "
                "start() first (after reset(), start() begins a new epoch)")
        return next(self._iter)

    def reset(self):
        it = self._iter
        self._iter = None
        if it is not None:
            it.close()  # unwind the generator's finally: stop the worker


class PyReader(DataLoader):
    """reference reader.py:569 — the older name for the same machinery."""

    def __init__(self, feed_list=None, capacity=4, use_double_buffer=True,
                 iterable=True, return_list=False):
        super().__init__(feed_list, capacity, use_double_buffer, iterable,
                         return_list)

    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        return self.set_sample_generator(sample_generator, batch_size,
                                         drop_last, places)

    def decorate_sample_list_generator(self, reader, places=None):
        return self.set_sample_list_generator(reader, places)

    def decorate_batch_generator(self, reader, places=None):
        return self.set_batch_generator(reader, places)


def _stack_samples(samples: List[tuple]) -> tuple:
    cols = list(zip(*samples))
    return tuple(np.stack([np.asarray(v) for v in col]) for col in cols)
