"""Communicator compat surface (reference communicator.py:26, wrapping the
C++ async-SGD Communicator, communicator.h:163).

The reference Communicator ran background send/recv threads merging
gradients for *async* pserver training. Sync training never needed it, and
async training is intentionally unsupported on TPU (see
transpiler.distribute_transpiler). Constructing one therefore raises with
the migration message — the importable class IS the decision surface a
2019 script hits, instead of an ImportError.
"""
from __future__ import annotations

__all__ = ["Communicator"]


class Communicator:
    def __init__(self, program, vars_info=None, trainers=None,
                 geo_sgd_need_push_nums=None):
        raise NotImplementedError(
            "Communicator drove ASYNC parameter-server training "
            "(communicator.h:163); async consistency has no TPU analogue. "
            "Sync collective training needs no communicator — gradients "
            "are exchanged by XLA collectives compiled into the step. See "
            "fluid.transpiler.DistributeTranspiler (sync mode) or "
            "fleet.distributed_optimizer.")

    def start(self):  # pragma: no cover - unreachable after __init__ raises
        pass

    def stop(self):  # pragma: no cover
        pass
