"""Initializers: emit init ops into the startup program.

Reference: python/paddle/fluid/initializer.py:76-862 (Constant/Uniform/Normal/
TruncatedNormal/Xavier/MSRA/Bilinear/NumpyArray). Same design: an initializer
appends ONE op to the var's (startup) block; the Executor runs the startup
program once and the resulting arrays become scope state.
"""
from __future__ import annotations

import math

import numpy as np

__all__ = ["Constant", "Uniform", "Normal", "TruncatedNormal", "Xavier",
           "MSRA", "Bilinear", "NumpyArrayInitializer", "ConstantInitializer",
           "UniformInitializer", "NormalInitializer", "XavierInitializer",
           "MSRAInitializer"]


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError

    @staticmethod
    def _fan_in_out(var):
        shape = var.shape
        if len(shape) < 2:
            return (shape[0], shape[0]) if shape else (1, 1)
        receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
        fan_in = shape[1] * receptive if len(shape) > 2 else shape[0]
        fan_out = shape[0] * receptive if len(shape) > 2 else shape[1]
        return fan_in, fan_out


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, var, block):
        return block.append_op(
            "fill_constant", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "value": float(self.value)})


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0, seed: int = 0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            "uniform_random", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "min": self.low, "max": self.high, "seed": self.seed})


class Normal(Initializer):
    def __init__(self, loc: float = 0.0, scale: float = 1.0, seed: int = 0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            "gaussian_random", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "mean": self.loc, "std": self.scale, "seed": self.seed})


class TruncatedNormal(Initializer):
    def __init__(self, loc: float = 0.0, scale: float = 1.0, seed: int = 0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            "truncated_gaussian_random", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "mean": self.loc, "std": self.scale, "seed": self.seed})


class Xavier(Initializer):
    """Glorot. uniform=True -> U(-sqrt(6/(fi+fo)), ...); else N(0, sqrt(2/(fi+fo)))."""

    def __init__(self, uniform: bool = True, fan_in=None, fan_out=None, seed: int = 0):
        self.uniform, self.fan_in, self.fan_out, self.seed = uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        fi, fo = self._fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            return Uniform(-limit, limit, self.seed)(var, block)
        std = math.sqrt(2.0 / (fi + fo))
        return Normal(0.0, std, self.seed)(var, block)


class MSRA(Initializer):
    """Kaiming He init."""

    def __init__(self, uniform: bool = True, fan_in=None, seed: int = 0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = self._fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            return Uniform(-limit, limit, self.seed)(var, block)
        std = math.sqrt(2.0 / fi)
        return Normal(0.0, std, self.seed)(var, block)


class Bilinear(Initializer):
    """For upsample deconv weights (reference initializer.py:668)."""

    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("Bilinear initializer requires 4-D weights")
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype=np.float32)
        size = shape[2] * shape[3]
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            w = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
            idx = np.unravel_index(i, shape)
            weight[idx] = w if idx[0] == idx[1] else weight[idx]
        weight_flat = weight.reshape(-1)
        return block.append_op(
            "assign_value", outputs={"Out": var},
            attrs={"shape": list(shape), "dtype": var.dtype,
                   "values": [float(v) for v in weight_flat]})


class NumpyArrayInitializer(Initializer):
    def __init__(self, value: np.ndarray):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        return block.append_op(
            "assign_value", outputs={"Out": var},
            attrs={"shape": list(self.value.shape), "dtype": var.dtype,
                   "values": [float(v) for v in self.value.astype(np.float64).flat]
                   if self.value.dtype.kind == "f"
                   else [int(v) for v in self.value.flat]})


# reference-compatible aliases
ConstantInitializer = Constant
UniformInitializer = Uniform
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
XavierInitializer = Xavier
MSRAInitializer = MSRA
BilinearInitializer = Bilinear

_global_weight_initializer = None
_global_bias_initializer = None
