"""paddle_tpu.tuning — the persistent, measurement-driven autotuner.

``tools/xla_sweep.py`` (the PR 5 one-shot sweep) proved the knobs move
throughput: ``FLAGS_xla_options`` reaches ``jax.jit(compiler_options=...)``
on every executor path, and the fused-GEMM kernel's block sizes change its
tiling. What it lacked was memory — every process re-paid the sweep. This
package is the TVM lesson ("Learning to Optimize Tensor Programs",
PAPERS.md arXiv 1805.08166) applied to those knobs: a durable cost
database keyed by **(program content fingerprint, shape bucket, backend)**
records measured step time / achieved TF/s per candidate, and the best
known entry feeds back into the executor compile path automatically.

Modes (``FLAGS_autotune``):

* ``off``     — no database access anywhere (default).
* ``use``     — ``Executor`` consults the DB at compile time: when
  ``FLAGS_xla_options`` / ``FLAGS_fused_gemm_blocks`` are not explicitly
  set, the best-known candidate supplies them (and joins the compile-cache
  key, so a DB update recompiles rather than silently reusing a stale
  executable). Zero trials ever run in this mode.
* ``measure`` — ``use`` plus :func:`measure_candidates` may run trials
  (the chained-differencing protocol) and record them.

Safety: the executor-path lookups NEVER raise — a torn/corrupt/alien DB
file degrades to "no best known" with one warning (the same
flight-recorder-safe posture as the monitor). Entries carry the framework
and jax versions; ``best()`` ignores trials measured by a different
version (staleness rule — docs/PERF_NOTES.md "Persistent autotuner").

Counters (docs/OBSERVABILITY.md): ``autotune_hits_total`` /
``autotune_misses_total`` (compile-path lookups), ``autotune_trials_total``
(measured candidates), ``autotune_best_per_step_seconds`` gauge per
(program, bucket).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..monitor.lockwitness import make_lock, make_rlock

__all__ = [
    "CostDatabase", "TunedConfig", "autotune_mode", "default_db_path",
    "get_database", "program_content_fingerprint", "shape_bucket",
    "lookup_best", "record_trial", "measure_candidates", "in_trial",
    "trial_guard", "chained_step_seconds",
    "CPU_OPTION_SETS", "TPU_OPTION_SETS", "GEMM_BLOCK_SETS",
]

logger = logging.getLogger("paddle_tpu.tuning")

_SCHEMA = 1

# measure_candidates sets this while a candidate trial is running: the
# executor compile path must NOT fill unset knobs from the DB during a
# trial, or the baseline {} candidate (and every candidate's unset
# gemm_blocks) would silently be measured under the best-known config and
# recorded as if it were its own — corrupting best() forever after.
# PROCESS-global (a nesting counter, not thread-local) on purpose: the
# candidate flags set_flags writes are process-global too, so any OTHER
# thread compiling mid-sweep (e.g. a serving dispatch sharing the
# executor) already sees the candidate's explicit flags — at least it
# must not additionally mix DB-filled knobs into them. Concurrent
# traffic during a sweep still compiles under transient candidate flags;
# see the measure_candidates docstring.
_trial_depth = 0
_trial_lock = make_lock("tuning._trial_lock")


def in_trial() -> bool:
    """True while measure_candidates is timing a candidate anywhere in
    this process (the executor skips DB knob-filling then)."""
    return _trial_depth > 0


class trial_guard:
    """Context manager marking this process as running a tuning trial:
    the executor compile path will not fill unset knobs from the cost
    database while it is active — a candidate must compile exactly as
    its flags specify (measure_candidates and tools/xla_sweep.py both
    time under it)."""

    def __enter__(self):
        global _trial_depth
        with _trial_lock:
            _trial_depth += 1
        return self

    def __exit__(self, *exc):
        global _trial_depth
        with _trial_lock:
            _trial_depth = max(0, _trial_depth - 1)
        return False

# candidate sets, moved here from tools/xla_sweep.py (the tool now imports
# them back): scheduling/fusion knobs that historically move dense-training
# throughput — swept and measured, never assumed.
TPU_OPTION_SETS: List[dict] = [
    {},
    {"xla_tpu_enable_latency_hiding_scheduler": True},
    {"xla_enable_async_all_gather": True,
     "xla_enable_async_collective_permute": True},
    {"xla_tpu_enable_latency_hiding_scheduler": True,
     "xla_enable_async_all_gather": True},
]
CPU_OPTION_SETS: List[dict] = [
    {},
    {"xla_cpu_enable_fast_min_max": True},
    {"xla_llvm_disable_expensive_passes": True},
    {"xla_cpu_enable_fast_min_max": True,
     "xla_llvm_disable_expensive_passes": True},
]
# fused-GEMM kernel tilings worth trying when the program carries fused ops
GEMM_BLOCK_SETS: List[Optional[Tuple[int, int, int]]] = [
    None,                      # the (128, 128, 128) default
    (256, 128, 128),
    (128, 256, 128),
    (128, 128, 256),
]


def autotune_mode() -> str:
    from ..flags import flag

    mode = str(flag("autotune")).strip().lower() or "off"
    if mode not in ("off", "use", "measure"):
        raise ValueError(f"FLAGS_autotune must be off|use|measure, "
                         f"got {mode!r}")
    return mode


def default_db_path() -> str:
    from ..flags import flag

    raw = str(flag("autotune_db")).strip()
    if raw:
        return raw
    return os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                        "autotune_db.json")


def _versions() -> Tuple[str, str]:
    import jax

    from .. import __version__

    return str(__version__), str(jax.__version__)


def shape_bucket(batch_rows: int) -> int:
    """Power-of-two batch bucket — the serving engine's padding rule, so a
    measurement at bucket 128 serves every batch the executor would pad
    there."""
    b = max(int(batch_rows), 1)
    p = 1
    while p < b:
        p <<= 1
    return p


_VOLATILE_ATTRS = ("__uid__", "op_callstack", "op_namescope")


def program_content_fingerprint(program) -> str:
    """Stable CONTENT hash of a program — unlike ``program._serial`` (a
    per-process counter) it survives process restarts, which is what makes
    the database durable. Hashes op types, slot wiring, non-volatile attrs
    and var metadata in deterministic order; memoized per (program,
    version)."""
    cached = getattr(program, "_content_fp", None)
    if cached is not None and cached[0] == getattr(program, "_version", 0):
        return cached[1]
    h = hashlib.sha256()
    for blk in program.blocks:
        for name in sorted(blk.vars):
            v = blk.vars[name]
            h.update(f"v|{blk.idx}|{name}|{v.shape}|{v.dtype}|"
                     f"{v.persistable}|{v.is_data}\n".encode())
        for op in blk.ops:
            attrs = sorted((k, repr(val)) for k, val in op.attrs.items()
                           if k not in _VOLATILE_ATTRS)
            h.update(f"o|{blk.idx}|{op.type}|"
                     f"{sorted((k, tuple(v)) for k, v in op.inputs.items())}|"
                     f"{sorted((k, tuple(v)) for k, v in op.outputs.items())}"
                     f"|{attrs}\n".encode())
    fp = h.hexdigest()[:16]
    try:
        program._content_fp = (getattr(program, "_version", 0), fp)
    except Exception:
        pass
    return fp


@dataclasses.dataclass(frozen=True)
class TunedConfig:
    """One candidate configuration (also the DB trial identity)."""

    xla_options: Tuple[Tuple[str, Any], ...] = ()
    gemm_blocks: Optional[Tuple[int, int, int]] = None

    @staticmethod
    def make(xla_options: Optional[dict] = None,
             gemm_blocks=None) -> "TunedConfig":
        return TunedConfig(
            xla_options=tuple(sorted((xla_options or {}).items())),
            gemm_blocks=tuple(int(b) for b in gemm_blocks)
            if gemm_blocks else None)

    def options_dict(self) -> dict:
        return dict(self.xla_options)

    def to_dict(self) -> dict:
        return {"xla_options": dict(self.xla_options),
                "gemm_blocks": list(self.gemm_blocks)
                if self.gemm_blocks else None}

    @staticmethod
    def from_dict(d: dict) -> "TunedConfig":
        return TunedConfig.make(d.get("xla_options") or {},
                                d.get("gemm_blocks"))


class CostDatabase:
    """The durable cost store: one JSON file, atomic rewrite (temp sibling
    + fsync + rename — the checkpoint manifest's publish discipline), a
    thread lock per instance plus a cross-process file lock + merge-on-save
    for concurrent recorders (two measure-mode processes sharing one DB
    union their trials instead of last-writer-wins dropping one side).
    Load failures are warnings, not errors: a corrupt database means
    "nothing is known", never a broken run."""

    def __init__(self, path: str):
        self.path = path
        self._lock = make_rlock("CostDatabase._lock")
        self._entries: Optional[Dict[str, dict]] = None

    # -- keys ------------------------------------------------------------
    @staticmethod
    def key(program_fp: str, bucket: int, backend: str) -> str:
        return f"{program_fp}|b{int(bucket)}|{backend}"

    # -- persistence -----------------------------------------------------
    def _read_file(self) -> Dict[str, dict]:
        """Entries as currently on disk — no memoization."""
        entries: Dict[str, dict] = {}
        try:
            if os.path.exists(self.path):
                with open(self.path, "r", encoding="utf-8") as f:
                    raw = json.load(f)
                if isinstance(raw, dict) and raw.get("schema") == _SCHEMA \
                        and isinstance(raw.get("entries"), dict):
                    entries = raw["entries"]
                else:
                    logger.warning(
                        "autotune DB %s has schema %r (want %d) — starting "
                        "empty", self.path, raw.get("schema")
                        if isinstance(raw, dict) else None, _SCHEMA)
        except Exception as e:
            logger.warning("autotune DB %s unreadable (%s: %s) — starting "
                           "empty", self.path, type(e).__name__, e)
        return entries

    def _load(self) -> Dict[str, dict]:
        if self._entries is None:
            self._entries = self._read_file()
        return self._entries

    def _merge_from_disk(self, entries: Dict[str, dict]) -> None:
        """Union trials another process recorded since we memoized into
        ``entries``. Same-candidate conflicts keep the in-memory trial
        (this process re-measured — record()'s latest-belief rule)."""
        for key, de in self._read_file().items():
            e = entries.get(key)
            if e is None:
                entries[key] = de
                continue
            have = {json.dumps(t.get("candidate"), sort_keys=True)
                    for t in e.get("trials", ())}
            for t in de.get("trials", ()):
                if json.dumps(t.get("candidate"),
                              sort_keys=True) not in have:
                    e.setdefault("trials", []).append(t)

    def save(self) -> None:
        with self._lock:
            entries = self._load()
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            # cross-process exclusive section: lock sibling, re-read,
            # merge, then the atomic rewrite — concurrent recorders
            # serialize here instead of last-replace-wins losing trials
            lk = None
            try:
                try:
                    import fcntl

                    lk = open(self.path + ".lock", "w")
                    fcntl.flock(lk, fcntl.LOCK_EX)
                except Exception:
                    lk = None   # no fcntl (or lockfile unwritable):
                    # merge-on-save still shrinks the lost-update window
                self._merge_from_disk(entries)
                tmp = self.path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump({"schema": _SCHEMA, "entries": entries}, f,
                              indent=1, sort_keys=True)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.path)
            finally:
                if lk is not None:
                    lk.close()

    # -- record / query --------------------------------------------------
    def record(self, program_fp: str, bucket: int, backend: str,
               config: TunedConfig, per_step_s: float,
               achieved_tflops: Optional[float] = None,
               save: bool = True) -> dict:
        fw, jx = _versions()
        trial = {"candidate": config.to_dict(),
                 "per_step_s": float(per_step_s),
                 "achieved_tflops": achieved_tflops,
                 "framework_version": fw, "jax_version": jx,
                 "recorded_at": time.time()}
        with self._lock:
            entries = self._load()
            key = self.key(program_fp, bucket, backend)
            e = entries.setdefault(key, {"program": program_fp,
                                         "bucket": int(bucket),
                                         "backend": backend, "trials": []})
            # one trial per candidate: remeasuring replaces (the DB stores
            # the latest belief, the artifact JSONs keep the history)
            cand = config.to_dict()
            e["trials"] = [t for t in e["trials"]
                           if t.get("candidate") != cand]
            e["trials"].append(trial)
            if save:
                self.save()
        return trial

    def best(self, program_fp: str, bucket: int, backend: str
             ) -> Optional[dict]:
        """The fastest valid trial, or None. Staleness rule: trials from a
        different framework or jax version are invisible — a compiler
        upgrade invalidates its own measurements."""
        fw, jx = _versions()
        with self._lock:
            e = self._load().get(self.key(program_fp, bucket, backend))
            if not e:
                return None
            valid = [t for t in e.get("trials", ())
                     if t.get("framework_version") == fw
                     and t.get("jax_version") == jx
                     and isinstance(t.get("per_step_s"), (int, float))]
            if not valid:
                return None
            return min(valid, key=lambda t: t["per_step_s"])

    def trial_count(self) -> int:
        with self._lock:
            return sum(len(e.get("trials", ()))
                       for e in self._load().values())

    def to_dict(self) -> dict:
        with self._lock:
            return {"schema": _SCHEMA, "path": self.path,
                    "entries": json.loads(json.dumps(self._load()))}


_db_cache: Dict[str, CostDatabase] = {}
_db_lock = make_lock("tuning._db_lock")


def get_database(path: Optional[str] = None) -> CostDatabase:
    p = path or default_db_path()
    with _db_lock:
        db = _db_cache.get(p)
        if db is None:
            db = _db_cache[p] = CostDatabase(p)
        return db


def reset_database_cache() -> None:
    """Test hook: drop memoized databases (a test pointing FLAGS_autotune_db
    at a fresh tmp file must not see another test's entries)."""
    with _db_lock:
        _db_cache.clear()


# ---------------------------------------------------------------------------
# executor compile-path feedback (the 'use' side)
# ---------------------------------------------------------------------------

_warned_lookup = False


def lookup_best(program, batch_rows: int) -> Optional[TunedConfig]:
    """Best-known config for (program, batch bucket, backend), or None.
    Called from the executor compile path — NEVER raises; counts
    ``autotune_hits_total`` / ``autotune_misses_total``."""
    global _warned_lookup
    try:
        if autotune_mode() == "off":
            return None
        import jax

        from .. import monitor

        fp = program_content_fingerprint(program)
        bucket = shape_bucket(batch_rows)
        backend = jax.default_backend()
        t = get_database().best(fp, bucket, backend)
        hit = t is not None
        if monitor.enabled():
            monitor.counter(
                "autotune_hits_total" if hit else "autotune_misses_total",
                "autotuner compile-path lookups that found (hits) / did "
                "not find (misses) a best-known config").inc()
        if not hit:
            return None
        if monitor.enabled():
            monitor.gauge(
                "autotune_best_per_step_seconds",
                "best-known measured step time fed to the compile path, "
                "by program fingerprint and shape bucket").labels(
                program=fp, bucket=str(bucket)).set(t["per_step_s"])
        return TunedConfig.from_dict(t["candidate"])
    except Exception as e:
        if not _warned_lookup:
            _warned_lookup = True
            logger.warning("autotune lookup disabled after error: %s: %s",
                           type(e).__name__, e)
        return None


def record_trial(program, batch_rows: int, config: TunedConfig,
                 per_step_s: float, achieved_tflops: Optional[float] = None,
                 db: Optional[CostDatabase] = None,
                 save: bool = True) -> dict:
    """Record one measured candidate (requires FLAGS_autotune=measure).
    ``save=False`` defers the durable write — callers recording a batch
    of trials (measure_candidates) save once at the end instead of
    paying a lock+merge+fsync cycle per candidate."""
    if autotune_mode() != "measure":
        raise RuntimeError(
            "recording autotune trials requires FLAGS_autotune=measure "
            f"(currently {autotune_mode()!r})")
    import jax

    from .. import monitor

    fp = program_content_fingerprint(program)
    bucket = shape_bucket(batch_rows)
    if monitor.enabled():
        monitor.counter("autotune_trials_total",
                        "measured autotuner candidates recorded into the "
                        "cost database").inc()
    return (db or get_database()).record(
        fp, bucket, jax.default_backend(), config, per_step_s,
        achieved_tflops, save=save)


# ---------------------------------------------------------------------------
# the measure loop (the 'measure' side; tools/xla_sweep.py + fusion_check
# drive this)
# ---------------------------------------------------------------------------

def chained_step_seconds(exe, program, feed, fetch_list, scope,
                         k_short: int = 2, k_long: int = 6,
                         repeats: int = 1) -> float:
    """Per-step seconds via the chained differencing protocol
    (docs/PERF_NOTES.md): (T(k_long) - T(k_short)) / (k_long - k_short),
    each T the min over ``repeats`` timed dispatches after one untimed
    warm-up (compile) dispatch, the final element read forcing the host
    sync. The ONE shared implementation — bench.py, tools/xla_sweep.py,
    tools/fusion_check.py and measure_candidates all time through here,
    so their numbers stay comparable (the r05 infer discontinuity in
    docs/PERF_NOTES.md is what silently-diverging copies of a measurement
    protocol cost). Floored at 1e-9: a noise-negative difference is
    meaningless, not a time machine."""
    import numpy as np

    def run_k(k: int) -> float:
        def once() -> float:
            t0 = time.perf_counter()
            out = exe.run_chained(program, feed=feed,
                                  fetch_list=fetch_list, steps=k,
                                  scope=scope, return_numpy=False)
            _ = float(np.asarray(out[0]).reshape(-1)[-1])
            return time.perf_counter() - t0
        once()
        return min(once() for _ in range(repeats))

    t_short, t_long = run_k(k_short), run_k(k_long)
    return max((t_long - t_short) / (k_long - k_short), 1e-9)


def default_candidates(include_gemm_blocks: bool = False
                       ) -> List[TunedConfig]:
    import jax

    sets = (TPU_OPTION_SETS if jax.default_backend() == "tpu"
            else CPU_OPTION_SETS)
    cands = [TunedConfig.make(o) for o in sets]
    if include_gemm_blocks:
        for blocks in GEMM_BLOCK_SETS:
            if blocks is not None:
                cands.append(TunedConfig.make({}, blocks))
    return cands


def measure_candidates(exe, program, feed, fetch_list, scope,
                       candidates: Optional[Sequence[TunedConfig]] = None,
                       k_short: int = 2, k_long: int = 6, repeats: int = 1,
                       batch_rows: Optional[int] = None,
                       db: Optional[CostDatabase] = None) -> dict:
    """Measure ``candidates`` on ``program`` with the honest
    chained-differencing protocol (docs/PERF_NOTES.md) and record every
    successful trial into the cost database. Returns the ranked report
    (the tool artifact). Requires ``FLAGS_autotune=measure``.

    NOT safe under concurrent traffic: each candidate is applied through
    process-global set_flags, so any other thread compiling mid-sweep
    (e.g. a serving dispatch sharing this executor) compiles under the
    candidate's transient flags. The trial guard is process-global so
    such a thread at least never mixes DB-filled knobs on top — but run
    sweeps offline, not under a live serving engine."""
    from .. import set_flags
    from ..flags import get_flags

    if autotune_mode() != "measure":
        raise RuntimeError("measure_candidates requires FLAGS_autotune="
                           f"measure (currently {autotune_mode()!r})")
    if batch_rows is None:
        from ..executor import _feed_batch_rows

        batch_rows = _feed_batch_rows(feed)
    candidates = list(candidates if candidates is not None
                      else default_candidates())

    prev = get_flags(["FLAGS_xla_options", "FLAGS_fused_gemm_blocks"])
    trials = []
    database = db or get_database()
    recorded = 0
    try:
        with trial_guard():
            for cand in candidates:
                set_flags({
                    "FLAGS_xla_options": json.dumps(cand.options_dict()),
                    "FLAGS_fused_gemm_blocks": ",".join(
                        str(b) for b in cand.gemm_blocks)
                    if cand.gemm_blocks else "",
                })
                label = json.dumps(cand.to_dict(), sort_keys=True)
                try:
                    per_step = chained_step_seconds(
                        exe, program, feed, fetch_list, scope,
                        k_short=k_short, k_long=k_long, repeats=repeats)
                    rec = record_trial(program, batch_rows, cand, per_step,
                                       db=database, save=False)
                    recorded += 1
                    trials.append({"candidate": cand.to_dict(),
                                   "status": "ok",
                                   "per_step_s": per_step,
                                   "recorded_at": rec["recorded_at"]})
                except Exception as e:
                    trials.append({"candidate": cand.to_dict(),
                                   "status": "error",
                                   "error": f"{type(e).__name__}: {e}"[:300]})
                    logger.warning("autotune candidate %s failed: %s",
                                   label, e)
    finally:
        set_flags(prev)
        # one durable write for the whole batch (in the finally so an
        # interrupted sweep keeps the trials measured before the crash)
        if recorded:
            try:
                database.save()
            except Exception as e:
                logger.warning("autotune DB save failed: %s: %s",
                               type(e).__name__, e)

    ok = sorted((t for t in trials if t["status"] == "ok"),
                key=lambda t: t["per_step_s"])
    for rank, t in enumerate(ok):
        t["rank"] = rank
    return {
        "program": program_content_fingerprint(program),
        "bucket": shape_bucket(batch_rows),
        "trials": trials,
        "best": ok[0] if ok else None,
    }
