"""paddle_tpu — a TPU-native deep-learning framework with the capabilities of
PaddlePaddle Fluid 1.5 (graph programs, registry autodiff, executors, fleet),
redesigned for XLA/TPU: whole program blocks compile to single XLA
executables; distribution is jax.sharding over device meshes.

The public surface mirrors ``paddle.fluid`` so reference user scripts port by
changing the import. See SURVEY.md at the repo root for the layer map.
"""
from . import ops  # registers all operator lowering rules (import order matters)
from . import initializer, layers, unique_name
from .backward import append_backward, calc_gradient, gradients
from .clip import (GradientClipByGlobalNorm, GradientClipByNorm,
                   GradientClipByValue, set_gradient_clip)
from .executor import (CPUPlace, CUDAPlace, Executor, Scope, TPUPlace,
                       global_scope, scope_guard)
from .framework import (Block, Operator, Parameter, Program, Variable,
                        default_main_program, default_startup_program,
                        in_dygraph_mode, name_scope, program_guard)
from .param_attr import ParamAttr, WeightNormParamAttr
from .parallel import BuildStrategy, CompiledProgram, ExecutionStrategy
from . import contrib
from . import dataset
from . import distributed
from . import dygraph
from . import incubate
from . import inference
from . import io
from . import reader
from .data_feeder import DataFeeder
from .dataset_feed import DatasetFactory
from .reader import DataLoader, PyReader, batch
from . import metrics
from . import optimizer
from . import transpiler
from .transpiler import (DistributeTranspiler, DistributeTranspilerConfig,
                         memory_optimize, release_memory)
from . import monitor
from . import profiler
from . import trace
from . import regularizer
from . import resilience
from . import serving
from . import analysis
from . import tuning
from . import aot_cache
from .core import registry as op_registry
from .flags import get_flags, set_flags
from .layers import learning_rate_scheduler  # registers fluid.layers.* decays

__version__ = "0.1.0"

# fluid-style: fluid.data is the recommended input declaration
data = layers.data
