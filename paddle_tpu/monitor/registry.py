"""Metrics registry: counters, gauges, histograms — thread-safe, zero-dep.

The measurement substrate of ``paddle_tpu.monitor`` (reference
platform/profiler.h gave Fluid per-event visibility; TVM's "Learning to
Optimize Tensor Programs" treats measurement as a first-class subsystem —
this is that subsystem for the executor's hot paths). Metric families carry
optional labels, Prometheus-style; exporters produce JSON (the CI artifact
format consumed by ``tools/metrics_report.py``) and the Prometheus text
exposition format (scrapeable by a serving sidecar).

Design constraints: no third-party deps, safe to update from any thread
(one registry lock — updates are dict/float ops, contention is irrelevant
next to a device dispatch), and cheap enough to stay on by default
(``FLAGS_monitor``).
"""
from __future__ import annotations

import json
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricFamily",
           "MetricsRegistry", "get_registry", "counter", "gauge",
           "histogram", "metric_value", "reset",
           "merge_histogram_snapshots", "snapshot_quantile"]

# default buckets sized for step/compile wall times in seconds
DEFAULT_TIME_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

# bounded per-bucket exemplar ring size (newest kept); exemplar storage
# is allocated lazily on the FIRST observe() that carries one, so a
# histogram that never sees an exemplar pays nothing
EXEMPLARS_PER_BUCKET = 4


class Counter:
    """Monotonic counter (one labeled child of a family)."""

    kind = "counter"

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up; use a gauge")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self):
        return self.value


class Gauge:
    """Point-in-time value (one labeled child of a family)."""

    kind = "gauge"

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self):
        return self.value


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics) plus min/max."""

    kind = "histogram"

    def __init__(self, lock: threading.RLock,
                 buckets: Sequence[float] = DEFAULT_TIME_BUCKETS):
        self._lock = lock
        self._bounds = tuple(sorted(float(b) for b in buckets))
        self._bucket_counts = [0] * (len(self._bounds) + 1)  # +1: +Inf
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        # per-bucket exemplar rings, None until an exemplar arrives
        self._exemplars: Optional[Dict[int, List[dict]]] = None

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        v = float(v)
        with self._lock:
            self._count += 1
            self._sum += v
            self._min = v if self._min is None else min(self._min, v)
            self._max = v if self._max is None else max(self._max, v)
            idx = len(self._bounds)
            for i, b in enumerate(self._bounds):
                if v <= b:
                    idx = i
                    break
            self._bucket_counts[idx] += 1
            if exemplar is not None:
                if self._exemplars is None:
                    self._exemplars = {}
                ring = self._exemplars.setdefault(idx, [])
                ring.append({"trace_id": str(exemplar), "value": v})
                if len(ring) > EXEMPLARS_PER_BUCKET:
                    del ring[0]

    def exemplars(self) -> Dict[str, List[dict]]:
        """Per-bucket exemplar rings keyed like the snapshot buckets
        (``repr(bound)`` / ``"+Inf"``); empty when none were recorded.
        Exported only through the JSON metrics form — the Prometheus
        text exporter stays plain 0.0.4."""
        with self._lock:
            if not self._exemplars:
                return {}
            out = {}
            for idx, ring in sorted(self._exemplars.items()):
                key = ("+Inf" if idx == len(self._bounds)
                       else repr(self._bounds[idx]))
                out[key] = [dict(e) for e in ring]
            return out

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> Optional[float]:
        """Estimate the ``q``-quantile (0 < q <= 1) from the cumulative
        buckets — Prometheus ``histogram_quantile`` semantics: linear
        interpolation inside the bucket the target rank lands in, with
        two honesty clamps the observed ``min``/``max`` make possible:
        the result never leaves ``[min, max]``, and ranks landing in the
        +Inf bucket report ``max`` instead of inventing an upper bound.
        None until something was observed."""
        if not 0.0 < q <= 1.0:
            raise ValueError(f"quantile must be in (0, 1], got {q}")
        with self._lock:
            if not self._count:
                return None
            target = q * self._count
            cum, lo = 0, 0.0
            for bound, c in zip(self._bounds, self._bucket_counts):
                if cum + c >= target:
                    est = lo + (bound - lo) * (target - cum) / c
                    return min(max(est, self._min), self._max)
                cum += c
                lo = bound
            return self._max

    def snapshot(self) -> dict:
        with self._lock:
            cum, cum_counts = 0, []
            for c in self._bucket_counts:
                cum += c
                cum_counts.append(cum)
            return {
                "count": self._count,
                "sum": self._sum,
                "min": self._min,
                "max": self._max,
                "avg": (self._sum / self._count) if self._count else None,
                # estimated quantiles ride along so every JSON artifact
                # (metrics_report, load_check) gets SLO percentiles for
                # free; the registry lock is an RLock, so the nested
                # quantile() calls see the same consistent state
                "p50": self.quantile(0.5),
                "p99": self.quantile(0.99),
                "buckets": {**{repr(b): c for b, c in
                               zip(self._bounds, cum_counts)},
                            "+Inf": self._count},
            }


class MetricFamily:
    """One metric name; children per label-set. The empty-label child is
    the family's own value, so ``registry.counter("x").inc()`` works with
    no labels() dance."""

    def __init__(self, name: str, cls, lock: threading.RLock, help: str = "",
                 **kwargs):
        self.name = name
        self.help = help
        self._cls = cls
        self._kwargs = kwargs
        self._lock = lock
        self._children: Dict[Tuple[Tuple[str, str], ...], object] = {}

    @property
    def kind(self) -> str:
        return self._cls.kind

    def labels(self, **kv):
        key = tuple(sorted((str(k), str(v)) for k, v in kv.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._cls(self._lock, **self._kwargs)
                self._children[key] = child
            return child

    # convenience: family-level ops act on the empty-label child
    def inc(self, n: float = 1.0):
        return self.labels().inc(n)

    def set(self, v: float):
        return self.labels().set(v)

    def dec(self, n: float = 1.0):
        return self.labels().dec(n)

    def observe(self, v: float, exemplar: Optional[str] = None):
        return self.labels().observe(v, exemplar=exemplar)

    @property
    def value(self):
        return self.labels().value

    def children(self) -> List[Tuple[Dict[str, str], object]]:
        with self._lock:
            return [(dict(k), c) for k, c in self._children.items()]


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.RLock()
        self._families: Dict[str, MetricFamily] = {}

    def _family(self, name: str, cls, help: str, **kwargs) -> MetricFamily:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = MetricFamily(name, cls, self._lock, help=help, **kwargs)
                self._families[name] = fam
            elif fam.kind != cls.kind:
                raise TypeError(
                    f"metric '{name}' already registered as {fam.kind}, "
                    f"cannot re-register as {cls.kind}")
            return fam

    def counter(self, name: str, help: str = "") -> MetricFamily:
        return self._family(name, Counter, help)

    def gauge(self, name: str, help: str = "") -> MetricFamily:
        return self._family(name, Gauge, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_TIME_BUCKETS
                  ) -> MetricFamily:
        return self._family(name, Histogram, help, buckets=buckets)

    def get(self, name: str) -> Optional[MetricFamily]:
        with self._lock:
            return self._families.get(name)

    def families(self) -> List[MetricFamily]:
        with self._lock:
            return list(self._families.values())

    def reset(self) -> None:
        with self._lock:
            self._families.clear()

    # -- exporters -------------------------------------------------------
    def to_dict(self) -> dict:
        out = {}
        for fam in self.families():
            out[fam.name] = {
                "kind": fam.kind,
                "help": fam.help,
                "values": [{"labels": labels, "value": child.snapshot()}
                           for labels, child in fam.children()],
            }
        return out

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.to_dict(), indent=indent, sort_keys=True)

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        lines: List[str] = []
        for fam in self.families():
            if fam.help:
                lines.append(f"# HELP {fam.name} {_esc_help(fam.help)}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for labels, child in fam.children():
                if fam.kind == "histogram":
                    snap = child.snapshot()
                    for le, c in snap["buckets"].items():
                        lines.append(_sample(fam.name + "_bucket",
                                             {**labels, "le": le}, c))
                    lines.append(_sample(fam.name + "_sum", labels,
                                         snap["sum"]))
                    lines.append(_sample(fam.name + "_count", labels,
                                         snap["count"]))
                else:
                    lines.append(_sample(fam.name, labels, child.value))
        return "\n".join(lines) + ("\n" if lines else "")


def _esc_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _esc_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace('"', '\\"')
             .replace("\n", "\\n"))


def _sample(name: str, labels: Dict[str, str], value) -> str:
    label_str = ",".join(f'{k}="{_esc_label(str(v))}"'
                         for k, v in sorted(labels.items()))
    body = f"{name}{{{label_str}}}" if label_str else name
    if isinstance(value, float) and value == int(value):
        value = int(value)
    return f"{body} {value}"


# -- histogram snapshot algebra (the fleet aggregator's merge) -------------

def _snapshot_bounds(snap: dict) -> List[Tuple[float, str]]:
    """Finite bucket bounds of a histogram snapshot, sorted, as
    (float bound, original key) pairs; the +Inf key is implicit."""
    out = []
    for key in snap.get("buckets", {}):
        if key == "+Inf":
            continue
        out.append((float(key), key))
    out.sort()
    return out


def snapshot_quantile(snap: dict, q: float) -> Optional[float]:
    """``Histogram.quantile`` over a SNAPSHOT dict (cumulative buckets +
    min/max) instead of a live histogram — same linear interpolation,
    same honesty clamps to ``[min, max]``, same +Inf-rank-reports-max
    rule. This is what makes scraped and merged histograms quantifiable
    without reconstructing a live ``Histogram``."""
    if not 0.0 < q <= 1.0:
        raise ValueError(f"quantile must be in (0, 1], got {q}")
    count = snap.get("count") or 0
    if not count:
        return None
    target = q * count
    cum_prev, lo = 0, 0.0
    for bound, key in _snapshot_bounds(snap):
        cum = snap["buckets"][key]
        c = cum - cum_prev
        if c and cum >= target:
            est = lo + (bound - lo) * (target - cum_prev) / c
            if snap.get("min") is not None:
                est = min(max(est, snap["min"]), snap["max"])
            return est
        cum_prev = cum
        lo = bound
    return snap.get("max")


def merge_histogram_snapshots(snaps: Iterable[dict]) -> dict:
    """EXACT merge of histogram snapshots sharing one bucket layout:
    counts, sums and every cumulative bucket add bucket-wise (fixed
    shared bounds make the merge well-defined); min/max combine;
    avg/p50/p99 are recomputed from the merged state. Snapshots with
    mismatched bucket bounds are REFUSED (``ValueError``) — summing
    across different layouts would silently misbucket observations."""
    snaps = [s for s in snaps if isinstance(s, dict)]
    if not snaps:
        raise ValueError("nothing to merge")
    ref = _snapshot_bounds(snaps[0])
    ref_bounds = [b for b, _ in ref]
    for s in snaps[1:]:
        if [b for b, _ in _snapshot_bounds(s)] != ref_bounds:
            raise ValueError(
                "histogram bucket bounds mismatch: "
                f"{ref_bounds} vs {[b for b, _ in _snapshot_bounds(s)]}")
    count = sum(s.get("count") or 0 for s in snaps)
    total = sum(s.get("sum") or 0.0 for s in snaps)
    mins = [s["min"] for s in snaps if s.get("min") is not None]
    maxs = [s["max"] for s in snaps if s.get("max") is not None]
    buckets = {}
    for _, key in ref:
        buckets[key] = sum(s["buckets"].get(key, 0) for s in snaps)
    buckets["+Inf"] = count
    merged = {
        "count": count,
        "sum": total,
        "min": min(mins) if mins else None,
        "max": max(maxs) if maxs else None,
        "avg": (total / count) if count else None,
        "buckets": buckets,
    }
    merged["p50"] = snapshot_quantile(merged, 0.5)
    merged["p99"] = snapshot_quantile(merged, 0.99)
    return merged


# -- default registry -----------------------------------------------------

_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _default_registry


def counter(name: str, help: str = "") -> MetricFamily:
    return _default_registry.counter(name, help)


def gauge(name: str, help: str = "") -> MetricFamily:
    return _default_registry.gauge(name, help)


def histogram(name: str, help: str = "",
              buckets: Sequence[float] = DEFAULT_TIME_BUCKETS) -> MetricFamily:
    return _default_registry.histogram(name, help, buckets=buckets)


def metric_value(name: str, default=0.0, **labels):
    """Scalar value of a counter/gauge child (histograms: the snapshot
    dict). ``default`` when the metric or label-set was never touched."""
    fam = _default_registry.get(name)
    if fam is None:
        return default
    key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
    with fam._lock:
        child = fam._children.get(key)
    return default if child is None else child.snapshot()


def reset() -> None:
    _default_registry.reset()
