"""Runtime lock witness — the dynamic half of the PT800 concurrency gate.

``paddle_tpu.analysis.concurrency`` builds the *static* lock-order graph
from the source; this module validates that model against real traffic.
Locks created through the factories here are plain ``threading``
primitives when ``FLAGS_lock_witness`` is off (the default — zero
overhead, identical types), and instrumented wrappers when it is on:

* a per-thread held-lock stack records every acquisition **order** edge
  (each lock currently held -> the lock being acquired);
* wait time (acquire call -> acquired) and hold time (acquired ->
  released) feed per-lock histograms, published as
  ``lock_wait_seconds{lock=}`` / ``lock_hold_seconds{lock=}`` /
  ``lock_acquisitions_total{lock=}`` / ``lock_order_edges_total{src,dst}``
  on the monitor registry when ``FLAGS_monitor`` is on;
* :func:`witness_report` returns the observed edges, any runtime
  lock-order **cycles**, and the wait/hold stats.

The chaos gate (``tools/load_check.py --fleet-chaos --lock-witness``)
asserts two properties after a run: zero runtime cycles, and every
observed edge ∈ the static graph — a runtime edge the static analysis
did not predict means the model (or the code) is wrong, and fails CI.

The lock *names* are the contract between the two halves: the factories
take a string literal (``make_lock("FleetRouter._lock")``) and the
static analyzer reads that same literal out of the AST as the lock's
canonical id, so the subset check compares like with like by
construction.  The witness's own bookkeeping uses a private
un-instrumented lock and never acquires a witnessed lock, so it can
never itself deadlock or pollute the edge set.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from .registry import Histogram

__all__ = [
    "make_lock", "make_rlock", "make_condition", "witness_enabled",
    "witness_report", "reset_witness", "witness_edges", "witness_cycles",
]

# fine-grained buckets: lock waits/holds live in the microsecond band
_LOCK_BUCKETS = (1e-6, 1e-5, 1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 5e-2,
                 0.1, 0.5, 1.0, 5.0)


def witness_enabled() -> bool:
    """``FLAGS_lock_witness`` (default off)."""
    from ..flags import flag

    return bool(flag("lock_witness"))


class _LockStats:
    __slots__ = ("wait", "hold", "acquisitions")

    def __init__(self):
        lk = threading.RLock()
        self.wait = Histogram(lk, buckets=_LOCK_BUCKETS)
        self.hold = Histogram(lk, buckets=_LOCK_BUCKETS)
        self.acquisitions = 0


class _WitnessState:
    """Process-wide witness store.  Guarded by a plain (un-witnessed)
    lock; recording never acquires a witnessed lock, so the witness can
    neither deadlock nor add edges of its own."""

    def __init__(self):
        self.lock = threading.Lock()
        self.tls = threading.local()
        self.edges: Dict[Tuple[str, str], dict] = {}
        self.stats: Dict[str, _LockStats] = {}

    def held(self) -> list:
        h = getattr(self.tls, "held", None)
        if h is None:
            h = []
            self.tls.held = h
        return h


_state = _WitnessState()


def _record_acquired(w: "_WitnessLock", wait_s: float) -> None:
    held = _state.held()
    thread = threading.current_thread().name
    with _state.lock:
        st = _state.stats.get(w.name)
        if st is None:
            st = _state.stats[w.name] = _LockStats()
        st.acquisitions += 1
        st.wait.observe(wait_s)
        for prev, _t in held:
            if prev is w:
                continue           # reentrant re-acquire: not an edge
            key = (prev.name, w.name)
            e = _state.edges.get(key)
            if e is None:
                _state.edges[key] = {"count": 1, "thread": thread}
            else:
                e["count"] += 1
    held.append((w, time.perf_counter()))
    _publish(w.name, "lock_wait_seconds", wait_s)


def _record_released(w: "_WitnessLock") -> None:
    held = _state.held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] is w:
            _, t_acq = held.pop(i)
            hold_s = time.perf_counter() - t_acq
            with _state.lock:
                st = _state.stats.get(w.name)
                if st is not None:
                    st.hold.observe(hold_s)
            _publish(w.name, "lock_hold_seconds", hold_s)
            return


def _publish(name: str, metric: str, v: float) -> None:
    """Mirror into the monitor registry (the CI metrics artifact)."""
    from . import enabled, histogram

    if enabled():
        histogram(metric, "lock witness timing (FLAGS_lock_witness)",
                  buckets=_LOCK_BUCKETS).labels(lock=name).observe(v)


class _WitnessLock:
    """Instrumented Lock/RLock with the duck-type surface
    ``threading.Condition`` needs (``_is_owned`` / ``_release_save`` /
    ``_acquire_restore``), so conditions built over witnessed locks keep
    working — and their release/re-acquire around ``wait()`` is recorded
    like any other."""

    def __init__(self, name: str, reentrant: bool):
        self.name = name
        self.reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()

    # -- lock protocol ---------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        t0 = time.perf_counter()
        got = self._inner.acquire(blocking, timeout)
        if got:
            _record_acquired(self, time.perf_counter() - t0)
        return got

    def release(self):
        _record_released(self)
        self._inner.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def locked(self):
        if self.reentrant:
            # RLock has no .locked() before 3.12; probe instead
            if self._inner.acquire(blocking=False):
                self._inner.release()
                return False
            return True
        return self._inner.locked()

    # -- Condition duck-type --------------------------------------------
    def _is_owned(self):
        inner_owned = getattr(self._inner, "_is_owned", None)
        if inner_owned is not None:
            return inner_owned()
        return any(w is self for w, _ in _state.held())

    def _release_save(self):
        """Full release for Condition.wait: pop our bookkeeping (the lock
        really is free while waiting) and save the inner state."""
        popped = 0
        held = _state.held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                _record_released(self)
                popped += 1
        inner_save = getattr(self._inner, "_release_save", None)
        if inner_save is not None:
            return (inner_save(), popped)
        self._inner.release()
        return (None, popped)

    def _acquire_restore(self, saved):
        state, popped = saved
        t0 = time.perf_counter()
        inner_restore = getattr(self._inner, "_acquire_restore", None)
        if inner_restore is not None:
            inner_restore(state)
        else:
            self._inner.acquire()
        # the wake-up re-acquire: record wait + re-push (no new edges —
        # the order was established at the original acquire)
        wait_s = time.perf_counter() - t0
        held = _state.held()
        with _state.lock:
            st = _state.stats.get(self.name)
            if st is None:
                st = _state.stats[self.name] = _LockStats()
            st.acquisitions += 1
            st.wait.observe(wait_s)
        for _ in range(max(1, popped)):
            held.append((self, time.perf_counter()))
        _publish(self.name, "lock_wait_seconds", wait_s)

    def __repr__(self):
        return f"<WitnessLock {self.name} reentrant={self.reentrant}>"


# --------------------------------------------------------------------------
# factories (the only public construction surface)
# --------------------------------------------------------------------------

def make_lock(name: str):
    """A named non-reentrant lock; plain ``threading.Lock()`` unless
    ``FLAGS_lock_witness`` is on."""
    if not witness_enabled():
        return threading.Lock()
    return _WitnessLock(name, reentrant=False)


def make_rlock(name: str):
    """A named reentrant lock; plain ``threading.RLock()`` unless
    ``FLAGS_lock_witness`` is on."""
    if not witness_enabled():
        return threading.RLock()
    return _WitnessLock(name, reentrant=True)


def make_condition(name: str, lock=None):
    """A condition variable over ``lock`` (or its own named RLock).
    Acquiring the condition acquires the underlying lock, so witnessed
    conditions contribute edges under the *lock's* name — exactly how
    the static analyzer aliases ``Condition(lock)`` onto its lock."""
    if lock is None:
        lock = make_rlock(name)
    return threading.Condition(lock)


# --------------------------------------------------------------------------
# reporting
# --------------------------------------------------------------------------

def witness_edges() -> Set[Tuple[str, str]]:
    with _state.lock:
        return set(_state.edges)


def witness_cycles() -> List[List[str]]:
    """Cycles in the observed runtime edge set (empty = no deadlock
    potential was exercised)."""
    with _state.lock:
        edges = set(_state.edges)
    nodes = {a for a, _ in edges} | {b for _, b in edges}
    # simple DFS cycle enumeration (the runtime graph is tiny)
    from ..analysis.concurrency import _find_cycles

    return _find_cycles(nodes, edges)


def witness_report() -> dict:
    """Everything observed since the last :func:`reset_witness`."""
    with _state.lock:
        edges = [{"src": a, "dst": b, "count": e["count"],
                  "first_thread": e["thread"]}
                 for (a, b), e in sorted(_state.edges.items())]
        locks = {}
        for name, st in sorted(_state.stats.items()):
            locks[name] = {
                "acquisitions": st.acquisitions,
                "wait": _hist_dict(st.wait),
                "hold": _hist_dict(st.hold),
            }
    return {
        "enabled": witness_enabled(),
        "locks": locks,
        "edges": edges,
        "cycles": witness_cycles(),
    }


def _hist_dict(h: Histogram) -> dict:
    return {
        "count": h.count,
        "sum": round(h.sum, 9),
        "max": h._max,
        "p50": h.quantile(0.5),
        "p99": h.quantile(0.99),
    }


def reset_witness() -> None:
    """Drop observed edges/stats (held stacks of live threads persist —
    they reflect reality)."""
    with _state.lock:
        _state.edges.clear()
        _state.stats.clear()
