"""Event-hook API: subscribe to executor lifecycle events.

``add_hook(on_step_begin=..., on_step_end=..., on_compile=...)`` lets
trainers, ``bench.py`` and serving wrappers observe execution without
patching the executor (the reference exposed the same seam as the
device_worker/trainer callbacks; here it is three well-typed events fed by
``Executor.run`` / ``run_chained`` / ``CompiledProgram``).

Hook failures are contained: a raising hook is logged and skipped, never
allowed to break a training step.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from .lockwitness import make_lock

__all__ = ["StepRecord", "CompileRecord", "Hook", "add_hook", "remove_hook",
           "clear_hooks", "dispatch"]

log = logging.getLogger("paddle_tpu.monitor")


@dataclasses.dataclass
class StepRecord:
    """One executor step (``path``: run | chained | parallel)."""

    path: str
    program_serial: int
    step_index: int = 0
    cache_hit: Optional[bool] = None
    iterations: int = 1              # run_chained: scanned steps per dispatch
    duration_s: Optional[float] = None
    feed_bytes: int = 0              # host->device transfer this step
    fetch_bytes: int = 0             # device->host transfer this step
    donated_buffers: int = 0         # state vars donated to XLA
    kept_buffers: int = 0            # state vars kept (donation-unsafe/copied)
    donated_bytes: int = 0           # live bytes of the donated buffers
    batch_rows: int = 0              # leading feed dim (cost-model batch)
    fetch_names: Tuple[str, ...] = ()

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class CompileRecord:
    """One compile-cache miss (fresh compile or recompilation)."""

    path: str
    program_serial: int
    build_site: str                  # op_callstack of the program's first op
    components: Dict[str, Any]       # the cache-key components
    recompile: bool                  # program serial was compiled before
    changed: Tuple[str, ...]         # key components that differ vs last time
    n_compiles: int                  # compiles of this program so far (>=1)
    detail: str = ""                 # human diff, e.g. old->new feed sig
    donated_bytes_est: int = 0       # static estimate (memory_plan sizes)
    trace_lower_s: Optional[float] = None   # jaxpr trace + StableHLO lower
    compile_s: Optional[float] = None       # XLA compile

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        # components may hold tuples of tuples; keep them JSON-friendly
        d["components"] = {k: repr(v) for k, v in self.components.items()}
        return d


class Hook:
    """Handle returned by ``add_hook``; pass to ``remove_hook``."""

    def __init__(self, on_step_begin=None, on_step_end=None, on_compile=None):
        self.on_step_begin = on_step_begin
        self.on_step_end = on_step_end
        self.on_compile = on_compile


_lock = make_lock("monitor.hooks._lock")
_hooks: List[Hook] = []


def add_hook(on_step_begin: Optional[Callable[[StepRecord], None]] = None,
             on_step_end: Optional[Callable[[StepRecord], None]] = None,
             on_compile: Optional[Callable[[CompileRecord], None]] = None,
             ) -> Hook:
    hook = Hook(on_step_begin, on_step_end, on_compile)
    with _lock:
        _hooks.append(hook)
    return hook


def remove_hook(hook: Hook) -> None:
    with _lock:
        try:
            _hooks.remove(hook)
        except ValueError:
            pass


def clear_hooks() -> None:
    with _lock:
        _hooks.clear()


def dispatch(event: str, record) -> None:
    """Fire one event ('step_begin' | 'step_end' | 'compile') at every
    subscribed hook; exceptions are logged, never propagated."""
    with _lock:
        hooks = list(_hooks)
    for h in hooks:
        fn = getattr(h, "on_" + event, None)
        if fn is None:
            continue
        try:
            fn(record)
        except Exception:
            log.exception("monitor hook %s raised; the event was skipped "
                          "for this hook but it stays subscribed — "
                          "remove_hook() to silence it", event)
