"""Recompilation diagnostics: explain *why* a cached program recompiled.

On TPU a silent recompilation is the #1 perf killer — a step that usually
takes 65 ms stalls for seconds while XLA rebuilds the executable, and
nothing in the reference stack (or ours, before this module) said why.
This tracker watches every compile-cache miss: for a program already seen
it diffs the cache-key components (program version/op-count, feed
signature, fetch list, scope serial, flags) against the previous compile
and names exactly what changed, attributed to the program's build site
(the ``op_callstack`` of its first user-built op).

Logging contract (``FLAGS_log_compiles``-style):
  * ``FLAGS_log_compiles=1`` — every compile logs INFO, every recompile
    logs WARNING with the component diff.
  * always — after ``FLAGS_recompile_warn_threshold`` recompiles of the
    same program (default 3), a WARNING fires regardless of the flag: this
    is the "your serving loop recompiles every request" tripwire.

Events are retained in a bounded ring (``events()``); ``tools/
metrics_report.py`` dumps them into the CI metrics artifact and its
``--check`` gate fails on unexpected recompiles.
"""
from __future__ import annotations

import collections
import logging
import threading
from typing import Any, Dict, List, Optional, Tuple

from .hooks import CompileRecord
from .lockwitness import make_lock

__all__ = ["RecompileTracker", "build_site", "get_tracker"]

log = logging.getLogger("paddle_tpu.monitor")

_MAX_EVENTS = 256
# per-(program, path) compile history cap: a server that builds a fresh
# Program per request must not leak one tracker entry per request forever
_MAX_PROGRAMS = 4096


def build_site(program) -> str:
    """The user line that built the program: the first global-block op
    carrying an ``op_callstack`` attr (reference op_call_stack.h — ops
    remember their creation site; the program inherits its first op's)."""
    try:
        for op in program.global_block.ops:
            site = op.attrs.get("op_callstack")
            if site:
                return str(site)
    except Exception:
        pass
    return "<unknown build site>"


def _diff_detail(name: str, old, new) -> str:
    """Compact old->new rendering for one changed component. Feed
    signatures diff per feed name so the message points at the tensor."""
    if name == "feed_signature":
        old_map = {e[0]: e[1:] for e in (old or ())}
        new_map = {e[0]: e[1:] for e in (new or ())}
        parts = []
        for k in sorted(set(old_map) | set(new_map)):
            o, n = old_map.get(k), new_map.get(k)
            if o != n:
                parts.append(f"'{k}': {o} -> {n}")
        if parts:
            return f"{name}[{'; '.join(parts)}]"
    return f"{name}: {old!r} -> {new!r}"


class RecompileTracker:
    def __init__(self):
        self._lock = make_lock("RecompileTracker._lock")
        # (program serial, path) -> (n_compiles, last components, site).
        # Keyed per path: run and run_chained build different executable
        # kinds with different key components — crossing them would report
        # phantom recompiles on the first chained call of a run program.
        self._programs: Dict[Tuple[int, str],
                             Tuple[int, Dict[str, Any], str]] = {}
        self._events = collections.deque(maxlen=_MAX_EVENTS)

    def observe(self, path: str, program_serial: int, site: str,
                components: Dict[str, Any]) -> CompileRecord:
        """Record one compile-cache miss; returns the CompileRecord (with
        ``recompile``/``changed``/``detail`` filled, timings still None)."""
        from ..flags import flag

        with self._lock:
            prev = self._programs.pop((program_serial, path), None)
            n = 1 if prev is None else prev[0] + 1
            # pop-then-insert keeps the dict LRU-ordered by last compile,
            # so eviction drops the LEAST recently compiling program, not
            # the hot one this tracker exists to watch
            self._programs[(program_serial, path)] = (n, dict(components),
                                                      site)
            while len(self._programs) > _MAX_PROGRAMS:
                # an evicted program that recompiles later reads as a
                # fresh compile — acceptable for a bounded diagnostic
                self._programs.pop(next(iter(self._programs)))
        if prev is None:
            rec = CompileRecord(path=path, program_serial=program_serial,
                                build_site=site, components=dict(components),
                                recompile=False, changed=(), n_compiles=n)
        else:
            _, last, _ = prev
            changed = tuple(k for k in components
                            if components.get(k) != last.get(k))
            detail = "; ".join(_diff_detail(k, last.get(k),
                                            components.get(k))
                               for k in changed)
            if not changed:
                detail = ("identical cache key — compiled step evicted or "
                          "use_program_cache=False")
            rec = CompileRecord(path=path, program_serial=program_serial,
                                build_site=site, components=dict(components),
                                recompile=True, changed=changed,
                                n_compiles=n, detail=detail)
        with self._lock:
            self._events.append(rec)

        n_recompiles = n - 1
        if rec.recompile:
            msg = (f"recompilation #{n_recompiles} of program "
                   f"{program_serial} (built at {rec.build_site}) on the "
                   f"'{path}' path — cache-key changed in "
                   f"{', '.join(rec.changed) or 'nothing'}: {rec.detail}")
            threshold = int(flag("recompile_warn_threshold"))
            if flag("log_compiles"):
                log.warning(msg)
            elif threshold and n_recompiles == threshold:
                log.warning(
                    "%s — this program has now recompiled %d times; every "
                    "recompile stalls the step for the full XLA compile "
                    "(set FLAGS_log_compiles=1 to log each one)",
                    msg, n_recompiles)
        elif flag("log_compiles"):
            log.info("compiling program %s (built at %s) on the '%s' path",
                     program_serial, rec.build_site, path)
        return rec

    def recompile_count(self, program_serial: Optional[int] = None) -> int:
        with self._lock:
            return sum(max(0, n - 1)
                       for (serial, _), (n, _, _) in self._programs.items()
                       if program_serial is None or serial == program_serial)

    def events(self, recompiles_only: bool = False) -> List[CompileRecord]:
        with self._lock:
            evs = list(self._events)
        return [e for e in evs if e.recompile] if recompiles_only else evs

    def reset(self) -> None:
        with self._lock:
            self._programs.clear()
            self._events.clear()


_tracker = RecompileTracker()


def get_tracker() -> RecompileTracker:
    return _tracker
