"""Runtime numerics witness — the dynamic half of the PT900 numerics gate.

``paddle_tpu.analysis.numerics`` proves conservative *static* value
intervals per var; this module observes the real ones. With
``FLAGS_numerics_witness=1`` the executor's step trace appends one tap per
float op output (lowering.py, next to the FLAGS_check_nan_inf taps): a
jitted ``[abs-max, min, max, nonfinite-count]`` stats vector, stacked into
one ``(N, 4)`` array the step returns alongside its fetches — one fused
device->host transfer per step, never a sync per op. The executor hands
each step's stats to :func:`record_step`, which merges them into a
process-wide per-var range store and mirrors them onto the monitor
registry when ``FLAGS_monitor`` is on.

The cross-check contract (the lock-witness idiom, tolerance-free): every
observed finite value must lie INSIDE its var's statically-proven interval
— the static side is conservative by construction, so any escape is an
analysis soundness bug, and ``tools/lint_numerics.py --witness`` fails CI
on it (:func:`containment_violations`). Observed abs-max additionally
feeds back into the PT906 quantizability report as calibration data.

The witness is also the attribution source for the nan/inf machinery
(docs/RESILIENCE.md): :func:`first_offender` names the first var of the
most recent step whose nonfinite count is nonzero, which
``resilience.nonfinite`` folds into the skip-escalation message and the
flight recorder's ``nonfinite_step`` incident.

Disabled (the default) this module costs nothing on the hot path: the
executor passes ``num_witness_meta=None`` and no tap is ever traced —
the same fast-path contract as the trace spans and the lock witness.

Min/max fold nonfinite lanes away (``where(finite, v, ±inf)``); the
nonfinite population is carried separately in the count lane, so a var
that went inf still reports the range of its finite values.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "numerics_witness_enabled", "record_step", "first_offender",
    "numerics_witness_vars", "numerics_witness_report",
    "reset_numerics_witness", "containment_violations",
]


def numerics_witness_enabled() -> bool:
    """``FLAGS_numerics_witness`` (default off)."""
    from ..flags import flag

    return bool(flag("numerics_witness"))


class _VarRange:
    __slots__ = ("absmax", "min", "max", "nonfinite", "steps")

    def __init__(self):
        self.absmax = 0.0
        self.min = np.inf       # stays +inf until a finite value is seen
        self.max = -np.inf
        self.nonfinite = 0
        self.steps = 0

    def to_dict(self) -> dict:
        return {"absmax": float(self.absmax),
                "min": None if not np.isfinite(self.min) else float(self.min),
                "max": None if not np.isfinite(self.max) else float(self.max),
                "nonfinite": int(self.nonfinite), "steps": int(self.steps)}


class _WitnessState:
    """Process-wide range store. Guarded by a plain lock; recording never
    runs device code — the stats arrive as one host array per step."""

    def __init__(self):
        self.lock = threading.Lock()
        self.vars: Dict[str, _VarRange] = {}
        self.last_offender: Optional[str] = None


_state = _WitnessState()


def record_step(names: Sequence[str], stats, path: str = "run") -> None:
    """Merge one step's ``(N, 4)`` stats array (rows aligned with
    ``names``: abs-max, min, max, nonfinite-count). Called by the executor
    after every witness-instrumented dispatch."""
    arr = np.asarray(stats, dtype=np.float64)
    if arr.size == 0:
        with _state.lock:
            _state.last_offender = None
        return
    offender = None
    with _state.lock:
        for name, row in zip(names, arr):
            r = _state.vars.get(name)
            if r is None:
                r = _state.vars[name] = _VarRange()
            r.absmax = max(r.absmax, float(row[0]))
            r.min = min(r.min, float(row[1]))
            r.max = max(r.max, float(row[2]))
            n_bad = int(row[3])
            r.nonfinite += n_bad
            r.steps += 1
            if n_bad and offender is None:
                offender = name
        _state.last_offender = offender
    _publish(names, arr, path)


def _publish(names: Sequence[str], arr, path: str) -> None:
    """Mirror into the monitor registry (the CI metrics artifact)."""
    from . import counter, enabled, gauge

    if not enabled():
        return
    total_bad = int(arr[:, 3].sum())
    if total_bad:
        counter("numerics_nonfinite_values_total",
                "nonfinite elements observed by the numerics witness "
                "(FLAGS_numerics_witness), by path").labels(
            path=path).inc(total_bad)
    gauge("numerics_witness_vars",
          "vars instrumented by the numerics witness in the most recent "
          "step, by path").labels(path=path).set(len(names))
    # per-var gauges only for the step's worst offenders: full per-var
    # label cardinality belongs in numerics_witness_report(), not the
    # registry
    order = np.argsort(arr[:, 0])[::-1][:8]
    for i in order:
        gauge("numerics_var_absmax",
              "observed abs-max of the largest-magnitude witnessed vars "
              "(most recent step)").labels(var=str(names[int(i)])).set(
            float(arr[int(i), 0]))


def first_offender() -> Optional[str]:
    """First var of the most recent recorded step with a nonzero
    nonfinite count (None = last step was clean). The attribution the
    nan_inf_policy escalation and the flight recorder's nonfinite
    incident name."""
    with _state.lock:
        return _state.last_offender


def numerics_witness_vars() -> Dict[str, dict]:
    """Merged per-var observed ranges since the last reset. The
    ``absmax`` entries are exactly the calibration dict
    ``numerics_check`` accepts via ``numerics_calibration``."""
    with _state.lock:
        return {n: r.to_dict() for n, r in sorted(_state.vars.items())}


def numerics_witness_report() -> dict:
    """Everything observed since the last :func:`reset_numerics_witness`."""
    vars_ = numerics_witness_vars()
    return {
        "enabled": numerics_witness_enabled(),
        "vars": vars_,
        "nonfinite_total": sum(v["nonfinite"] for v in vars_.values()),
        "first_offender": first_offender(),
    }


def reset_numerics_witness() -> None:
    with _state.lock:
        _state.vars.clear()
        _state.last_offender = None


def containment_violations(
        static_intervals: Dict[str, Tuple[float, float]],
        observed: Optional[Dict[str, dict]] = None) -> List[dict]:
    """The CI cross-check: every observed finite value must lie inside
    its statically-proven interval, tolerance-free (the static side is
    conservative by construction — an escape is an analysis soundness
    bug, the lock-witness subset idiom). Only vars present on BOTH sides
    are compared; each violation names the var, the bound and both
    values."""
    if observed is None:
        observed = numerics_witness_vars()
    violations = []
    for name, (lo, hi) in sorted(static_intervals.items()):
        obs = observed.get(name)
        if obs is None or obs["min"] is None:
            continue        # never witnessed, or no finite value seen
        if obs["min"] < lo:
            violations.append({
                "var": name, "bound": "lo", "static": lo,
                "observed": obs["min"],
                "detail": f"observed min {obs['min']:g} < static lower "
                          f"bound {lo:g}"})
        if obs["max"] > hi:
            violations.append({
                "var": name, "bound": "hi", "static": hi,
                "observed": obs["max"],
                "detail": f"observed max {obs['max']:g} > static upper "
                          f"bound {hi:g}"})
    return violations
