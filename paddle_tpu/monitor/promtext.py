"""Scrape-side parser for the Prometheus text exposition format (0.0.4).

The consuming half of ``MetricsRegistry.to_prometheus()``: the fleet
aggregator (``serving.fleet.telemetry``) can scrape a replica's
``GET /metrics`` in text form, and the exporter-conformance unit tests
round-trip hostile HELP strings and label values through this parser to
prove the escaping is per-spec in BOTH directions.

Stdlib-only, tolerant of the full format (comments, unknown TYPE kinds,
arbitrary label order, escaped ``\\``/``\\"``/``\\n`` in label values,
``+Inf``/``-Inf``/``NaN`` sample values) but strict about structural
garbage: a line that is neither a comment nor a parseable sample raises
``PromParseError`` — the aggregator treats that as a typed
corrupt-scrape failure, never a silent partial parse.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

__all__ = ["PromParseError", "ParsedFamily", "parse_prometheus_text",
           "histogram_snapshot_from_samples"]


class PromParseError(ValueError):
    """The text body is not valid exposition format."""


class ParsedFamily:
    """One metric family reassembled from the text form."""

    def __init__(self, name: str):
        self.name = name
        self.kind: Optional[str] = None    # from # TYPE, if present
        self.help: Optional[str] = None    # from # HELP, if present
        # [(labels dict, float value)] in document order
        self.samples: List[Tuple[Dict[str, str], float]] = []

    def value(self, **labels) -> Optional[float]:
        want = {str(k): str(v) for k, v in labels.items()}
        for lab, v in self.samples:
            if lab == want:
                return v
        return None


def _unescape_help(s: str) -> str:
    out, i = [], 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            nxt = s[i + 1]
            if nxt == "\\":
                out.append("\\")
                i += 2
                continue
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


def _parse_labels(body: str, line: str) -> Dict[str, str]:
    labels: Dict[str, str] = {}
    i, n = 0, len(body)
    while i < n:
        j = body.find("=", i)
        if j < 0:
            raise PromParseError(f"bad label pair in: {line!r}")
        name = body[i:j].strip().lstrip(",").strip()
        if not name:
            raise PromParseError(f"empty label name in: {line!r}")
        j += 1
        if j >= n or body[j] != '"':
            raise PromParseError(f"unquoted label value in: {line!r}")
        j += 1
        val = []
        while j < n:
            c = body[j]
            if c == "\\" and j + 1 < n:
                nxt = body[j + 1]
                if nxt == "\\":
                    val.append("\\")
                elif nxt == '"':
                    val.append('"')
                elif nxt == "n":
                    val.append("\n")
                else:           # unknown escape: keep verbatim
                    val.append(c)
                    val.append(nxt)
                j += 2
                continue
            if c == '"':
                break
            val.append(c)
            j += 1
        else:
            raise PromParseError(f"unterminated label value in: {line!r}")
        labels[name] = "".join(val)
        i = j + 1
    return labels


def _parse_value(tok: str, line: str) -> float:
    try:
        return float(tok)       # handles +Inf/-Inf/NaN spellings too
    except ValueError:
        raise PromParseError(f"bad sample value in: {line!r}")


def parse_prometheus_text(text: str) -> Dict[str, ParsedFamily]:
    """Parse an exposition body into ``{family_name: ParsedFamily}``.

    Histogram series keep their ``_bucket``/``_sum``/``_count`` suffixed
    sample names but are grouped under the BASE family name when a
    ``# TYPE <base> histogram`` line declared them (the shape our own
    exporter emits); without a TYPE line each suffixed series stands as
    its own family.
    """
    if isinstance(text, bytes):
        try:
            text = text.decode("utf-8")
        except UnicodeDecodeError as e:
            raise PromParseError(f"not utf-8: {e}")
    families: Dict[str, ParsedFamily] = {}
    histogram_bases = set()

    def fam(name: str) -> ParsedFamily:
        f = families.get(name)
        if f is None:
            f = families[name] = ParsedFamily(name)
        return f

    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "HELP":
                fam(parts[2]).help = _unescape_help(
                    parts[3] if len(parts) > 3 else "")
            elif len(parts) >= 4 and parts[1] == "TYPE":
                fam(parts[2]).kind = parts[3]
                if parts[3] == "histogram":
                    histogram_bases.add(parts[2])
            # other comments are ignored per spec
            continue
        # sample: name[{labels}] value [timestamp]
        if "{" in line:
            brace = line.index("{")
            name = line[:brace]
            close = line.rfind("}")
            if close < brace:
                raise PromParseError(f"unbalanced braces in: {line!r}")
            labels = _parse_labels(line[brace + 1:close], line)
            rest = line[close + 1:].split()
        else:
            toks = line.split()
            if len(toks) < 2:
                raise PromParseError(f"missing value in: {line!r}")
            name, rest = toks[0], toks[1:]
            labels = {}
        if not rest:
            raise PromParseError(f"missing value in: {line!r}")
        if not name or not (name[0].isalpha() or name[0] in "_:"):
            raise PromParseError(f"bad metric name in: {line!r}")
        value = _parse_value(rest[0], line)
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] \
                    in histogram_bases:
                base = name[:-len(suffix)]
                break
        f = fam(base)
        if base != name:
            labels = dict(labels)
            labels["__series__"] = name[len(base) + 1:]
        f.samples.append((labels, value))
    return families


def histogram_snapshot_from_samples(family: ParsedFamily) -> dict:
    """Rebuild a histogram SNAPSHOT dict (the ``Histogram.snapshot()``
    shape minus min/max, which the text form does not carry) from a
    parsed histogram family's ``_bucket``/``_sum``/``_count`` samples.
    Labeled histograms: pass a family filtered to one label set."""
    buckets: Dict[str, float] = {}
    count = total = 0.0
    for labels, v in family.samples:
        series = labels.get("__series__")
        if series == "bucket":
            le = labels.get("le")
            if le is None:
                raise PromParseError(
                    f"_bucket sample without le in {family.name}")
            buckets[le] = v
        elif series == "sum":
            total = v
        elif series == "count":
            count = v
    snap = {
        "count": int(count),
        "sum": total,
        "min": None,
        "max": None,
        "avg": (total / count) if count else None,
        "buckets": {k: int(v) for k, v in buckets.items()},
    }
    return snap
