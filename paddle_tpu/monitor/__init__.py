"""paddle_tpu.monitor — executor runtime metrics, recompilation diagnostics
and structured step tracing.

The reference stack's profiler/CUPTI layer (platform/profiler.h,
device_tracer.h) gave Fluid per-event visibility; this package is the
TPU-native equivalent for the rebuild's actual hot paths, which are
otherwise opaque: the jit compile cache, liveness-gated buffer donation,
and ``run_chained``. Three layers:

* ``registry`` — thread-safe counters/gauges/histograms with JSON and
  Prometheus-text exporters (``monitor.get_registry()``,
  ``monitor.metric_value()``).
* ``hooks`` — ``monitor.add_hook(on_step_begin=..., on_step_end=...,
  on_compile=...)`` subscription API fed by the executor.
* ``recompile`` — cache-miss diagnostics that name *which* cache-key
  component changed (program / feed_signature / fetch_list / scope /
  flags) with build-site attribution, and warn after
  ``FLAGS_recompile_warn_threshold`` recompiles of one program.

``paddle_tpu.resilience`` reports through the same registry:
``resilience_retries_total`` / ``resilience_giveups_total`` (transient-site
retry), ``resilience_faults_injected_total`` (FLAGS_fault_plan),
``steps_skipped_nonfinite_total`` (FLAGS_nan_inf_policy) and
``trainer_ckpt_fallback_total`` (torn-checkpoint recovery) — see
docs/RESILIENCE.md.

Everything is on by default (``FLAGS_monitor=0`` disables collection —
hooks, counters and diagnostics all go quiet). Executor spans additionally
flow through ``profiler.RecordEvent`` so they land in the host timeline
(``tools/timeline.py``). ``tools/metrics_report.py`` dumps
``monitor.snapshot()`` as the CI metrics artifact and gates on unexpected
recompiles. Metric names and semantics: docs/OBSERVABILITY.md.
"""
from __future__ import annotations

import itertools
import time
from typing import Any, Dict, Optional

from .hooks import (CompileRecord, Hook, StepRecord, add_hook, clear_hooks,
                    dispatch, remove_hook)
from .lockwitness import (make_condition, make_lock, make_rlock,
                          reset_witness, witness_cycles, witness_edges,
                          witness_enabled, witness_report)
from .numwitness import (containment_violations, first_offender,
                         numerics_witness_enabled, numerics_witness_report,
                         numerics_witness_vars, reset_numerics_witness)
from .promtext import (ParsedFamily, PromParseError,
                       histogram_snapshot_from_samples,
                       parse_prometheus_text)
from .recompile import RecompileTracker, build_site, get_tracker
from .registry import (DEFAULT_TIME_BUCKETS, Counter, Gauge, Histogram,
                       MetricFamily, MetricsRegistry, counter, gauge,
                       get_registry, histogram,
                       merge_histogram_snapshots, metric_value,
                       snapshot_quantile)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricFamily", "MetricsRegistry",
    "StepRecord", "CompileRecord", "Hook", "RecompileTracker",
    "add_hook", "remove_hook", "clear_hooks", "get_registry", "counter",
    "gauge", "histogram", "metric_value", "enabled", "record_cache_lookup",
    "observe_compile", "complete_compile", "step_begin", "step_end",
    "record_pass", "record_remat", "record_fusion",
    "record_watchdog_timeout",
    "program_cost", "observe_step_cost", "observe_serving_cost",
    "observe_comms_cost",
    "recompile_events",
    "recompile_count", "snapshot", "reset", "get_tracker", "build_site",
    "make_lock", "make_rlock", "make_condition", "witness_enabled",
    "witness_report", "witness_edges", "witness_cycles", "reset_witness",
    "numerics_witness_enabled", "numerics_witness_report",
    "numerics_witness_vars", "reset_numerics_witness", "first_offender",
    "containment_violations",
    # telemetry plane: exact histogram-snapshot algebra + the
    # scrape-side Prometheus text parser (docs/OBSERVABILITY.md
    # "Fleet telemetry plane")
    "merge_histogram_snapshots", "snapshot_quantile",
    "parse_prometheus_text", "histogram_snapshot_from_samples",
    "ParsedFamily", "PromParseError", "telemetry_enabled",
]

_step_counter = itertools.count()


def enabled() -> bool:
    """Collection master switch (``FLAGS_monitor``, default on)."""
    from ..flags import flag

    return bool(flag("monitor"))


def telemetry_enabled() -> bool:
    """Fleet telemetry plane master switch (``FLAGS_fleet_telemetry``,
    default OFF): gates the aggregator scrape thread and trace-exemplar
    capture — off must stay a hot-path no-op
    (docs/OBSERVABILITY.md "Fleet telemetry plane")."""
    from ..flags import flag

    return bool(flag("fleet_telemetry"))


# -- executor instrumentation entry points ---------------------------------
# (called from Executor.run / run_chained / CompiledProgram; every entry
# no-ops when FLAGS_monitor=0)

def record_cache_lookup(path: str, hit: bool) -> None:
    if not enabled():
        return
    counter("executor_cache_lookups_total",
            "compile-cache lookups by path and result").labels(
        path=path, result="hit" if hit else "miss").inc()


def observe_compile(path: str, program, components: Dict[str, Any],
                    donated_names=()) -> Optional[CompileRecord]:
    """Record a compile-cache miss: compile counters, recompile diagnosis
    (component diff + build site), static donated-bytes estimate from the
    program's var shapes (``memory_plan`` sizing). Returns the record so
    the caller can fill stage timings and fire ``complete_compile``."""
    if not enabled():
        return None
    serial = int(getattr(program, "_serial", -1))
    rec = get_tracker().observe(path, serial, build_site(program),
                                components)
    counter("executor_compiles_total",
            "compile-cache misses that built a new executable").labels(
        path=path).inc()
    if rec.recompile:
        counter("executor_recompiles_total",
                "compiles of a program that was already compiled — the "
                "TPU perf tripwire").labels(path=path).inc()
    try:
        from ..analysis.liveness import _var_bytes

        blk = program.global_block
        rec.donated_bytes_est = sum(
            _var_bytes(blk.var(n), 1)[0]
            for n in donated_names if blk.has_var(n))
    except Exception:
        pass
    return rec


def complete_compile(rec: Optional[CompileRecord],
                     trace_lower_s: Optional[float],
                     compile_s: Optional[float]) -> None:
    """Attach stage timings to a compile record, export them, and fire the
    ``on_compile`` hooks. Called once per compile, after the executable
    exists (or after stage timing failed — timings then stay None)."""
    if rec is None:
        return
    rec.trace_lower_s = trace_lower_s
    rec.compile_s = compile_s
    if trace_lower_s is not None:
        histogram("executor_compile_seconds",
                  "compile-stage wall time by stage").labels(
            stage="trace_lower").observe(trace_lower_s)
    if compile_s is not None:
        histogram("executor_compile_seconds",
                  "compile-stage wall time by stage").labels(
            stage="xla_compile").observe(compile_s)
    dispatch("compile", rec)


def step_begin(path: str, program) -> Optional[StepRecord]:
    if not enabled():
        return None
    rec = StepRecord(path=path,
                     program_serial=int(getattr(program, "_serial", -1)),
                     step_index=next(_step_counter))
    # non-field stash for the cost model (step_end turns duration +
    # batch_rows into MFU gauges); transient — dies with the record
    rec._program = program
    rec._t0 = time.perf_counter()
    dispatch("step_begin", rec)
    return rec


def step_end(rec: Optional[StepRecord]) -> None:
    if rec is None:
        return
    if rec.duration_s is None and hasattr(rec, "_t0"):
        rec.duration_s = time.perf_counter() - rec._t0
    p = {"path": rec.path}
    counter("executor_steps_total", "executor dispatches").labels(**p).inc()
    if rec.path == "chained":
        counter("executor_chained_iterations_total",
                "scanned iterations inside run_chained dispatches").inc(
            rec.iterations)
    if rec.duration_s is not None:
        histogram("executor_step_seconds",
                  "wall time of one executor dispatch (feed packing + "
                  "device step + state writeback)").labels(**p).observe(
            rec.duration_s)
        prog = getattr(rec, "_program", None)
        if prog is not None and rec.batch_rows:
            observe_step_cost(prog, rec.batch_rows, rec.duration_s,
                              iterations=rec.iterations, path=rec.path)
    if rec.feed_bytes:
        counter("executor_feed_bytes_total",
                "host->device feed transfer bytes").inc(rec.feed_bytes)
    if rec.fetch_bytes:
        counter("executor_fetch_bytes_total",
                "device->host fetch transfer bytes").inc(rec.fetch_bytes)
    if rec.donated_buffers:
        counter("executor_donated_buffers_total",
                "state buffers donated to XLA (updated in place)").inc(
            rec.donated_buffers)
    if rec.kept_buffers:
        counter("executor_kept_buffers_total",
                "state buffers kept/copied (donation-unsafe)").inc(
            rec.kept_buffers)
    if rec.donated_bytes:
        counter("executor_donated_bytes_total",
                "live bytes of donated buffers").inc(rec.donated_bytes)
    dispatch("step_end", rec)


# -- cost model: per-(program, batch) FLOPs -> MFU gauges -------------------
# (analysis/cost_model.py; ROADMAP item 4's accounting — the monitor turns
# measured step durations into model-FLOP utilisation per program and
# shape bucket. Reports are cached: estimation walks the ops once per
# (program version, batch); steady-state steps pay one dict probe.)

_cost_cache: Dict[tuple, Any] = {}
_COST_CACHE_MAX = 64


def program_cost(program, batch: int):
    """The cached ``CostReport`` for ``program`` at ``batch`` rows, or
    ``None`` when estimation failed (never raises into a step)."""
    if not hasattr(program, "blocks"):
        # CompiledProgram wrapper on the parallel path
        program = getattr(program, "program", program)
        if not hasattr(program, "blocks"):
            return None
    key = (int(getattr(program, "_serial", -1)),
           int(getattr(program, "_version", 0)), int(batch))
    if key in _cost_cache:
        return _cost_cache[key]
    try:
        from ..analysis.cost_model import estimate_cost

        rep = estimate_cost(program, batch_size=batch)
    except Exception:
        rep = None
    # unlocked bounded eviction: two step threads can race here, so the
    # pop must tolerate the other thread winning ('never raises into a
    # step' is the contract)
    while len(_cost_cache) >= _COST_CACHE_MAX:
        try:
            _cost_cache.pop(next(iter(_cost_cache)), None)
        except (StopIteration, RuntimeError):
            break
    _cost_cache[key] = rep
    return rep


def observe_step_cost(program, batch: int, duration_s: float,
                      iterations: int = 1, path: str = "run"):
    """Turn one measured dispatch into the cost-model gauges:
    ``executor_model_gflops_per_step`` (static, per program+batch),
    ``executor_achieved_tflops`` and ``executor_mfu`` (per path+program+
    batch, against ``FLAGS_device_peak_tflops``). Returns the achieved
    TF/s, or None when disabled/unmeasurable."""
    if not enabled() or not duration_s or duration_s <= 0:
        return None
    rep = program_cost(program, batch)
    if rep is None or rep.flops_total <= 0:
        return None
    from ..flags import flag

    peak = float(flag("device_peak_tflops"))
    achieved = rep.flops_total * max(1, int(iterations)) / duration_s / 1e12
    labels = {"path": path,
              "program": str(int(getattr(program, "_serial", -1))),
              "batch": str(int(batch))}
    gauge("executor_model_gflops_per_step",
          "cost-model FLOPs of one step (GF, 2 FLOPs/MAC) by program "
          "and batch").labels(program=labels["program"],
                              batch=labels["batch"]).set(
        rep.flops_total / 1e9)
    gauge("executor_achieved_tflops",
          "achieved model TF/s of the most recent dispatch, by path/"
          "program/batch").labels(**labels).set(achieved)
    if peak > 0:
        gauge("executor_mfu",
              "model-FLOP utilisation of the most recent dispatch vs "
              "FLAGS_device_peak_tflops").labels(**labels).set(
            achieved / peak)
    return achieved


def observe_serving_cost(program, padded_rows: int, batch_s: float,
                         bucket: str):
    """Serving flavour of :func:`observe_step_cost`: per shape-bucket
    ``serving_bucket_achieved_tflops`` / ``serving_bucket_mfu`` gauges
    from one dispatched batch's wall time."""
    if not enabled() or not batch_s or batch_s <= 0:
        return None
    rep = program_cost(program, padded_rows)
    if rep is None or rep.flops_total <= 0:
        return None
    from ..flags import flag

    peak = float(flag("device_peak_tflops"))
    achieved = rep.flops_total / batch_s / 1e12
    gauge("serving_bucket_achieved_tflops",
          "achieved model TF/s of the most recent batch, per shape "
          "bucket").labels(bucket=bucket).set(achieved)
    if peak > 0:
        gauge("serving_bucket_mfu",
              "model-FLOP utilisation of the most recent batch vs "
              "FLAGS_device_peak_tflops, per shape bucket").labels(
            bucket=bucket).set(achieved / peak)
    return achieved


def observe_comms_cost(program, comms, cost=None) -> None:
    """Static-sharding comms gauges (analysis.cost_model.estimate_comms):
    ``executor_comms_gbytes_per_step`` — predicted per-chip collective
    wire volume of one step under the compiled sharding assignment — and
    ``executor_comms_compute_ratio`` — predicted wire time over MXU time
    (>1 = communication-bound). Labels carry the program serial and the
    mesh shape so multi-mesh runs stay distinguishable."""
    if not enabled() or comms is None:
        return
    labels = {"program": str(int(getattr(program, "_serial", -1))),
              "mesh": "x".join(f"{k}={v}"
                               for k, v in sorted(comms.mesh.items()))}
    gauge("executor_comms_gbytes_per_step",
          "predicted per-chip collective wire GB of one step under the "
          "static sharding assignment, by program and mesh").labels(
        **labels).set(comms.gbytes_per_step)
    if cost is not None and cost.flops_total > 0:
        from ..analysis.cost_model import comms_compute_ratio

        gauge("executor_comms_compute_ratio",
              "predicted comms-vs-compute time ratio of one step "
              "(>1 = communication-bound), by program and mesh").labels(
            **labels).set(comms_compute_ratio(comms, cost))


def record_watchdog_timeout(section: str) -> None:
    """Account one step-watchdog expiry (resilience.distributed): the
    section name is the armed region (compile / step / chained /
    parallel_step / collective). The dump itself — thread stacks, active
    program serial, last recompile diagnosis — goes to the resilience
    logger and stderr; this records the event on the registry so CI
    artifacts show it (docs/OBSERVABILITY.md)."""
    if not enabled():
        return
    counter("watchdog_timeouts_total",
            "watchdog deadlines that expired (hangs converted to "
            "diagnosed failures)").labels(section=section).inc()


def record_pass(name: str, kind: str, seconds: float,
                cached: bool = False) -> None:
    """Account one IR-pass execution (analysis.pass_manager): run counts by
    pass/kind/result (``cached`` = the PassContext served the analysis from
    its cache) and wall-time histograms for real runs — the per-pass
    timings the ROADMAP item 5 refactor promised (docs/OBSERVABILITY.md)."""
    if not enabled():
        return
    counter("pass_runs_total",
            "IR pass executions by pass, kind and result (result=cached "
            "means the PassContext analysis cache was hit)").labels(
        **{"pass": name, "kind": kind,
           "result": "cached" if cached else "run"}).inc()
    if not cached:
        histogram("pass_duration_seconds",
                  "wall time of one IR pass execution, by pass").labels(
            **{"pass": name}).observe(seconds)


def record_fusion(decision) -> None:
    """Record one FLAGS_epilogue_fusion decision
    (analysis/epilogue_fusion.py FusionDecision): programs transformed vs
    refused, and fused chains by epilogue kind (docs/OBSERVABILITY.md)."""
    if not enabled():
        return
    counter("fusion_programs_total",
            "epilogue-fusion decisions by outcome").labels(
        outcome="applied" if decision.applied else "refused").inc()
    if not decision.applied:
        return
    for c in decision.chains:
        counter("fusion_ops_fused_total",
                "GEMM-epilogue chains rewritten into fused_gemm_epilogue, "
                "by epilogue kind").labels(
            epilogue=c.get("epilogue", "?")).inc()


def record_remat(decision) -> None:
    """Record one FLAGS_auto_recompute decision (analysis/remat.py
    RematDecision): how many programs were transformed vs refused, segments
    inserted, and the planner's predicted peak bytes for the plain and
    remat variants (docs/OBSERVABILITY.md)."""
    if not enabled():
        return
    counter("remat_programs_total",
            "auto-remat decisions by outcome").labels(
        outcome="applied" if decision.applied else "refused").inc()
    if not decision.applied:
        return
    counter("remat_segments_inserted_total",
            "recompute segments inserted by FLAGS_auto_recompute").inc(
        decision.n_segments)
    gauge("remat_predicted_peak_bytes",
          "memory_plan predicted peak of the last transformed program, "
          "by variant").labels(variant="plain").set(decision.peak_before)
    gauge("remat_predicted_peak_bytes",
          "memory_plan predicted peak of the last transformed program, "
          "by variant").labels(variant="remat").set(decision.peak_after)


# -- introspection ---------------------------------------------------------

def recompile_events(recompiles_only: bool = True):
    """Recent compile records (bounded ring; newest last)."""
    return get_tracker().events(recompiles_only=recompiles_only)


def recompile_count(program_serial: Optional[int] = None) -> int:
    return get_tracker().recompile_count(program_serial)


def snapshot() -> dict:
    """One JSON-ready view of everything: metrics + compile/recompile
    events. This is the metrics artifact ``tools/metrics_report.py``
    writes for CI."""
    return {
        "metrics": get_registry().to_dict(),
        "compile_events": [e.to_dict() for e in
                           get_tracker().events()],
        "recompiles_total": get_tracker().recompile_count(),
    }


def reset() -> None:
    """Clear metrics, recompile history and the cost-report cache (hooks
    stay subscribed)."""
    get_registry().reset()
    get_tracker().reset()
    _cost_cache.clear()
