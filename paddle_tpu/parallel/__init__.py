"""Distributed execution: device meshes, data/model parallel compilation.

TPU-native replacement for the reference's ParallelExecutor + NCCL stack
(paddle/fluid/framework/parallel_executor.cc, platform/nccl_helper.h): instead
of an SSA graph with AllReduceOpHandles, programs compile once under jit with
sharding annotations over a jax.sharding.Mesh and XLA inserts the collectives
over ICI/DCN.
"""
from .compiled_program import CompiledProgram, BuildStrategy, ExecutionStrategy  # noqa: F401
