"""CompiledProgram: data-parallel (and later model-parallel) compilation.

Reference: python/paddle/fluid/compiler.py:65 CompiledProgram /
:143 with_data_parallel, which constructs a C++ ParallelExecutor running an
SSA graph with per-gradient NCCL AllReduceOpHandles
(framework/details/all_reduce_op_handle.cc).

TPU-native design: no graph surgery at all. The SAME lowering used by the
single-device Executor is jitted with sharding annotations over a
jax.sharding.Mesh — feeds are sharded along the batch ('dp') axis, parameters
and optimizer state are replicated (or sharded, = the reference's
BuildStrategy.reduce_strategy kReduce / ZeRO), and XLA GSPMD inserts the
gradient all-reduce over ICI automatically. The per-grad AllReduce builder
(multi_devices_graph_pass.cc:454 CreateAllReduceOp) has no equivalent because
the compiler owns collective placement.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import monitor as _monitor
from ..framework import Program, Variable
from ..executor import _feed_host_bytes, _live_bytes, _shape_dtype_sig
from ..lowering import LowerCtx, lower_block
from ..profiler import RecordEvent
from ..resilience import distributed as _dist
from ..resilience import elastic as _elastic
from ..resilience import faults as _faults
from ..resilience import nonfinite as _nonfinite
from ..resilience.retry import call_with_retry

__all__ = ["CompiledProgram", "BuildStrategy", "ExecutionStrategy", "data_parallel_mesh"]


class ReduceStrategy:
    AllReduce = 0  # replicate params, all-reduce grads (default)
    Reduce = 1     # shard optimizer states across devices (ZeRO-1 style)


class BuildStrategy:
    """Knobs carried over from details/build_strategy.h:37 that still mean
    something under XLA; the fusion/memory toggles are compiler-owned now."""

    ReduceStrategy = ReduceStrategy

    def __init__(self):
        self.reduce_strategy = ReduceStrategy.AllReduce
        self.gradient_scale_strategy = 0  # CoeffNumDevice
        self.num_trainers = 1
        self.trainer_id = 0
        self.sync_batch_norm = False


class ExecutionStrategy:
    """Reference execution_strategy.h:22; scheduling knobs are no-ops under
    XLA's static schedule but kept for API parity."""

    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 1
        self.use_experimental_executor = True


def data_parallel_mesh(places=None) -> Mesh:
    if isinstance(places, Mesh):
        return places   # caller brought a full mesh (dp/tp/pp axes)
    devices = np.array(jax.devices() if places is None else places)
    return Mesh(devices, axis_names=("dp",))


def _ensure_global(v, sharding):
    """Promote a process-local array (e.g. fresh from the per-process startup
    run) to a global array on the multi-process mesh. Startup programs run
    identically on every process (same seeds), so replicated promotion is the
    reference's BCastParamsToDevices without the broadcast."""
    if isinstance(v, jax.Array) and not v.is_fully_addressable:
        if v.sharding.is_equivalent_to(sharding, v.ndim):
            return v  # already global with the right layout
        raise RuntimeError(
            f"state array has cross-process sharding {v.sharding} but the "
            f"step expects {sharding}; cannot reshard across processes")
    host = np.asarray(v)
    return jax.make_array_from_callback(host.shape, sharding,
                                        lambda idx: host[idx])


def _fetch_numpy(v) -> np.ndarray:
    """np.asarray for fetches that works when the array spans processes:
    fetch out_shardings are replicated, so shard 0 holds the full value."""
    if isinstance(v, jax.Array) and not v.is_fully_addressable:
        return np.asarray(v.addressable_data(0))
    return np.asarray(v)


class CompiledProgram:
    def __init__(self, program: Program, build_strategy: Optional[BuildStrategy] = None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = ExecutionStrategy()
        self._loss_name: Optional[str] = None
        self._mesh: Optional[Mesh] = None
        self._is_data_parallel = False
        self._cache: Dict[tuple, Any] = {}
        # same contract as Executor._lock: the step cache must survive
        # concurrent dispatch threads (serving) without forking duplicate
        # compiles for one key
        self._cache_lock = _monitor.make_rlock("CompiledProgram._cache_lock")

    @property
    def program(self) -> Program:
        return self._program

    def with_data_parallel(self, loss_name: Optional[str] = None,
                           build_strategy: Optional[BuildStrategy] = None,
                           exec_strategy: Optional[ExecutionStrategy] = None,
                           places=None) -> "CompiledProgram":
        self._is_data_parallel = True
        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        if exec_strategy is not None:
            self._exec_strategy = exec_strategy
        self._mesh = data_parallel_mesh(places)
        return self

    def rescale(self, places) -> "CompiledProgram":
        """Elastic recovery (resilience.elastic): tear down every compiled
        step — the executables were built with shardings over the OLD
        mesh and must never dispatch onto the new one — and re-form the
        mesh on ``places`` (a device list or a ready Mesh). State in the
        scope re-shards lazily: the next dispatch's ``in_shardings``
        place it onto the new mesh (the same mechanism the PR 6 elastic
        restore relies on). The replica-divergence sweep counter resets
        with the mesh so the first post-rescale interval is a full one."""
        with self._cache_lock:
            self._cache.clear()
            self._mesh = data_parallel_mesh(places)
            self._replica_steps = 0
        return self

    # -- execution (called by Executor.run) ------------------------------
    def _run(self, exe, feed, fetch_list, scope, return_numpy):
        from ..executor import global_scope

        scope = scope or global_scope()
        feed = feed or {}
        fetch_names = [f.name if isinstance(f, Variable) else f
                       for f in (fetch_list or [])]
        # FLAGS_auto_recompute: the data-parallel path shares the executor's
        # remat cache; the transformed program's fresh _serial keys this
        # CompiledProgram's own step cache apart from the plain variant
        program = exe._maybe_auto_remat(self._program, feed, fetch_names)
        mrec = _monitor.step_begin("parallel", program)
        from .. import trace as _trace

        with _trace.span("executor.parallel_step",
                         program=int(getattr(program, "_serial", -1)),
                         mesh=str(dict(self._mesh.shape))
                         if self._mesh is not None else ""):
            try:
                # classification wraps the WHOLE dispatch, not just the jit
                # call: with async dispatch (watchdog unarmed) a real device
                # loss only surfaces when a result is read — at
                # unpack_step_result or the return_numpy materialization —
                # and must still come out typed (resilience.elastic)
                with _elastic.device_loss_classification("parallel_step"):
                    return self._run_body(exe, program, feed, fetch_names,
                                          scope, return_numpy, mrec)
            finally:
                # paired with step_begin even when the step raises
                _monitor.step_end(mrec)

    def _run_body(self, exe, program, feed, fetch_names, scope,
                  return_numpy, mrec):
        if mrec is not None:
            mrec.fetch_names = tuple(fetch_names)
        step = self._get_compiled(exe, program, feed, fetch_names, scope,
                                  mrec=mrec)
        if mrec is not None:
            from ..executor import _feed_batch_rows

            mrec.feed_bytes = sum(_feed_host_bytes(v)
                                  for v in feed.values())
            mrec.batch_rows = _feed_batch_rows(feed)
        multiproc = jax.process_count() > 1
        batch_shard = NamedSharding(
            self._mesh, P("dp") if "dp" in self._mesh.axis_names else P())
        repl = NamedSharding(self._mesh, P())
        state_shardings = getattr(step, "state_shardings", {})
        def _pack_feed(n):
            def _put():
                # device_put fault site + transient retry (host->device)
                _faults.fault_point("device_put")
                if multiproc:
                    # each trainer feeds its LOCAL batch shard; together
                    # they form the global batch (the reference's
                    # FeedAndSplitTensorIntoLocalScopes,
                    # parallel_executor.cc:75, inverted: feeds are split
                    # before the call, not inside it)
                    return jax.make_array_from_process_local_data(
                        batch_shard, np.asarray(feed[n]))
                return jnp.asarray(np.asarray(feed[n]))
            return call_with_retry("device_put", _put)

        feed_vals = [_pack_feed(n) for n in step.feed_names]

        def read(names):
            vals = []
            for n in names:
                v = scope.find_var(n)
                if v is None:
                    raise RuntimeError(f"Variable '{n}' not initialized in scope")
                if multiproc:
                    v = _ensure_global(v, state_shardings.get(n, repl))
                vals.append(v)
            return vals

        key = jax.random.key(exe._next_seed(program))
        donated_vals = read(step.donated_names)
        # step-site fault probe fires BEFORE donation, scope stays usable
        _faults.fault_point("step")
        rollback = None
        if step.nan_check_meta is not None and _nonfinite.rollback_active():
            if all(getattr(v, "is_fully_addressable", True)
                   for v in donated_vals):
                # host-side pre-step image: a device-side copy would lose
                # the mesh sharding; the restore re-shards on the next
                # step's read(). MUST be an owned copy — np.asarray of a
                # CPU-backend jax array can be a zero-copy VIEW of the
                # device buffer, and that buffer is donated below: XLA
                # would write the post-step (possibly non-finite) values
                # straight through the "pre-step" image
                rollback = [(n, np.array(v, copy=True))
                            for n, v in zip(step.donated_names,
                                            donated_vals)]
            # multi-process global arrays cannot be host-imaged here; the
            # policy degrades to raise for this dispatch
        if mrec is not None:
            mrec.donated_buffers = len(step.donated_names)
            mrec.kept_buffers = len(step.kept_names)
            mrec.donated_bytes = _live_bytes(donated_vals)
        # the parallel dispatch IS the collective section: a stuck ICI
        # collective here used to hang CI forever; under
        # FLAGS_step_timeout_s the watchdog dumps + raises instead
        # the classification at the _run boundary turns the jax/XLA
        # error zoo anywhere in this dispatch into typed DeviceLostError
        # (transient=False — retry never absorbs a dead chip) so
        # contrib.Trainer's elastic recovery can act on it
        with RecordEvent("executor::parallel_step"), \
                _dist.watchdog_section("parallel_step",
                                       program=program) as tok:
            # device_lost probe (resilience.elastic): fires BEFORE the
            # dispatch donates anything, like a preemption notice racing
            # the step; the classifier treats injected and real losses
            # identically
            _faults.fault_point("device_lost")
            _faults.fault_point("hang")
            result = step.fn(feed_vals, donated_vals,
                             read(step.ro_names), key)
            if tok is not None:
                # async dispatch: a wedged collective only blocks at the
                # first result read — keep the section armed through it
                jax.block_until_ready(result)
        from ..executor import unpack_step_result

        fetches, new_state = unpack_step_result(step, result, scope,
                                                to_host=_fetch_numpy,
                                                path="parallel", exe=exe,
                                                rollback=rollback)
        if new_state is not None:
            for n, v in zip(step.state_out_names, new_state):
                scope.set_var(n, v)
        self._maybe_check_replicas(step, scope)
        if return_numpy:
            outs = [_fetch_numpy(v) for v in fetches]
            if mrec is not None:
                mrec.fetch_bytes = _live_bytes(outs)
            return outs
        return list(fetches)

    def _maybe_check_replicas(self, step, scope):
        """FLAGS_replica_check_interval: every N-th parallel step, verify
        that state replicated over the dp axis still holds identical bytes
        on every replica (resilience.distributed — a jitted per-device
        checksum reduce, no host gather of tensors). Disagreement is
        handled by FLAGS_replica_divergence_policy."""
        from ..flags import flag

        interval = int(flag("replica_check_interval"))
        mesh = self._mesh
        if interval <= 0 or mesh is None \
                or mesh.shape.get("dp", 1) <= 1:
            return
        self._replica_steps = getattr(self, "_replica_steps", 0) + 1
        if self._replica_steps % interval:
            return
        values = {}
        for n in step.state_out_names:
            v = scope.find_var(n)
            if not isinstance(v, jax.Array):
                continue
            if getattr(v.sharding, "mesh", None) != mesh:
                continue
            values[n] = v
        if not values:
            return
        if _monitor.enabled():
            _monitor.counter(
                "resilience_divergence_checks_total",
                "cross-replica consistency sweeps run").inc()
        # axis=None: compare across EVERY axis a var is replicated over
        # (on a dp x tp mesh that covers both replica directions)
        diverged = _dist.replica_divergence_check(mesh, values)
        if diverged:
            _dist.handle_divergence(diverged, path="parallel", axis="dp")

    def _get_compiled(self, exe, program, feed, fetch_names, scope,
                      mrec=None):
        feed_sig = tuple(sorted(
            (n,) + _shape_dtype_sig(v) for n, v in feed.items()
        ))
        from ..flags import flag, xla_options

        xla_opts = tuple(sorted(xla_options().items()))
        key = (exe._program_fingerprint(program), feed_sig,
               tuple(fetch_names), flag("check_nan_inf"), xla_opts)
        with self._cache_lock:
            hit = key in self._cache
            _monitor.record_cache_lookup("parallel", hit)
            if mrec is not None:
                mrec.cache_hit = hit
            if hit:
                return self._cache[key]

        # compile-site fault probe + transient retry (the actual XLA
        # compile happens lazily at first dispatch on this path; the
        # probe models the build pipeline's transient failures). Only
        # the probe is retried: a real build failure must surface its
        # ORIGINAL diagnostic immediately, exactly like the
        # single-device path. OUTSIDE the cache lock: retry backoff can
        # sleep for seconds, and concurrent cache HITS must not queue
        # behind it
        call_with_retry("compile", _faults.fault_point, "compile")
        with self._cache_lock:
            step = self._cache.get(key)
            if step is not None:
                # a racing thread built it while we were probing
                return step
            with RecordEvent("executor::build_step"), \
                    _dist.watchdog_section("compile", program=program):
                step = self._compile(program, set(feed.keys()), fetch_names,
                                     scope)
            step.program = program
            # the data-parallel path keeps jit dispatch (shardings make the
            # AOT fast path fiddly across process topologies), so the
            # compile event completes here without stage timings
            _monitor.complete_compile(_monitor.observe_compile(
                "parallel", program,
                components={
                    "program": exe._program_fingerprint(program)[1:],
                    "feed_signature": feed_sig,
                    "fetch_list": tuple(fetch_names),
                    "flags": (("check_nan_inf", flag("check_nan_inf")),),
                    "xla_options": xla_opts,
                },
                donated_names=step.donated_names), None, None)
            self._cache[key] = step
        # outside the cache lock: a pure-metadata walk, but no reason to
        # queue concurrent cache hits behind it
        self._observe_static_sharding(program, fetch_names, feed)
        return step

    def _observe_static_sharding(self, program, fetch_names, feed) -> None:
        """Predicted per-chip collective volume + comms-vs-compute gauges
        for the layout this compile just fixed (analysis.sharding_check
        over the same zero1_spec_for rule the executable was built with).
        Advisory: never raises into a step."""
        if not _monitor.enabled() or self._mesh is None:
            return
        try:
            from ..analysis.cost_model import estimate_comms, estimate_cost
            from ..analysis.sharding_check import propagate_sharding
            from ..executor import _feed_batch_rows
            from .sharding import extract_param_specs

            mesh_shape = {str(k): int(v)
                          for k, v in dict(self._mesh.shape).items()}
            zero = (self._build_strategy.reduce_strategy
                    == ReduceStrategy.Reduce)
            specs, feed_spec = extract_param_specs(program, mesh_shape,
                                                   zero=zero)
            batch = _feed_batch_rows(feed) or 1
            analysis = propagate_sharding(
                program, mesh_shape, param_specs=specs,
                feed_spec=feed_spec, feed_names=list(feed.keys()),
                fetch_names=fetch_names, batch_size=batch)
            _monitor.observe_comms_cost(
                program, estimate_comms(analysis),
                estimate_cost(program, batch_size=batch))
        except Exception:
            pass

    def _compile(self, program: Program, feed_names: set, fetch_names, scope):
        """Same env-threading as Executor._compile, but jitted with shardings
        over the mesh: feeds split on 'dp', state replicated."""
        from ..executor import _CompiledStep, analyze_block_io, pick_step_fn

        from ..flags import flag, xla_options

        block = program.global_block
        io = analyze_block_io(block, feed_names, fetch_names)
        mesh = self._mesh
        nan_meta = [] if flag("check_nan_inf") else None
        step_fn = pick_step_fn(program)(block, io, fetch_names, mesh=mesh,
                                        nan_check_meta=nan_meta)

        batch_spec = NamedSharding(
            mesh, P("dp") if "dp" in mesh.axis_names else P())
        repl_spec = NamedSharding(mesh, P())

        # ZeRO-1 (BuildStrategy.ReduceStrategy.Reduce, ref build_strategy.h:58
        # kReduce / multi_devices_graph_pass.h:157 ReduceSSAGraphBuilder):
        # optimizer-state vars are sharded over the dp axis on dim 0. GSPMD
        # then partitions the update elementwise — grads reach each shard as
        # a reduce-scatter and fresh params are all-gathered, which is exactly
        # the reduce+broadcast the reference builder inserts by hand.
        zero1 = self._build_strategy.reduce_strategy == ReduceStrategy.Reduce
        dp = mesh.shape.get("dp", 1)

        def state_sharding(name):
            # the metadata rule is shared with the static sharding_check
            # pass (parallel/sharding.py), so the layout the analysis
            # reasons about IS the one this executable runs
            from .sharding import zero1_spec_for

            v = block.var(name) if block.has_var(name) else None
            spec = zero1_spec_for(v, dp, zero1)
            if not spec:
                return repl_spec
            return NamedSharding(mesh, P(*spec))

        state_shardings = {n: state_sharding(n)
                           for n in set(io["state_in"]) | set(io["state_out"])}
        in_shardings = (
            [batch_spec] * len(io["feed_order"]),
            [state_shardings[n] for n in io["donated"]],
            [state_shardings[n] for n in io["ro"]],
            None,
        )
        # fetches pinned replicated so multi-process fetch reads one
        # addressable shard; state keeps its (possibly dp-sharded) layout so
        # it stays valid as a next-step input
        out_shardings = (
            [repl_spec] * len(fetch_names),
            [state_shardings[n] for n in io["state_out"]],
        )
        if nan_meta is not None:
            out_shardings = out_shardings + (repl_spec,)
        jitted = jax.jit(step_fn, donate_argnums=(1,),
                         in_shardings=in_shardings,
                         out_shardings=out_shardings,
                         compiler_options=xla_options() or None)
        step = _CompiledStep(jitted, io["feed_order"], io["donated"],
                             io["ro"], io["state_out"], tuple(fetch_names))
        step.kept_names = [n for n in io["ro"] if n in io["state_out"]]
        step.state_shardings = state_shardings
        step.nan_check_meta = nan_meta
        return step
