"""Ring attention: sequence/context parallelism over a mesh axis.

The reference (2019) has NO long-context story beyond LoD packing
(SURVEY §5); this is the capability-parity-PLUS item the TPU rebuild adds:
attention over sequences sharded across chips, K/V blocks rotating around
the ICI ring (`jax.lax.ppermute` lowers to collective-permute on TPU; the
same code runs on the CPU test mesh), with flash-style ONLINE softmax —
running max + denominator — so no chip ever materialises the full
[T, T] score matrix or the gathered K/V. Memory per chip is O(T_local),
enabling sequences P times longer than single-chip attention.

Layout: q/k/v are [batch, seq, heads, head_dim] sharded on `seq` over the
ring axis. Causal masking uses GLOBAL positions reconstructed from the
ring step, so results equal single-device causal attention exactly.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .sharding import axis_size, shard_map_compat

__all__ = ["ring_attention", "ring_attention_local", "attention_reference"]


def ring_attention_local(q, k, v, axis_name: str, causal: bool = False,
                         scale: Optional[float] = None,
                         use_flash: Optional[bool] = None):
    """The per-shard body — call inside shard_map over ``axis_name``.

    q, k, v: [B, T_local, H, D] local chunks. Returns [B, T_local, H, D].

    ``use_flash`` routes the per-block attention through the Pallas flash
    kernel (kernels/flash_attention.py) — the same kernel as
    fused_multihead_attention — combining ring steps through each block's
    log-sum-exp instead of carrying (m, l) explicitly. None = auto: kernel
    on TPU when the local block shapes divide its tiles, jnp math
    elsewhere (the CPU test mesh keeps the einsum path — Pallas interpret
    inside shard_map is slow and PRNG-free anyway).
    """
    if use_flash is None:
        import jax as _jax

        from ..kernels import supports_shapes

        use_flash = (_jax.default_backend() == "tpu"
                     and supports_shapes(q.shape[1], k.shape[1]))
    if use_flash:
        return _ring_attention_local_flash(q, k, v, axis_name, causal, scale)
    return _ring_attention_local_jnp(q, k, v, axis_name, causal, scale)


def _ring_attention_local_flash(q, k, v, axis_name: str, causal: bool,
                                scale: Optional[float]):
    """Ring body where each block product is one flash-kernel call.

    Blocks combine by log-sum-exp re-weighting: for partials (o_a, lse_a)
    and (o_b, lse_b) over disjoint key sets, lse = logaddexp and
    o = o_a*exp(lse_a-lse) + o_b*exp(lse_b-lse). The kernel honours the
    lse cotangent, so jax.grad through the whole ring is exact."""
    from ..kernels import flash_attention_with_lse

    B, Tl, H, D = q.shape
    P_ = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    perm = [(i, (i + 1) % P_) for i in range(P_)]
    # forcing the flash path on the CPU test mesh runs the kernel in the
    # pallas interpreter (slow, tests only); compiled Mosaic on TPU
    interpret = jax.default_backend() != "tpu"

    # kernel layout is [B*H, T, D] head-major; transpose ALL of q/k/v once
    # up front and rotate k/v around the ring already head-major (ppermute
    # is layout-agnostic), so no per-step transpose copies
    def to_bh(t):
        return t.transpose(0, 2, 1, 3).reshape(B * H, Tl, D)

    qh, k, v = to_bh(q), to_bh(k), to_bh(v)

    def block(kb, vb, s):
        src = (my - s) % P_                      # owner of this k/v block
        o_s, lse_s = flash_attention_with_lse(
            qh, kb, vb, causal=causal, scale=scale,
            q_offset=my * Tl, k_offset=src * Tl, num_heads=H,
            interpret=interpret)
        return o_s, lse_s

    def combine(o, lse, o_s, lse_s):
        lse_new = jnp.logaddexp(lse, lse_s)
        # fully-masked-so-far rows: lse == lse_new == -inf -> weight 0
        w = jnp.where(jnp.isfinite(lse), jnp.exp(lse - lse_new), 0.0)
        w_s = jnp.where(jnp.isfinite(lse_s), jnp.exp(lse_s - lse_new), 0.0)
        o_new = o * w[..., None] + o_s * w_s[..., None]
        return o_new, lse_new

    o0, lse0 = block(k, v, 0)
    kb = jax.lax.ppermute(k, axis_name, perm)
    vb = jax.lax.ppermute(v, axis_name, perm)

    def step(carry, s):
        o, lse, kb, vb = carry
        o_s, lse_s = block(kb, vb, s)
        o, lse = combine(o, lse, o_s, lse_s)
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return (o, lse, kb, vb), None

    if P_ > 2:
        (o, lse, kb, vb), _ = jax.lax.scan(
            step, (o0, lse0, kb, vb), jnp.arange(1, P_ - 1))
    else:
        o, lse = o0, lse0
    if P_ > 1:
        o_s, lse_s = block(kb, vb, P_ - 1)     # last block: no dead permute
        o, lse = combine(o, lse, o_s, lse_s)
    return o.reshape(B, H, Tl, D).transpose(0, 2, 1, 3)


def _ring_attention_local_jnp(q, k, v, axis_name: str, causal: bool = False,
                              scale: Optional[float] = None):
    """Einsum ring body (runs anywhere, incl. the 8-device CPU test mesh)."""
    B, Tl, H, D = q.shape
    P_ = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    q = q * scale

    neg = jnp.asarray(jnp.finfo(q.dtype).min, q.dtype)
    # accumulators derive from q so they inherit its varying-axes type on
    # ANY mesh (shard_map vma tracking: a fresh jnp.zeros would be
    # unvaried and mismatch the scan carry after the ppermute)
    zero_qh = q.sum(axis=-1) * 0.0                     # [B, Tl, H]
    m0 = zero_qh + neg                                 # running max
    l0 = zero_qh                                       # running denom
    o0 = q * 0.0                                       # numerator acc
    perm = [(i, (i + 1) % P_) for i in range(P_)]

    q_pos = my * Tl + jnp.arange(Tl)                   # global q positions

    def block_update(m, l, o, kb, vb, s):
        src = (my - s) % P_                            # owner of this block
        k_pos = src * Tl + jnp.arange(Tl)
        # scores: [B, Tl(q), H, Tl(k)]
        scores = jnp.einsum("bqhd,bkhd->bqhk", q, kb)
        valid = jnp.ones((Tl, Tl), bool)
        if causal:
            valid = q_pos[:, None] >= k_pos[None, :]   # [Tq, Tk] global
            scores = jnp.where(valid[None, :, None, :], scores, neg)
        blk_max = scores.max(axis=-1)                  # [B, Tq, H]
        m_new = jnp.maximum(m, blk_max)
        # fully-masked rows keep m == neg; their corr/p must be 0 or
        # exp(neg - neg)=1 would average masked-out values in
        alive = m_new > neg
        corr = jnp.where(alive, jnp.exp(m - m_new), 0.0)
        p = jnp.exp(scores - m_new[..., None])
        p = p * (valid[None, :, None, :] & alive[..., None])
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr[..., None] + jnp.einsum("bqhk,bkhd->bqhd", p, vb)
        return m_new, l_new, o_new

    def step(carry, s):
        m, l, o, kb, vb = carry
        m, l, o = block_update(m, l, o, kb, vb, s)
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return (m, l, o, kb, vb), None

    # scan P-1 rotating steps, then peel the LAST block without the two
    # dead trailing ppermutes (the rotated K/V would be discarded)
    (m, l, o, kb, vb), _ = jax.lax.scan(step, (m0, l0, o0, k, v),
                                        jnp.arange(P_ - 1))
    m, l, o = block_update(m, l, o, kb, vb, P_ - 1)
    return o / jnp.maximum(l, 1e-20)[..., None]


def ring_attention(q, k, v, mesh: Mesh, seq_axis: str = "sp",
                   causal: bool = False, scale: Optional[float] = None,
                   use_flash: Optional[bool] = None):
    """shard_map wrapper: q/k/v [B, T, H, D] (global); T shards over
    ``seq_axis``, batch over 'dp' when the mesh has one."""
    batch_axis = "dp" if "dp" in mesh.axis_names else None
    spec = P(batch_axis, seq_axis, None, None)

    if use_flash is None:
        from ..kernels import supports_shapes

        n_sp = mesh.shape[seq_axis]
        t_local = q.shape[1] // n_sp
        use_flash = (jax.default_backend() == "tpu"
                     and supports_shapes(t_local, t_local))
    # check_vma=False on the flash path: the kernel's scalar operands
    # (global position offsets) legitimately vary over the ring axis, which
    # the vma checker's pallas handling rejects
    fn = shard_map_compat(
        partial(ring_attention_local, axis_name=seq_axis, causal=causal,
                scale=scale, use_flash=use_flash),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=not use_flash)
    # eager dispatches ride the ICI ring (P ppermute rotations) — the one
    # collective in the stack with no deadline until now; armed so a stuck
    # permute is dumped + raised under FLAGS_step_timeout_s (inside a jit
    # trace this wraps only host-side trace work and disarms immediately)
    from ..resilience.distributed import (block_until_ready_concrete,
                                          watchdog_section)

    from ..resilience.elastic import device_loss_classification

    # a dead ring rank surfaces here as an untyped runtime error — the
    # shared wrapper classifies it typed so the elastic path can act
    with watchdog_section("collective",
                          detail=f"ring_attention over '{seq_axis}'") \
            as tok, device_loss_classification("collective"):
        out = fn(q, k, v)
        if tok is not None:
            # async dispatch: arm through device completion (no-op when
            # called inside a jit trace; real runtime errors propagate)
            block_until_ready_concrete(out)
        return out


def attention_reference(q, k, v, causal: bool = False,
                        scale: Optional[float] = None):
    """Dense single-device attention (the correctness oracle)."""
    D = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bqhk", q * scale, k)
    if causal:
        T = q.shape[1]
        mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
        scores = jnp.where(mask[None, :, None, :], scores,
                           jnp.finfo(q.dtype).min)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bqhk,bkhd->bqhd", p, v)
