"""Ring attention: sequence/context parallelism over a mesh axis.

The reference (2019) has NO long-context story beyond LoD packing
(SURVEY §5); this is the capability-parity-PLUS item the TPU rebuild adds:
attention over sequences sharded across chips, K/V blocks rotating around
the ICI ring (`jax.lax.ppermute` lowers to collective-permute on TPU; the
same code runs on the CPU test mesh), with flash-style ONLINE softmax —
running max + denominator — so no chip ever materialises the full
[T, T] score matrix or the gathered K/V. Memory per chip is O(T_local),
enabling sequences P times longer than single-chip attention.

Layout: q/k/v are [batch, seq, heads, head_dim] sharded on `seq` over the
ring axis. Causal masking uses GLOBAL positions reconstructed from the
ring step, so results equal single-device causal attention exactly.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["ring_attention", "ring_attention_local", "attention_reference"]


def ring_attention_local(q, k, v, axis_name: str, causal: bool = False,
                         scale: Optional[float] = None):
    """The per-shard body — call inside shard_map over ``axis_name``.

    q, k, v: [B, T_local, H, D] local chunks. Returns [B, T_local, H, D].
    """
    B, Tl, H, D = q.shape
    P_ = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    q = q * scale

    neg = jnp.asarray(jnp.finfo(q.dtype).min, q.dtype)
    # accumulators derive from q so they inherit its varying-axes type on
    # ANY mesh (shard_map vma tracking: a fresh jnp.zeros would be
    # unvaried and mismatch the scan carry after the ppermute)
    zero_qh = q.sum(axis=-1) * 0.0                     # [B, Tl, H]
    m0 = zero_qh + neg                                 # running max
    l0 = zero_qh                                       # running denom
    o0 = q * 0.0                                       # numerator acc
    perm = [(i, (i + 1) % P_) for i in range(P_)]

    q_pos = my * Tl + jnp.arange(Tl)                   # global q positions

    def block_update(m, l, o, kb, vb, s):
        src = (my - s) % P_                            # owner of this block
        k_pos = src * Tl + jnp.arange(Tl)
        # scores: [B, Tl(q), H, Tl(k)]
        scores = jnp.einsum("bqhd,bkhd->bqhk", q, kb)
        valid = jnp.ones((Tl, Tl), bool)
        if causal:
            valid = q_pos[:, None] >= k_pos[None, :]   # [Tq, Tk] global
            scores = jnp.where(valid[None, :, None, :], scores, neg)
        blk_max = scores.max(axis=-1)                  # [B, Tq, H]
        m_new = jnp.maximum(m, blk_max)
        # fully-masked rows keep m == neg; their corr/p must be 0 or
        # exp(neg - neg)=1 would average masked-out values in
        alive = m_new > neg
        corr = jnp.where(alive, jnp.exp(m - m_new), 0.0)
        p = jnp.exp(scores - m_new[..., None])
        p = p * (valid[None, :, None, :] & alive[..., None])
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr[..., None] + jnp.einsum("bqhk,bkhd->bqhd", p, vb)
        return m_new, l_new, o_new

    def step(carry, s):
        m, l, o, kb, vb = carry
        m, l, o = block_update(m, l, o, kb, vb, s)
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return (m, l, o, kb, vb), None

    # scan P-1 rotating steps, then peel the LAST block without the two
    # dead trailing ppermutes (the rotated K/V would be discarded)
    (m, l, o, kb, vb), _ = jax.lax.scan(step, (m0, l0, o0, k, v),
                                        jnp.arange(P_ - 1))
    m, l, o = block_update(m, l, o, kb, vb, P_ - 1)
    return o / jnp.maximum(l, 1e-20)[..., None]


def ring_attention(q, k, v, mesh: Mesh, seq_axis: str = "sp",
                   causal: bool = False, scale: Optional[float] = None):
    """shard_map wrapper: q/k/v [B, T, H, D] (global); T shards over
    ``seq_axis``, batch over 'dp' when the mesh has one."""
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    batch_axis = "dp" if "dp" in mesh.axis_names else None
    spec = P(batch_axis, seq_axis, None, None)

    fn = shard_map(
        partial(ring_attention_local, axis_name=seq_axis, causal=causal,
                scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def attention_reference(q, k, v, causal: bool = False,
                        scale: Optional[float] = None):
    """Dense single-device attention (the correctness oracle)."""
    D = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bqhk", q * scale, k)
    if causal:
        T = q.shape[1]
        mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
        scores = jnp.where(mask[None, :, None, :], scores,
                           jnp.finfo(q.dtype).min)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bqhk,bkhd->bqhd", p, v)
