"""Sharding rules: map program vars onto a device mesh.

TPU-native replacement for the reference's multi-device graph builders
(ir/multi_devices_graph_pass/) and BuildStrategy reduce strategies: instead of
rewriting the graph with per-grad AllReduce handles, we attach a
PartitionSpec to each var and jit once — XLA GSPMD partitions the whole step
and places the collectives (grad all-reduce over 'dp', activation collectives
over 'tp') on ICI.

``ShardingRules`` is name-pattern based so model code stays sharding-agnostic
(the reference reached the same decoupling via transpiler passes).
"""
from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``shard_map`` across the jax API rename: newer jax spells the
    replication-check kwarg ``check_vma``, 0.4.x spells it ``check_rep``
    (same semantics). Callers use the new spelling; this maps it to
    whichever the installed jax accepts."""
    import inspect

    try:
        from jax import shard_map as _sm
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map as _sm
    params = inspect.signature(_sm).parameters
    kw = "check_vma" if "check_vma" in params else "check_rep"
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               **{kw: check_vma})


def axis_size(axis_name: str):
    """``jax.lax.axis_size`` where it exists; the ``psum(1, axis)``
    idiom (folded to a constant at trace time) on 0.4.x."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def has_varying_types() -> bool:
    """Does the installed jax type values as varying-over-axis inside
    shard_map (``pcast``/``pvary``)? 0.4.x has neither — callers that
    need a varying scan carry disable the replication check instead."""
    return hasattr(jax.lax, "pcast") or hasattr(jax.lax, "pvary")


def pvary_compat(t, axis_name: str):
    """Type ``t`` as varying over ``axis_name`` inside shard_map, across
    the jax API generations (``pcast(to="varying")`` / ``pvary``); a
    no-op on 0.4.x, where the caller must pass ``check_vma=False``."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(t, (axis_name,), to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(t, (axis_name,))
    return t


def make_mesh(shape: Dict[str, int], devices=None) -> Mesh:
    """mesh({'dp': 2, 'tp': 4}) over the first prod(shape) devices.
    Axis order follows dict order; put the fastest-varying (intra-chip ICI
    neighbour) axis last — that is where tp belongs."""
    devices = list(devices if devices is not None else jax.devices())
    n = int(np.prod(list(shape.values())))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(tuple(shape.values()))
    return Mesh(arr, axis_names=tuple(shape.keys()))


class ShardingRules:
    """Ordered (regex, PartitionSpec) rules for params + batch axis for feeds."""

    def __init__(self, param_rules: Sequence[Tuple[str, P]] = (),
                 feed_spec: P = P("dp"), default: P = P()):
        self.param_rules = [(re.compile(pat), spec) for pat, spec in param_rules]
        self.feed_spec = feed_spec
        self.default = default

    def spec_for_param(self, name: str, shape=None) -> P:
        for pat, spec in self.param_rules:
            if pat.search(name):
                return spec
        return self.default

    def sharding_for_param(self, mesh: Mesh, name: str, shape=None):
        # pipeline-stacked params (layers.PipelineRegion) always place one
        # stage slice per 'pp' rank — their leading dim IS the stage axis.
        # This also covers their optimizer accumulators, whose names embed
        # the param name.
        if ".pp_stacked" in name and "pp" in mesh.axis_names:
            return NamedSharding(mesh, P("pp"))
        return NamedSharding(mesh, self.spec_for_param(name, shape))

    def sharding_for_feed(self, mesh: Mesh):
        return NamedSharding(mesh, self.feed_spec)


# Megatron-style tensor-parallel rules for the BERT/transformer family:
# column-parallel QKV/FFN-in (shard output dim), row-parallel out/FFN-out
# (shard input dim), vocab-sharded embedding. Everything else replicated.
def transformer_tp_rules() -> ShardingRules:
    return ShardingRules(param_rules=[
        (r"_(q|k|v|ffn1)_w$", P(None, "tp")),
        (r"_(q|k|v|ffn1)_b$", P("tp")),
        (r"_(out|ffn2)_w$", P("tp", None)),
        (r"word_embedding$", P("tp", None)),
    ], feed_spec=P("dp"))


def compile_sharded_step(program, mesh: Mesh, feed_names: Sequence[str],
                         fetch_names: Sequence[str],
                         rules: Optional[ShardingRules] = None,
                         donate: bool = True):
    """Jit the program's global block over ``mesh`` with rule-derived
    in/out shardings. Returns (jitted_fn, io) where io describes arg order
    (see executor.analyze_block_io)."""
    from ..executor import analyze_block_io, make_step_fn
    from ..flags import flag

    rules = rules or ShardingRules()
    block = program.global_block
    io = analyze_block_io(block, set(feed_names), fetch_names)
    nan_meta = [] if flag("check_nan_inf") else None
    step_fn = make_step_fn(block, io, fetch_names, mesh=mesh,
                           nan_check_meta=nan_meta)

    def state_shard(name):
        return rules.sharding_for_param(mesh, name)

    feed_shard = rules.sharding_for_feed(mesh)
    in_shardings = (
        [feed_shard] * len(io["feed_order"]),
        [state_shard(n) for n in io["donated"]],
        [state_shard(n) for n in io["ro"]],
        None,
    )
    # outputs: fetches replicated; state keeps its input sharding
    out_shardings = (
        [NamedSharding(mesh, P())] * len(fetch_names),
        [state_shard(n) for n in io["state_out"]],
    )
    if nan_meta is not None:
        out_shardings = out_shardings + (NamedSharding(mesh, P()),)
    jitted = jax.jit(step_fn, in_shardings=in_shardings,
                     out_shardings=out_shardings,
                     donate_argnums=(1,) if donate else ())
    io["nan_check_meta"] = nan_meta
    return jitted, io


# ---------------------------------------------------------------------------
# static spec extraction (consumed by analysis.sharding_check and shared
# with CompiledProgram._compile so the static layout IS the runtime layout)
# ---------------------------------------------------------------------------

def zero1_spec_for(v, dp: int, zero1: bool) -> tuple:
    """Pure-metadata twin of CompiledProgram's ``state_sharding`` rule:
    the PartitionSpec-like tuple (one axis name or None per dim) a state
    var gets on a dp mesh. ``()`` = replicated. Sharded embedding tables
    (``is_distributed``) row-shard regardless of the reduce strategy;
    optimizer-state vars row-shard under ZeRO-1
    (``BuildStrategy.ReduceStrategy.Reduce``)."""
    if dp <= 1:
        return ()
    if v is None or not v.shape or len(v.shape) < 1 \
            or v.shape[0] < dp or v.shape[0] % dp:
        return ()
    if getattr(v, "is_distributed", False):
        return ("dp",)
    if zero1 and getattr(v, "is_optimizer_state", False):
        return ("dp",)
    return ()


def extract_param_specs(program, mesh_shape: Dict[str, int],
                        build_strategy=None, zero: bool = False,
                        rules: Optional[ShardingRules] = None
                        ) -> Tuple[Dict[str, tuple], tuple]:
    """Derive the per-param spec assignment a ``BuildStrategy`` implies,
    as plain metadata (no devices touched): the input to
    ``analysis.sharding_check`` and ``Program.memory_plan(mesh=...)``.

    Returns ``(param_specs, feed_spec)`` — ``param_specs`` maps var name
    to a spec tuple (only sharded vars listed), ``feed_spec`` is the
    batch-axis spec for feeds. ``zero=True`` (or a build_strategy with
    ``ReduceStrategy.Reduce``) applies the ZeRO-1 optimizer-state layout;
    ``rules`` layers name-pattern tensor-parallel specs on top (the
    ``ShardingRules`` the tp path uses)."""
    dp = int(mesh_shape.get("dp", 1))
    if build_strategy is not None:
        zero = zero or getattr(build_strategy, "reduce_strategy", 0) == 1
    specs: Dict[str, tuple] = {}
    for blk in program.blocks:
        for v in blk.vars.values():
            if not v.persistable or v.is_data:
                continue
            spec: tuple = ()
            if rules is not None:
                p = rules.spec_for_param(v.name, v.shape)
                spec = tuple(p) if tuple(p) else ()
                if ".pp_stacked" in v.name and "pp" in mesh_shape:
                    spec = ("pp",)
            if not any(a is not None for a in spec):
                spec = zero1_spec_for(v, dp, zero)
            if any(a is not None for a in spec):
                specs[v.name] = spec
    feed_spec = ("dp",) if dp > 1 else ()
    return specs, feed_spec


def place_state(scope_values: Dict[str, "jax.Array"], mesh: Mesh,
                rules: ShardingRules) -> Dict[str, "jax.Array"]:
    """Device_put scope state onto the mesh per rules (param broadcast —
    the reference's BCastParamsToDevices, parallel_executor.cc:503)."""
    placed = {}
    for name, v in scope_values.items():
        placed[name] = jax.device_put(v, rules.sharding_for_param(mesh, name))
    return placed
