"""Sharding rules: map program vars onto a device mesh.

TPU-native replacement for the reference's multi-device graph builders
(ir/multi_devices_graph_pass/) and BuildStrategy reduce strategies: instead of
rewriting the graph with per-grad AllReduce handles, we attach a
PartitionSpec to each var and jit once — XLA GSPMD partitions the whole step
and places the collectives (grad all-reduce over 'dp', activation collectives
over 'tp') on ICI.

``ShardingRules`` is name-pattern based so model code stays sharding-agnostic
(the reference reached the same decoupling via transpiler passes).
"""
from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(shape: Dict[str, int], devices=None) -> Mesh:
    """mesh({'dp': 2, 'tp': 4}) over the first prod(shape) devices.
    Axis order follows dict order; put the fastest-varying (intra-chip ICI
    neighbour) axis last — that is where tp belongs."""
    devices = list(devices if devices is not None else jax.devices())
    n = int(np.prod(list(shape.values())))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(tuple(shape.values()))
    return Mesh(arr, axis_names=tuple(shape.keys()))


class ShardingRules:
    """Ordered (regex, PartitionSpec) rules for params + batch axis for feeds."""

    def __init__(self, param_rules: Sequence[Tuple[str, P]] = (),
                 feed_spec: P = P("dp"), default: P = P()):
        self.param_rules = [(re.compile(pat), spec) for pat, spec in param_rules]
        self.feed_spec = feed_spec
        self.default = default

    def spec_for_param(self, name: str, shape=None) -> P:
        for pat, spec in self.param_rules:
            if pat.search(name):
                return spec
        return self.default

    def sharding_for_param(self, mesh: Mesh, name: str, shape=None):
        # pipeline-stacked params (layers.PipelineRegion) always place one
        # stage slice per 'pp' rank — their leading dim IS the stage axis.
        # This also covers their optimizer accumulators, whose names embed
        # the param name.
        if ".pp_stacked" in name and "pp" in mesh.axis_names:
            return NamedSharding(mesh, P("pp"))
        return NamedSharding(mesh, self.spec_for_param(name, shape))

    def sharding_for_feed(self, mesh: Mesh):
        return NamedSharding(mesh, self.feed_spec)


# Megatron-style tensor-parallel rules for the BERT/transformer family:
# column-parallel QKV/FFN-in (shard output dim), row-parallel out/FFN-out
# (shard input dim), vocab-sharded embedding. Everything else replicated.
def transformer_tp_rules() -> ShardingRules:
    return ShardingRules(param_rules=[
        (r"_(q|k|v|ffn1)_w$", P(None, "tp")),
        (r"_(q|k|v|ffn1)_b$", P("tp")),
        (r"_(out|ffn2)_w$", P("tp", None)),
        (r"word_embedding$", P("tp", None)),
    ], feed_spec=P("dp"))


def compile_sharded_step(program, mesh: Mesh, feed_names: Sequence[str],
                         fetch_names: Sequence[str],
                         rules: Optional[ShardingRules] = None,
                         donate: bool = True):
    """Jit the program's global block over ``mesh`` with rule-derived
    in/out shardings. Returns (jitted_fn, io) where io describes arg order
    (see executor.analyze_block_io)."""
    from ..executor import analyze_block_io, make_step_fn
    from ..flags import flag

    rules = rules or ShardingRules()
    block = program.global_block
    io = analyze_block_io(block, set(feed_names), fetch_names)
    nan_meta = [] if flag("check_nan_inf") else None
    step_fn = make_step_fn(block, io, fetch_names, mesh=mesh,
                           nan_check_meta=nan_meta)

    def state_shard(name):
        return rules.sharding_for_param(mesh, name)

    feed_shard = rules.sharding_for_feed(mesh)
    in_shardings = (
        [feed_shard] * len(io["feed_order"]),
        [state_shard(n) for n in io["donated"]],
        [state_shard(n) for n in io["ro"]],
        None,
    )
    # outputs: fetches replicated; state keeps its input sharding
    out_shardings = (
        [NamedSharding(mesh, P())] * len(fetch_names),
        [state_shard(n) for n in io["state_out"]],
    )
    if nan_meta is not None:
        out_shardings = out_shardings + (NamedSharding(mesh, P()),)
    jitted = jax.jit(step_fn, in_shardings=in_shardings,
                     out_shardings=out_shardings,
                     donate_argnums=(1,) if donate else ())
    io["nan_check_meta"] = nan_meta
    return jitted, io


def place_state(scope_values: Dict[str, "jax.Array"], mesh: Mesh,
                rules: ShardingRules) -> Dict[str, "jax.Array"]:
    """Device_put scope state onto the mesh per rules (param broadcast —
    the reference's BCastParamsToDevices, parallel_executor.cc:503)."""
    placed = {}
    for name, v in scope_values.items():
        placed[name] = jax.device_put(v, rules.sharding_for_param(mesh, name))
    return placed
