"""Pipeline parallelism over a `pp` mesh axis — the SectionWorker, TPU-native.

The reference pipelines by cutting the program into sections placed on
different devices and streaming scopes through blocking queues between
section-worker threads (reference: python/paddle/fluid/optimizer.py:2781
PipelineOptimizer, paddle/fluid/framework/trainer.h:110 PipelineTrainer,
device_worker.h:267 SectionWorker). The TPU-native equivalent keeps the
same schedule — GPipe microbatches flowing through stages — but expresses
it as ONE SPMD program: each pp rank holds one stage's parameters (a
[P, ...]-stacked param tree sharded over 'pp'), and the inter-section
queues become `lax.ppermute` of activations to the next rank each tick.
XLA lowers the ppermute to ICI collective-permute; the "queue" is the wire.

Schedule (GPipe, M microbatches, P stages, T = M + P - 1 ticks):

    tick t: rank s works on microbatch (t - s) when 0 <= t - s < M;
    rank 0 injects microbatch t; rank P-1 emits microbatch t - (P - 1).

All ranks execute the stage function every tick (idle ranks chew on
zeros — the SPMD pipelining bubble, cost P-1 of M+P-1 ticks, same as the
reference's warm-up/drain). The loop is a lax.scan, so the whole pipeline
— including backward, which reverses the permutes automatically under
jax.grad — is one compiled step.
"""
from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .sharding import (axis_size, has_varying_types, pvary_compat,
                       shard_map_compat)

__all__ = ["pipeline_spmd", "pipeline", "stack_stage_params"]


def pipeline_spmd(stage_fn: Callable, stage_params, x_micro,
                  axis_name: str = "pp"):
    """Run the GPipe schedule inside shard_map over ``axis_name``.

    stage_fn: (params_leaf_tree, activation [B_mb, ...]) -> activation of
        the SAME shape/dtype (homogeneous stages — the repeated-block
        architecture every transformer has).
    stage_params: this rank's stage parameters — from a [P, ...]-stacked
        tree sharded over the axis, i.e. leaves arrive [1, ...]; a leading
        singleton dim is squeezed.
    x_micro: [M, B_mb, ...] microbatched input (replicated over the axis).

    Returns [M, B_mb, ...] outputs of the final stage, replicated.
    """
    P_ = axis_size(axis_name)
    s = jax.lax.axis_index(axis_name)
    M = x_micro.shape[0]
    T = M + P_ - 1
    params = jax.tree.map(
        lambda l: l[0] if (hasattr(l, "shape") and l.shape
                           and l.shape[0] == 1) else l, stage_params)

    # non-circular shift s -> s+1: rank 0 receives zeros
    perm = [(i, i + 1) for i in range(P_ - 1)]

    # the carry must be typed as VARYING over the pipeline axis (its value
    # depends on axis_index from tick 1 on), or the scan carry types clash
    carry0 = jax.tree.map(
        lambda t: pvary_compat(t, axis_name),
        (jnp.zeros_like(x_micro[0]), jnp.zeros_like(x_micro)))

    def tick(carry, t):
        prev_act, out_buf = carry
        mb = t - s                                   # my microbatch index
        active = (mb >= 0) & (mb < M)
        inj = x_micro[jnp.clip(t, 0, M - 1)]
        inp = jnp.where(s == 0, inj, prev_act)
        y = stage_fn(params, inp)
        # zero inactive ranks' output so garbage never propagates and the
        # backward through idle ticks contributes exact zeros
        y = jnp.where(active, y, jnp.zeros_like(y))
        # last stage banks its finished microbatch
        emit = (s == P_ - 1) & active
        idx = jnp.clip(mb, 0, M - 1)
        out_buf = jnp.where(
            emit, jax.lax.dynamic_update_index_in_dim(
                out_buf, y.astype(out_buf.dtype), idx, 0), out_buf)
        nxt = jax.lax.ppermute(y, axis_name, perm)
        return (nxt, out_buf), None

    (_, out_buf), _ = jax.lax.scan(tick, carry0, jnp.arange(T))
    # only rank P-1 holds the real outputs; mask-psum replicates them
    return jax.lax.psum(
        jnp.where(s == P_ - 1, out_buf, jnp.zeros_like(out_buf)), axis_name)


def stack_stage_params(per_stage_params):
    """[tree_stage0, tree_stage1, ...] -> one tree with [P, ...] leaves
    (shard the leading dim over 'pp' to place each stage on its rank)."""
    return jax.tree.map(lambda *ls: jnp.stack(ls), *per_stage_params)


def pipeline(stage_fn: Callable, stacked_params, x, mesh: Mesh,
             num_microbatches: int, axis_name: str = "pp",
             batch_axis: str = "dp", place_params: bool = True):
    """Whole-array wrapper: shard_map the GPipe schedule over ``mesh``.

    stacked_params: tree with leading [P] dim on every leaf (see
    stack_stage_params); sharded over ``axis_name``.
    x: [B, ...] batch (sharded over ``batch_axis`` when the mesh has it).
    ``place_params=False`` skips the eager device_put (required when called
    from inside a jit trace, where shardings come from the caller).
    Returns [B, ...] final-stage outputs with x's sharding.
    """
    M = int(num_microbatches)
    B = x.shape[0]
    n_stages = {l.shape[0] for l in jax.tree.leaves(stacked_params)}
    if len(n_stages) != 1:
        raise ValueError(
            f"stacked param leaves disagree on stage count: {n_stages}")
    (n_stages,) = n_stages
    if mesh.shape[axis_name] != n_stages:
        raise ValueError(
            f"mesh '{axis_name}' axis has {mesh.shape[axis_name]} ranks "
            f"but the stacked params carry {n_stages} stages — they must "
            f"match (one stage per rank)")
    has_dp = batch_axis is not None and batch_axis in mesh.axis_names
    local_b = B // mesh.shape[batch_axis] if has_dp else B
    if local_b % M:
        raise ValueError(
            f"per-{batch_axis + '-rank ' if has_dp else ''}batch {local_b} "
            f"not divisible by num_microbatches {M}")
    xspec = P(batch_axis if has_dp else None, *([None] * (x.ndim - 1)))
    pspec = jax.tree.map(
        lambda l: P(axis_name, *([None] * (l.ndim - 1))), stacked_params)

    def local(params, xl):
        xm = xl.reshape((M, xl.shape[0] // M) + xl.shape[1:])
        ym = pipeline_spmd(stage_fn, params, xm, axis_name)
        return ym.reshape(xl.shape)

    # 0.4.x jax cannot type the scan carry as varying (no pcast/pvary), so
    # the replication check must be off there; newer jax keeps it on
    fn = shard_map_compat(local, mesh=mesh, in_specs=(pspec, xspec),
                          out_specs=xspec, check_vma=has_varying_types())
    if place_params and _needs_place(stacked_params, mesh):
        stacked_params = jax.device_put(
            stacked_params,
            jax.tree.map(lambda sp: NamedSharding(mesh, sp), pspec))
    # the GPipe schedule is T = M + P - 1 collective-permutes around the
    # pp ring; a wedged stage rank stalls every other rank's ppermute
    # forever. Armed like the executor step sections: dump + raise under
    # FLAGS_step_timeout_s instead of hanging (a jit-trace caller only
    # wraps host-side tracing and disarms immediately).
    from ..resilience.distributed import (block_until_ready_concrete,
                                          watchdog_section)

    from ..resilience.elastic import device_loss_classification

    # a dead pp-ring rank surfaces here as an untyped runtime error —
    # the shared wrapper classifies it typed so the elastic path can act
    with watchdog_section("collective",
                          detail=f"pipeline over '{axis_name}' "
                                 f"({num_microbatches} microbatches)") \
            as tok, device_loss_classification("collective"):
        out = fn(stacked_params, x)
        if tok is not None:
            # async dispatch: arm through device completion (no-op when
            # called inside a jit trace; real runtime errors propagate)
            block_until_ready_concrete(out)
        return out


def _needs_place(tree, mesh) -> bool:
    """True when leaves are plain (uncommitted) arrays: device_put them
    onto the mesh so shard_map sees the intended stage placement."""
    for leaf in jax.tree.leaves(tree):
        sh = getattr(leaf, "sharding", None)
        if sh is None or getattr(sh, "mesh", None) is not mesh:
            return True
    return False
