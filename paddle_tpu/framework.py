"""Program IR: Program / Block / Operator / Variable / Parameter.

TPU-native re-design of the reference's graph-program layer
(reference: python/paddle/fluid/framework.py:408 Variable, :1320 Operator,
:1769 Block, :3152 Program, :4095 Parameter and the C++ descs behind them,
paddle/fluid/framework/framework.proto:43-220). Differences by design:

* One representation, not desc+wrapper twins: the Python objects ARE the IR,
  with a JSON-serialisable dict form replacing protobuf (``Program.to_dict``).
* No per-op kernels behind the ops — an entire block lowers to one XLA
  executable (see ``paddle_tpu.lowering``); ops here are pure metadata.
* Every op gets a stable ``__uid__`` attr at append time. Random ops derive
  their PRNG key from it, and the auto-generated ``*_grad`` op reuses the
  forward uid so grad-side recomputation sees identical randomness.
"""
from __future__ import annotations

import contextlib
import copy
import itertools
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from . import unique_name
from .core import registry
from .core.types import VarType, canonical_dtype

__all__ = [
    "Program",
    "Block",
    "Operator",
    "Variable",
    "Parameter",
    "default_main_program",
    "default_startup_program",
    "program_guard",
    "name_scope",
    "grad_var_name",
    "in_dygraph_mode",
]

GRAD_VAR_SUFFIX = "@GRAD"
ZERO_VAR_SUFFIX = "@ZERO"


class OpRole:
    """Role stamped on every op at append time (reference
    op_proto_maker.h OpRole + framework.py _current_role): lets
    ``clone(for_test=True)`` prune the backward/optimize/lr parts the way
    the reference's ``core.prune_backward`` does."""

    Forward = "forward"
    Backward = "backward"
    Optimize = "optimize"
    LRSched = "lr_sched"

    PRUNE_FOR_TEST = (Backward, Optimize, LRSched)


def grad_var_name(name: str) -> str:
    return name + GRAD_VAR_SUFFIX


class Variable:
    """A named tensor in a Block (reference framework.py:408).

    Build-time metadata only; runtime values live in the executor Scope as jax
    arrays. ``shape`` may contain -1 for dims resolved at feed time.
    """

    def __init__(
        self,
        block: "Block",
        name: str,
        shape: Optional[Sequence[int]] = None,
        dtype: Any = "float32",
        type: VarType = VarType.LOD_TENSOR,
        lod_level: int = 0,
        persistable: bool = False,
        stop_gradient: bool = False,
        is_data: bool = False,
        initializer=None,
        **kwargs,
    ):
        self.block = block
        self.name = name
        self.shape = tuple(int(s) for s in shape) if shape is not None else None
        self.dtype = canonical_dtype(dtype)
        self.type = type
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        # distributed annotation: optional PartitionSpec-like tuple mapping
        # each dim to a mesh axis name (or None). Consumed by parallel/.
        self.dist_spec: Optional[tuple] = None

    # -- convenience -----------------------------------------------------
    @property
    def grad_name(self) -> str:
        return grad_var_name(self.name)

    def astype(self, dtype):
        from .layers import tensor as _t

        return _t.cast(self, dtype)

    def __repr__(self):
        return (
            f"Variable(name={self.name!r}, shape={self.shape}, dtype={self.dtype},"
            f" persistable={self.persistable}, stop_gradient={self.stop_gradient})"
        )

    # arithmetic sugar (reference: math_op_patch.py monkeypatch)
    def _binary(self, other, op, reverse=False):
        from .layers import math_op_patch

        return math_op_patch.binary(self, other, op, reverse)

    def __add__(self, o):
        return self._binary(o, "elementwise_add")

    def __radd__(self, o):
        return self._binary(o, "elementwise_add", True)

    def __sub__(self, o):
        return self._binary(o, "elementwise_sub")

    def __rsub__(self, o):
        return self._binary(o, "elementwise_sub", True)

    def __mul__(self, o):
        return self._binary(o, "elementwise_mul")

    def __rmul__(self, o):
        return self._binary(o, "elementwise_mul", True)

    def __truediv__(self, o):
        return self._binary(o, "elementwise_div")

    def __rtruediv__(self, o):
        return self._binary(o, "elementwise_div", True)

    def __pow__(self, o):
        return self._binary(o, "elementwise_pow")

    def __neg__(self):
        from .layers import tensor as _t

        return _t.scale(self, scale=-1.0)

    def __matmul__(self, o):
        from .layers import nn as _nn

        return _nn.matmul(self, o)

    # -- serialization ---------------------------------------------------
    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "shape": list(self.shape) if self.shape is not None else None,
            "dtype": self.dtype,
            "type": self.type.value,
            "lod_level": self.lod_level,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "is_data": self.is_data,
        }
        if getattr(self, "is_optimizer_state", False):
            d["is_optimizer_state"] = True  # ZeRO-1 sharding survives clone
        if getattr(self, "is_distributed", False):
            d["is_distributed"] = True  # sharded-embedding tag survives clone
        return d

    @staticmethod
    def from_dict(block: "Block", d: dict) -> "Variable":
        v = Variable(
            block,
            name=d["name"],
            shape=d["shape"],
            dtype=d["dtype"],
            type=VarType(d["type"]),
            lod_level=d.get("lod_level", 0),
            persistable=d.get("persistable", False),
            stop_gradient=d.get("stop_gradient", False),
            is_data=d.get("is_data", False),
        )
        if d.get("is_optimizer_state"):
            v.is_optimizer_state = True
        if d.get("is_distributed"):
            v.is_distributed = True
        return v


class Parameter(Variable):
    """A trainable persistable Variable (reference framework.py:4095)."""

    def __init__(self, block, name, shape, dtype, **kwargs):
        kwargs.setdefault("persistable", True)
        self.trainable = kwargs.pop("trainable", True)
        self.optimize_attr = kwargs.pop("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kwargs.pop("regularizer", None)
        self.do_model_average = kwargs.pop("do_model_average", None)
        super().__init__(block, name, shape=shape, dtype=dtype, **kwargs)

    def to_dict(self):
        d = super().to_dict()
        d["is_parameter"] = True
        d["trainable"] = self.trainable
        d["optimize_attr"] = self.optimize_attr
        if self.regularizer is not None:
            d["regularizer"] = {
                "type": type(self.regularizer).__name__,
                "coeff": getattr(self.regularizer, "_coeff", 0.0),
            }
        return d


class Operator:
    """One op in a Block (reference framework.py:1320 + C++ OpDesc).

    inputs/outputs map slot name -> list of var *names*; attrs is a plain
    dict checked against the registered OpDef schema.
    """

    def __init__(
        self,
        block: "Block",
        type: str,
        inputs: Optional[Dict[str, Any]] = None,
        outputs: Optional[Dict[str, Any]] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ):
        self.block = block
        self.type = type
        self.inputs: Dict[str, List[str]] = {}
        self.outputs: Dict[str, List[str]] = {}
        for slot, vars_ in (inputs or {}).items():
            self.inputs[slot] = [v.name if isinstance(v, Variable) else v for v in _as_list(vars_)]
        for slot, vars_ in (outputs or {}).items():
            self.outputs[slot] = [v.name if isinstance(v, Variable) else v for v in _as_list(vars_)]
        self.attrs: Dict[str, Any] = dict(attrs or {})

        # fill attr defaults from schema when the op is registered
        if registry.has_op(type):
            opdef = registry.get_op_def(type)
            for aname, aspec in opdef.attrs.items():
                if aname not in self.attrs:
                    if aspec.required:
                        raise ValueError(f"op {type}: required attr '{aname}' missing")
                    self.attrs[aname] = copy.copy(aspec.default)
        elif not (type.endswith("_grad") and registry.has_op(type[:-5])) \
                and type not in ("feed", "fetch"):
            raise ValueError(
                f"operator '{type}' is not registered "
                f"({len(registry.all_ops())} ops known)")

    def input(self, slot: str) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot: str) -> List[str]:
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self) -> List[str]:
        return [n for ns in self.inputs.values() for n in ns]

    @property
    def output_arg_names(self) -> List[str]:
        return [n for ns in self.outputs.values() for n in ns]

    def attr(self, name: str):
        return self.attrs.get(name)

    def set_attr(self, name: str, value) -> None:
        """Mutate an attr AND invalidate compiled-executable caches. Direct
        ``op.attrs[k] = v`` writes on an already-run program are NOT seen by
        the executor cache (reference invalidates via desc version); all
        framework code mutates through here."""
        self.attrs[name] = value
        self.block.program._bump_version()

    _set_attr = set_attr  # reference-API alias (Operator._set_attr)

    def infer_shape(self):
        if registry.has_op(self.type):
            opdef = registry.get_op_def(self.type)
            if opdef.infer_shape is not None:
                opdef.infer_shape(self, self.block)
            elif opdef.lower is not None:
                from . import lowering

                lowering.auto_infer_shape(self, self.block)

    def __repr__(self):
        ins = {k: v for k, v in self.inputs.items()}
        outs = {k: v for k, v in self.outputs.items()}
        return f"Op({self.type}, in={ins}, out={outs})"

    def to_dict(self) -> dict:
        return {
            "type": self.type,
            "inputs": {k: list(v) for k, v in self.inputs.items()},
            "outputs": {k: list(v) for k, v in self.outputs.items()},
            "attrs": _jsonable_attrs(self.attrs),
        }

    @staticmethod
    def from_dict(block: "Block", d: dict) -> "Operator":
        return Operator(
            block, d["type"], inputs=d["inputs"], outputs=d["outputs"], attrs=d["attrs"]
        )


def _jsonable_attrs(attrs: dict) -> dict:
    out = {}
    for k, v in attrs.items():
        if isinstance(v, np.ndarray):
            out[k] = {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
        elif isinstance(v, (np.integer,)):
            out[k] = int(v)
        elif isinstance(v, (np.floating,)):
            out[k] = float(v)
        else:
            out[k] = v
    return out


class Block:
    """An ordered list of ops plus a var table (reference framework.py:1769)."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.forward_block_idx = -1
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    @property
    def parent_block(self) -> Optional["Block"]:
        if self.parent_idx < 0:
            return None
        return self.program.blocks[self.parent_idx]

    # -- var management --------------------------------------------------
    def create_var(self, name: Optional[str] = None, **kwargs) -> Variable:
        if name is None:
            name = unique_name.generate("_generated_var")
        if name in self.vars:
            return self.vars[name]
        v = Variable(self, name, **kwargs)
        self.vars[name] = v
        return v

    def create_parameter(self, name, shape, dtype, **kwargs) -> Parameter:
        p = Parameter(self, name, shape, dtype, **kwargs)
        self.vars[name] = p
        return p

    def var(self, name: str) -> Variable:
        v = self.vars.get(name)
        if v is None:
            raise KeyError(f"variable '{name}' not found in block {self.idx}")
        return v

    def has_var(self, name: str) -> bool:
        return name in self.vars

    def _var_recursive(self, name: str) -> Variable:
        """Find var in this or any ancestor block (reference Block.var climb)."""
        blk = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent_block
        raise KeyError(f"variable '{name}' not found in block {self.idx} or ancestors")

    def has_var_recursive(self, name: str) -> bool:
        try:
            self._var_recursive(name)
            return True
        except KeyError:
            return False

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # -- op management ---------------------------------------------------
    def _stamp(self, op: Operator) -> None:
        op.attrs.setdefault("__uid__", self.program._next_uid())
        op.attrs.setdefault("__op_role__", self.program._op_role)
        if "op_callstack" not in op.attrs:
            site = _user_call_site()
            if site:
                # reference framework/op_call_stack.h: the op remembers the
                # user line that created it; lowering errors point here
                op.attrs["op_callstack"] = site
        if _name_scope_stack and "op_namescope" not in op.attrs:
            # reference op_proto_maker OpNamescopeAttrName: consumed by e.g.
            # the slim quant pass's skip_pattern
            op.attrs["op_namescope"] = "/".join(_name_scope_stack)

    def append_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs=inputs, outputs=outputs, attrs=attrs)
        self._stamp(op)
        self.ops.append(op)
        op.infer_shape()
        return op

    def prepend_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs=inputs, outputs=outputs, attrs=attrs)
        self._stamp(op)
        self.ops.insert(0, op)
        op.infer_shape()
        return op

    def insert_op(self, index: int, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs=inputs, outputs=outputs, attrs=attrs)
        self._stamp(op)
        self.ops.insert(index, op)
        op.infer_shape()
        return op

    # -- serialization ---------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "forward_block_idx": self.forward_block_idx,
            "vars": [v.to_dict() for v in self.vars.values()],
            "ops": [o.to_dict() for o in self.ops],
        }


class Program:
    """A multi-block program (reference framework.py:3152, framework.proto:212)."""

    # monotonic identity for executor cache keys: id(program) can alias
    # after GC, handing a fresh Program a dead program's compiled step or
    # verified-program cache entry
    _serial_counter = itertools.count()

    def __init__(self):
        self._serial = next(Program._serial_counter)
        self.blocks: List[Block] = [Block(self, 0)]
        self.current_block_idx = 0
        self._uid_counter = 0
        self._seed = 0
        # name -> lr-scheduler / misc program-level state
        self._lr_schedulers = []
        self.random_seed = 0
        # bumped on structural/attr mutation; part of the executor cache key
        self._version = 0
        # role stamped on appended ops (reference _current_role)
        self._op_role = OpRole.Forward

    def _next_uid(self) -> int:
        self._uid_counter += 1
        self._version += 1
        return self._uid_counter

    def _bump_version(self) -> None:
        self._version += 1

    @contextlib.contextmanager
    def _op_role_guard(self, role: str):
        old, self._op_role = self._op_role, role
        try:
            yield
        finally:
            self._op_role = old

    # -- blocks ----------------------------------------------------------
    @property
    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    def _create_block(self, parent_idx: Optional[int] = None) -> Block:
        new_idx = len(self.blocks)
        parent = self.current_block_idx if parent_idx is None else parent_idx
        b = Block(self, new_idx, parent)
        self.blocks.append(b)
        self.current_block_idx = new_idx
        return b

    def _rollback(self):
        self.current_block_idx = self.blocks[self.current_block_idx].parent_idx

    # -- whole-program transforms ---------------------------------------
    def clone(self, for_test: bool = False) -> "Program":
        """Deep-copy (reference Program.clone framework.py:3376). With
        ``for_test`` True, ops switch to inference behaviour via their
        ``is_test`` attr (dropout/batch_norm)."""
        p = Program.from_dict(self.to_dict())
        p._uid_counter = self._uid_counter
        p.random_seed = self.random_seed
        if for_test:
            # prune the backward/optimize/lr-sched parts (reference
            # core.prune_backward called from clone framework.py:3571):
            # keeping them would make "inference" runs mutate parameters
            for blk in p.blocks:
                blk.ops = [op for op in blk.ops
                           if op.attrs.get("__op_role__", OpRole.Forward)
                           not in OpRole.PRUNE_FOR_TEST]
            for blk in p.blocks:
                for op in blk.ops:
                    if "is_test" in op.attrs:
                        op.set_attr("is_test", True)
                    if op.type == "batch_norm":
                        op.set_attr("use_global_stats", True)
        return p

    def memory_plan(self, feed_names: Sequence[str] = (),
                    fetch_names: Sequence[str] = (), batch_size: int = 1,
                    mesh: Optional[Dict[str, int]] = None,
                    specs: Optional[Dict[str, tuple]] = None):
        """Static peak-memory plan for the global block: a linear-scan
        estimate of live bytes per op index with weights / gradients /
        optimizer state / activations split out (the analysis layer of the
        reference's ir/memory_optimize_pass family). ``-1`` dims resolve to
        ``batch_size``. See ``paddle_tpu.analysis.liveness.memory_plan``
        and ``tools/mem_report.py``.

        With ``mesh`` (``{"dp": 8, ...}``) the plan is **per chip** under a
        sharding assignment: ``specs`` (name -> PartitionSpec-like tuple,
        e.g. from ``parallel.sharding.extract_param_specs``) seeds
        ``analysis.sharding_check.propagate_sharding``, live bytes divide
        per propagated spec (replicated tensors count whole), and
        collective staging buffers are charged at their emitting op. The
        resulting plan carries the analysis on ``plan.sharding``. With
        ``mesh=None`` the path and numbers are identical to the
        single-device planner."""
        from .analysis.liveness import memory_plan as _memory_plan

        if mesh is None:
            return _memory_plan(self, feed_names=feed_names,
                                fetch_names=fetch_names,
                                batch_size=batch_size)
        from .analysis.sharding_check import (propagate_sharding,
                                              staging_bytes_by_op)

        analysis = propagate_sharding(
            self, mesh, param_specs=specs, feed_names=feed_names,
            fetch_names=fetch_names, batch_size=batch_size)
        plan = _memory_plan(self, feed_names=feed_names,
                            fetch_names=fetch_names, batch_size=batch_size,
                            mesh=analysis.mesh, specs=analysis.var_specs,
                            staging=staging_bytes_by_op(analysis))
        plan.sharding = analysis
        return plan

    def list_vars(self):
        for blk in self.blocks:
            yield from blk.vars.values()

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.list_vars() if isinstance(v, Parameter)]

    # -- serialization ---------------------------------------------------
    def to_dict(self) -> dict:
        d = {
            "version": 1,
            "blocks": [b.to_dict() for b in self.blocks],
        }
        amp = getattr(self, "_amp_policy", None)
        if amp is not None:
            # program-level compute policy must survive serde: a deserialized
            # inference program silently reverting to fp32 is a perf bug
            d["amp_policy"] = {"white": sorted(amp.white),
                               "black": sorted(amp.black),
                               "compute_dtype": str(amp.compute_dtype)}
        return d

    @staticmethod
    def from_dict(d: dict) -> "Program":
        p = Program()
        p.blocks = []
        for bd in d["blocks"]:
            b = Block(p, bd["idx"], bd["parent_idx"])
            b.forward_block_idx = bd.get("forward_block_idx", -1)
            p.blocks.append(b)
        for b, bd in zip(p.blocks, d["blocks"]):
            for vd in bd["vars"]:
                if vd.get("is_parameter"):
                    reg = None
                    if vd.get("regularizer"):
                        from . import regularizer as reg_mod

                        reg_cls = getattr(reg_mod, vd["regularizer"]["type"],
                                          None)
                        if reg_cls is not None:
                            reg = reg_cls(vd["regularizer"]["coeff"])
                    param = Parameter(
                        b,
                        vd["name"],
                        vd["shape"],
                        vd["dtype"],
                        trainable=vd.get("trainable", True),
                        optimize_attr=vd.get("optimize_attr",
                                             {"learning_rate": 1.0}),
                        regularizer=reg,
                    )
                    param.stop_gradient = vd.get("stop_gradient", False)
                    if vd.get("is_distributed"):
                        param.is_distributed = True
                    b.vars[vd["name"]] = param
                else:
                    b.vars[vd["name"]] = Variable.from_dict(b, vd)
            for od in bd["ops"]:
                op = Operator.from_dict(b, od)
                b.ops.append(op)
                p._uid_counter = max(p._uid_counter, op.attrs.get("__uid__", 0))
        if d.get("amp_policy"):
            from .lowering import AmpPolicy

            ap = d["amp_policy"]
            p._amp_policy = AmpPolicy(ap["white"], ap["black"],
                                      ap["compute_dtype"])
        return p

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @staticmethod
    def from_json(s: str) -> "Program":
        return Program.from_dict(json.loads(s))

    def __repr__(self):
        lines = []
        for blk in self.blocks:
            lines.append(f"-- block {blk.idx} (parent {blk.parent_idx}) --")
            for v in blk.vars.values():
                lines.append(f"  {v!r}")
            for op in blk.ops:
                lines.append(f"  {op!r}")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# default program globals (reference framework.py:4190-4304)
# ---------------------------------------------------------------------------

_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


def switch_main_program(p: Program) -> Program:
    global _main_program
    old, _main_program = _main_program, p
    return old


def switch_startup_program(p: Program) -> Program:
    global _startup_program
    old, _startup_program = _startup_program, p
    return old


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    old_main = switch_main_program(main_program)
    old_startup = None
    if startup_program is not None:
        old_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_startup is not None:
            switch_startup_program(old_startup)


_name_scope_stack: List[str] = []


@contextlib.contextmanager
def name_scope(prefix: str):
    _name_scope_stack.append(prefix)
    try:
        yield
    finally:
        _name_scope_stack.pop()


def in_dygraph_mode() -> bool:
    from .dygraph import base as _dy

    return _dy.in_dygraph_mode()


def _current_tracer():
    from .dygraph import base as _dy

    return _dy._tape


_PKG_DIR = os.path.dirname(os.path.abspath(__file__)) + os.sep


def _user_call_site() -> str:
    """First stack frame outside paddle_tpu — the user line that created the
    op (reference op_call_stack.cc InsertCallStackInfo)."""
    f = sys._getframe(1)
    while f is not None:
        # normpath: the tools/ CLIs import the package via a "tools/.."
        # sys.path entry, leaving ".." in co_filename — unnormalized it
        # never prefix-matches _PKG_DIR and every op blames framework.py
        fn = os.path.normpath(f.f_code.co_filename)
        if not fn.startswith(_PKG_DIR):
            return f"{fn}:{f.f_lineno} in {f.f_code.co_name}"
        f = f.f_back
    return ""


def _as_list(x) -> list:
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]
