"""Static SPMD sharding analysis over the Program IR (pass ``sharding_check``).

The reference stack reasons about multi-device placement by *rewriting the
graph* (ir/multi_devices_graph_pass: one AllReduceOpHandle per gradient,
ReduceSSAGraphBuilder for the sharded-update layout); this rebuild hands
placement to XLA GSPMD at jit time (parallel/compiled_program.py), which
means nothing reasoned about sharding *statically*: ``Program.memory_plan()``
planned as if single-device, and the first signal that a layout was wrong —
an unsatisfiable spec, a shard-indivisible dim, a reshard inside the hot
loop — was a runtime error or a silent collective storm on real chips.

This module is the build-time layer (ROADMAP item 4's memory-plan gate and
item 2's comms-vs-compute signal):

* ``propagate_sharding`` — takes a mesh shape (``{"dp": 8, "tp": 2}``) and a
  per-param spec assignment (sourced from ``BuildStrategy`` via
  ``parallel.sharding.extract_param_specs``, including the ZeRO-1
  ``ReduceStrategy.Reduce`` layout) and pushes shard specs through every op
  using the shapes the build-time ``infer_shape`` contract already recorded
  on each var. Specs are ``PartitionSpec``-like tuples: one mesh-axis name
  (or None) per dim.
* The **PT730–PT744** diagnostic family (docs/ANALYSIS.md): malformed or
  unsatisfiable specs, shard-indivisible dims, implicit full replication of
  large tensors, resharding inside the training loop, gradient/optimizer-
  state specs that disagree with the param's, and donations the liveness
  proof takes but resharding invalidates (the parallel-path extension of
  the PT710 family).
* ``ShardingAnalysis`` — the propagation product: per-var specs plus the
  **collective events** (all-reduce / all-gather / reduce-scatter /
  reshard) implied by spec transitions, with full tensor bytes attached.
  ``analysis.cost_model.estimate_comms`` turns these into per-chip wire
  volumes and the predicted comms-vs-compute ratio;
  ``liveness.memory_plan(mesh=..., specs=...)`` divides live bytes per
  spec for the per-chip peak estimate (collective staging included).

Registered as analysis pass ``sharding_check`` (requires ``liveness``).
The pass reads its inputs from ``PassContext.options``:

    run_pipeline(prog, ("sharding_check",), fetch_names=[loss.name],
                 options={"mesh": {"dp": 8}, "zero": True})

``options["specs"]`` overrides the derived per-param assignment; with no
``mesh`` option the pass is a silent no-op (returns None) so generic
pipelines can always include it.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .diagnostics import Diagnostic
from .verifier import EMPTY, _site

__all__ = [
    "Spec", "CollectiveEvent", "ShardingAnalysis", "normalize_spec",
    "spec_divisor", "shard_bytes", "propagate_sharding", "check_sharding",
    "staging_bytes_by_op", "format_spec",
]

# one mesh-axis name (or None) per dim; () means fully replicated
Spec = Tuple[Optional[str], ...]

REPLICATED: Spec = ()

# optimizer update ops: Param/Grad in, ParamOut out, state slots between
_OPT_STATE_SLOTS = (
    "Moment", "Moment1", "Moment2", "Velocity", "MeanSquare", "MeanGrad",
    "AvgSquaredGrad", "AvgSquaredUpdate", "InfNorm",
)

# default byte threshold for the PT736 implicit-replication lint
LARGE_BYTES_DEFAULT = 1 << 20

# ops with no per-dim spec transfer by design (reductions/metrics): the
# generic rule's replicated-output + partial-sum all-reduce IS their
# correct model, so they never warrant a PT744 "no rule" note
_KNOWN_REDUCTIONS = frozenset({
    "mean", "reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
    "accuracy", "auc", "top_k", "argmax", "argmin", "not_equal",
    "equal", "less_than", "greater_than",
})

# data-movement ops: their own rules record the gather/reshard they imply;
# the partial-sum reduce rule must not double-charge them
_LAYOUT_TYPES = frozenset({
    "reshape2", "squeeze2", "unsqueeze2", "flatten2", "transpose2",
    "concat", "slice", "assign", "shape", "lookup_table",
    "fill_constant_batch_size_like",
})


def normalize_spec(spec: Optional[Sequence], ndim: int) -> Spec:
    """Pad/trim a spec to ``ndim`` entries (None = unsharded dim)."""
    spec = tuple(spec or ())
    if len(spec) < ndim:
        spec = spec + (None,) * (ndim - len(spec))
    return spec[:ndim]


def is_sharded(spec: Optional[Sequence]) -> bool:
    return any(a is not None for a in (spec or ()))


def _dedup_axes(spec: Spec) -> Spec:
    """Drop repeated mesh axes from a composed spec (first dim wins) —
    a PartitionSpec may use each axis at most once."""
    seen: set = set()
    out = []
    for a in spec:
        if a is not None and a in seen:
            out.append(None)
        else:
            if a is not None:
                seen.add(a)
            out.append(a)
    return tuple(out)


def format_spec(spec: Optional[Sequence]) -> str:
    if not is_sharded(spec):
        return "replicated"
    return "P(" + ", ".join("None" if a is None else repr(a)
                            for a in spec) + ")"


def spec_divisor(spec: Optional[Sequence], mesh: Dict[str, int],
                 shape: Optional[Sequence[int]] = None,
                 batch_size: int = 1) -> int:
    """How many ways the spec splits the value: the product of the mesh
    sizes of its axes — counting only dims the split divides evenly
    (an indivisible dim is kept whole: the conservative per-chip bound)."""
    if not spec:
        return 1
    div = 1
    seen: set = set()
    for d, axis in enumerate(spec):
        if axis is None or axis not in mesh or axis in seen:
            # one mesh axis can split a value at most once — a composed
            # spec that reuses an axis must never multiply the divisor
            # past the mesh size (the per-chip plan would UNDER-estimate)
            continue
        n = int(mesh[axis])
        if n <= 1:
            continue
        if shape is not None and d < len(shape):
            dim = int(shape[d]) if shape[d] is not None else -1
            if dim < 0:
                dim = int(batch_size)
            if dim % n:
                continue
        seen.add(axis)
        div *= n
    return div


def shard_bytes(nbytes: int, spec: Optional[Sequence], mesh: Dict[str, int],
                shape: Optional[Sequence[int]] = None,
                batch_size: int = 1) -> int:
    return int(nbytes) // spec_divisor(spec, mesh, shape, batch_size)


@dataclasses.dataclass
class CollectiveEvent:
    """One collective implied by a spec transition. ``bytes_full`` is the
    FULL (unsharded, batch-resolved) tensor size; the wire-volume formulas
    per kind live in ``cost_model.estimate_comms``."""

    block_idx: int
    op_idx: int
    kind: str            # all_reduce | all_gather | reduce_scatter | reshard
    axis: str            # mesh axis (comma-joined when more than one)
    var: str
    bytes_full: int
    reason: str

    def axis_size(self, mesh: Dict[str, int]) -> int:
        n = 1
        for a in self.axis.split(","):
            n *= int(mesh.get(a, 1))
        return max(n, 1)

    def to_dict(self) -> dict:
        return {"block": self.block_idx, "op": self.op_idx,
                "kind": self.kind, "axis": self.axis, "var": self.var,
                "bytes_full": self.bytes_full, "reason": self.reason}


@dataclasses.dataclass
class ShardingAnalysis:
    """Result of one ``propagate_sharding`` run (cached on the PassContext
    as the ``sharding_check`` analysis value)."""

    mesh: Dict[str, int]
    batch_size: int
    var_specs: Dict[str, Spec]          # every var touched by propagation
    param_specs: Dict[str, Spec]        # the input assignment (validated)
    feed_spec: Spec
    collectives: List[CollectiveEvent]
    diagnostics: List[Diagnostic]

    @property
    def n_devices(self) -> int:
        n = 1
        for s in self.mesh.values():
            n *= int(s)
        return max(n, 1)

    def spec_of(self, name: str) -> Spec:
        return self.var_specs.get(name, REPLICATED)

    def to_dict(self) -> dict:
        return {
            "mesh": dict(self.mesh),
            "batch_size": self.batch_size,
            "n_devices": self.n_devices,
            "sharded_vars": {n: [a for a in s]
                             for n, s in sorted(self.var_specs.items())
                             if is_sharded(s)},
            "collectives": [c.to_dict() for c in self.collectives],
            "diagnostics": [d.code for d in self.diagnostics],
        }


def staging_bytes_by_op(analysis: "ShardingAnalysis"
                        ) -> Dict[Tuple[int, int], int]:
    """Per-(block, op) collective staging bytes for the per-chip memory
    plan: one ring send+recv chunk pair per collective —
    ``2 * bytes_full / axis_size`` (capped at the full tensor). The
    gathered/reduced DESTINATION is the out var itself and is already
    counted by its (replicated or sharded) live bytes; this term is the
    transient wire-side scratch XLA adds on top."""
    out: Dict[Tuple[int, int], int] = {}
    for ev in analysis.collectives:
        n = ev.axis_size(analysis.mesh)
        chunk = min(ev.bytes_full, 2 * ev.bytes_full // max(n, 1))
        key = (ev.block_idx, ev.op_idx)
        out[key] = out.get(key, 0) + int(chunk)
    return out


# ---------------------------------------------------------------------------
# the propagation engine
# ---------------------------------------------------------------------------

class _Propagator:
    """Walks every block in op order, assigning an output spec per op from
    its input specs + recorded shapes, recording collective events at spec
    transitions, and reporting PT73x findings. Conservative by design:
    whenever a rule cannot prove a sharding, the value is replicated (a
    per-chip OVER-estimate, never an under-estimate)."""

    def __init__(self, program, mesh: Dict[str, int], batch_size: int,
                 large_bytes: int = LARGE_BYTES_DEFAULT):
        self.program = program
        self.mesh = {str(k): int(v) for k, v in mesh.items()}
        self.batch = max(1, int(batch_size))
        self.large = int(large_bytes)
        self.specs: Dict[str, Spec] = {}
        self.diags: List[Diagnostic] = []
        self.collectives: List[CollectiveEvent] = []
        self._reported: Set[tuple] = set()
        self._no_rule_types: Set[str] = set()
        # blocks already walked: a sub-block shared by several owning ops
        # (recurrent + recurrent_grad reference one body) propagates ONCE,
        # at its first owner — the same _seen guard liveness.memory_plan
        # uses; also breaks (malformed) sub_block cycles
        self._visited_blocks: Set[int] = set()
        # grad all-reduce events by var name, for the ZeRO rewrite at the
        # optimizer op (reduce-scatter replaces the all-reduce)
        self._ar_by_var: Dict[str, CollectiveEvent] = {}

    # -- helpers ----------------------------------------------------------
    def emit(self, code: str, msg: str, blk, oi: Optional[int],
             op=None, dedup_key: Optional[tuple] = None) -> None:
        key = dedup_key if dedup_key is not None else (code, msg)
        if key in self._reported:
            return
        self._reported.add(key)
        self.diags.append(Diagnostic(
            code, msg, blk.idx if blk is not None else 0, oi,
            op.type if op is not None else None,
            _site(op) if op is not None else ""))

    def var(self, blk, name: str):
        try:
            return blk._var_recursive(name)
        except KeyError:
            return None

    def shape_of(self, blk, name: str) -> Optional[Tuple[int, ...]]:
        v = self.var(blk, name)
        if v is None or v.shape is None:
            return None
        return tuple(int(self.batch) if int(d) < 0 else int(d)
                     for d in v.shape)

    def bytes_of(self, blk, name: str) -> int:
        from .liveness import _var_bytes

        v = self.var(blk, name)
        if v is None:
            return 0
        return _var_bytes(v, self.batch)[0]

    def spec_of(self, blk, name: str) -> Spec:
        sp = self.specs.get(name)
        if sp is not None:
            return sp
        shape = self.shape_of(blk, name)
        return normalize_spec((), len(shape) if shape else 0)

    def collective(self, kind: str, axis, name: str, nbytes: int, blk,
                   oi: int, reason: str) -> Optional[CollectiveEvent]:
        if nbytes <= 0:
            return None   # no recorded shape -> no meaningful volume
        axis = ",".join(axis) if isinstance(axis, (list, tuple)) else str(axis)
        ev = CollectiveEvent(blk.idx, oi, kind, axis, name, int(nbytes),
                             reason)
        self.collectives.append(ev)
        if kind == "all_reduce":
            self._ar_by_var[name] = ev
        return ev

    # -- spec validation (the PT730-PT733 input contract) -----------------
    def validate(self, name: str, spec: Sequence, blk, source: str) -> Spec:
        """Sanitize one assigned spec against the mesh and the var's
        recorded shape; offending dims degrade to None (replicated —
        conservative) after the diagnostic. PT733 divisibility applies to
        STATIC dims only — a ``-1`` dim is resolved at feed time, so its
        divisibility is the runtime contract (the per-chip plan re-checks
        it at the resolved batch and keeps indivisible dims whole)."""
        v = self.var(blk, name)
        shape = self.shape_of(blk, name)
        raw_shape = tuple(v.shape) if v is not None and v.shape is not None \
            else None
        ndim = len(shape) if shape is not None else len(tuple(spec))
        raw = tuple(spec or ())
        if shape is not None and len(raw) > len(shape):
            self.emit("PT731",
                      f"{source} spec {format_spec(raw)} for '{name}' names "
                      f"{len(raw)} dims but the var has shape {shape}",
                      blk, None, dedup_key=("PT731", name))
            raw = raw[:len(shape)]
        out: List[Optional[str]] = list(normalize_spec(raw, ndim))
        seen_axes: Set[str] = set()
        for d, axis in enumerate(out):
            if axis is None:
                continue
            if axis not in self.mesh:
                self.emit("PT730",
                          f"{source} spec for '{name}' shards dim {d} over "
                          f"axis '{axis}' but the mesh has axes "
                          f"{sorted(self.mesh)}",
                          blk, None, dedup_key=("PT730", name, axis))
                out[d] = None
                continue
            if axis in seen_axes:
                self.emit("PT732",
                          f"{source} spec for '{name}' uses mesh axis "
                          f"'{axis}' on two different dims — an axis can "
                          f"shard at most one dim",
                          blk, None, dedup_key=("PT732", name, axis))
                out[d] = None
                continue
            seen_axes.add(axis)
            n = self.mesh[axis]
            if (raw_shape is not None and d < len(raw_shape)
                    and int(raw_shape[d]) >= 0 and n > 1
                    and int(raw_shape[d]) % n):
                self.emit("PT733",
                          f"{source} spec shards '{name}' dim {d} "
                          f"(size {raw_shape[d]}) over axis '{axis}' of "
                          f"size {n} — not divisible; the dim is kept "
                          f"whole",
                          blk, None, dedup_key=("PT733", name, d))
                out[d] = None
        return tuple(out)

    # -- generic rules ----------------------------------------------------
    def _join_elementwise(self, op, blk, oi, out_name: str) -> Spec:
        """Output spec for a same-shape/broadcast op: dims aligned from
        the RIGHT (numpy broadcast); conflicting votes are PT734 and the
        first-seen axis wins (the other input is resharded)."""
        out_shape = self.shape_of(blk, out_name)
        if out_shape is None:
            return REPLICATED
        votes: List[Optional[str]] = [None] * len(out_shape)
        voters: List[Optional[str]] = [None] * len(out_shape)
        for in_name in op.input_arg_names:
            if in_name == EMPTY:
                continue
            in_shape = self.shape_of(blk, in_name)
            if in_shape is None:
                continue
            sp = self.spec_of(blk, in_name)
            sp = normalize_spec(sp, len(in_shape))
            off = len(out_shape) - len(in_shape)
            for d_in, axis in enumerate(sp):
                d_out = d_in + off
                if axis is None or d_out < 0:
                    continue
                if in_shape[d_in] == 1 or in_shape[d_in] != out_shape[d_out]:
                    continue   # broadcast dim carries no sharding vote
                if votes[d_out] is None:
                    votes[d_out] = axis
                    voters[d_out] = in_name
                elif votes[d_out] != axis:
                    self.emit(
                        "PT734",
                        f"op '{op.type}' inputs '{voters[d_out]}' and "
                        f"'{in_name}' shard the aligned dim {d_out} over "
                        f"'{votes[d_out]}' vs '{axis}' — '{in_name}' is "
                        f"resharded to agree",
                        blk, oi, op,
                        dedup_key=("PT734", blk.idx, oi, d_out))
                    self.collective(
                        "reshard", axis, in_name,
                        self.bytes_of(blk, in_name), blk, oi,
                        f"input layout conflict at '{op.type}'")
        return tuple(votes)

    def _reduce_collectives(self, op, blk, oi, out_specs: Dict[str, Spec]
                            ) -> None:
        """Shared partial-sum rule: an input sharded over axis α feeding an
        output that neither keeps α nor keeps the input's shape was reduced
        over sharded data — the output needs an all-reduce over α. Layout
        ops move data without summing, so they are exempt (their own rules
        record the gather/reshard they imply)."""
        if op.type in _LAYOUT_TYPES:
            return
        for out, osp in out_specs.items():
            out_shape = self.shape_of(blk, out)
            if out_shape is None:
                continue
            kept = {a for a in osp if a is not None}
            seen_axes: Set[str] = set()
            for in_name in op.input_arg_names:
                if in_name == EMPTY:
                    continue
                isp = self.specs.get(in_name)
                if not is_sharded(isp):
                    continue
                in_shape = self.shape_of(blk, in_name)
                if in_shape == out_shape:
                    continue
                for a in isp:
                    if a is None or a in kept or a in seen_axes:
                        continue
                    seen_axes.add(a)
                    self.collective(
                        "all_reduce", a, out, self.bytes_of(blk, out),
                        blk, oi,
                        f"'{op.type}' reduces over data sharded on "
                        f"'{a}' (partial sums per chip)")

    def _check_large_replication(self, op, blk, oi,
                                 out_specs: Dict[str, Spec],
                                 explained: Set[str]) -> None:
        """PT736 is for UNINTENDED replication (a sharding lost through a
        reshape, a big activation materialized whole); a value whose
        replication a recorded collective already explains — the DP grad
        all-reduce, the ZeRO param all-gather — is the accounted cost of
        the layout, not a finding."""
        any_sharded_in = any(is_sharded(self.specs.get(n))
                             for n in op.input_arg_names if n != EMPTY)
        if not any_sharded_in:
            return
        for out, osp in out_specs.items():
            if is_sharded(osp) or out in explained:
                continue
            nbytes = self.bytes_of(blk, out)
            if nbytes >= self.large:
                self.emit(
                    "PT736",
                    f"'{out}' ({nbytes / 2**20:.1f} MiB) comes out of "
                    f"'{op.type}' fully replicated although its inputs "
                    f"are sharded — every chip holds the whole tensor",
                    blk, oi, op, dedup_key=("PT736", out))

    # -- op dispatch ------------------------------------------------------
    def run_block(self, blk) -> None:
        if blk.idx in self._visited_blocks:
            return
        self._visited_blocks.add(blk.idx)
        for oi, op in enumerate(blk.ops):
            sub = op.attrs.get("sub_block")
            if isinstance(sub, int) and 0 <= sub < len(self.program.blocks):
                # sub-block vars get specs at the owning op's program point
                self.run_block(self.program.blocks[sub])
            self.run_op(op, blk, oi)

    def run_op(self, op, blk, oi) -> None:
        t = op.type
        if t in ("feed", "fetch"):
            return
        handler = _RULES.get(t)
        out_specs: Dict[str, Spec]
        n_coll = len(self.collectives)
        if handler is not None:
            out_specs = handler(self, op, blk, oi)
        elif t.endswith("_grad"):
            out_specs = self._grad_rule(op, blk, oi)
        else:
            out_specs = self._generic_rule(op, blk, oi)
        # composing rules (a dp-sharded feed meeting a param whose spec
        # also uses dp) can yield one axis on two dims — illegal as a
        # PartitionSpec; keep the first (outermost) occurrence
        for name, sp in out_specs.items():
            out_specs[name] = _dedup_axes(sp)
        self._reduce_collectives(op, blk, oi, out_specs)
        explained = {ev.var for ev in self.collectives[n_coll:]}
        self._check_large_replication(op, blk, oi, out_specs, explained)
        for name, sp in out_specs.items():
            self.specs[name] = sp

    def _generic_rule(self, op, blk, oi) -> Dict[str, Spec]:
        """Fallback: each output whose shape matches some input carries the
        elementwise join; an opaque output goes replicated, with PT744
        once per op type when sharding is actually being dropped."""
        out_specs: Dict[str, Spec] = {}
        opaque = False
        for out in op.output_arg_names:
            if out == EMPTY:
                continue
            sp = self._join_elementwise(op, blk, oi, out)
            out_specs[out] = sp
            if not is_sharded(sp):
                out_shape = self.shape_of(blk, out)
                if out_shape is not None and any(
                        self.shape_of(blk, n) == out_shape
                        for n in op.input_arg_names if n != EMPTY):
                    continue   # genuinely matched, inputs just unsharded
                opaque = True
        if opaque and op.type not in _KNOWN_REDUCTIONS \
                and op.type not in self._no_rule_types and any(
                is_sharded(self.specs.get(n))
                for n in op.input_arg_names if n != EMPTY):
            self._no_rule_types.add(op.type)
            self.emit("PT744",
                      f"no sharding propagation rule for op '{op.type}' — "
                      f"its outputs are treated as replicated "
                      f"(conservative for per-chip memory)",
                      blk, oi, op, dedup_key=("PT744", op.type))
        return out_specs

    def _grad_rule(self, op, blk, oi) -> Dict[str, Spec]:
        """Gradients co-locate with their forward var: ``X@GRAD`` gets
        ``X``'s spec. The shared reduce rule then inserts the data-parallel
        all-reduce for every param grad contracted over the sharded batch
        (the multi_devices_graph_pass AllReduceOpHandle, derived instead
        of built)."""
        out_specs: Dict[str, Spec] = {}
        for out in op.output_arg_names:
            if out == EMPTY:
                continue
            if out.endswith("@GRAD"):
                fwd = out[:-len("@GRAD")]
                sp = self.specs.get(fwd)
                if sp is None:
                    sp = self._join_elementwise(op, blk, oi, out)
                else:
                    shape = self.shape_of(blk, out)
                    sp = normalize_spec(sp, len(shape) if shape else len(sp))
                out_specs[out] = sp
            else:
                out_specs[out] = self._join_elementwise(op, blk, oi, out)
        return out_specs

    # -- matmul-class rules -----------------------------------------------
    def _contract(self, op, blk, oi, x, y, x_dims: Sequence[int],
                  y_dims: Sequence[int], out: str) -> Optional[str]:
        """Handle the contracted dims of a matmul-class op. Returns the
        axis both sides agree on (partial sums -> caller records the
        all-reduce via the shared reduce rule) or None."""
        xs = self.spec_of(blk, x)
        ys = self.spec_of(blk, y)
        ax = {xs[d] for d in x_dims if d < len(xs) and xs[d] is not None}
        ay = {ys[d] for d in y_dims if d < len(ys) and ys[d] is not None}
        if not ax and not ay:
            return None
        if ax == ay and len(ax) == 1:
            return next(iter(ax))
        if ax and ay and ax != ay:
            self.emit("PT735",
                      f"op '{op.type}': contracted dims of '{x}' are "
                      f"sharded over {sorted(ax)} but '{y}' over "
                      f"{sorted(ay)} — no partial-sum layout satisfies "
                      f"both; '{y}' is resharded",
                      blk, oi, op, dedup_key=("PT735", blk.idx, oi))
            self.collective("reshard", sorted(ay), y,
                            self.bytes_of(blk, y), blk, oi,
                            "contraction layout conflict")
            return next(iter(ax))
        # one side sharded, the other replicated: the sharded side's
        # contraction produces partials only if BOTH operands split the
        # contracted dim — with one side whole, GSPMD all-gathers the
        # sharded operand instead
        side, spec_axes = (x, ax) if ax else (y, ay)
        self.collective("all_gather", sorted(spec_axes), side,
                        self.bytes_of(blk, side), blk, oi,
                        f"contracted dim of '{side}' sharded on one side "
                        f"only")
        return None

    def _rule_mul(self, op, blk, oi) -> Dict[str, Spec]:
        x = (op.input("X") or [EMPTY])[0]
        y = (op.input("Y") or [EMPTY])[0]
        out = (op.output("Out") or [EMPTY])[0]
        xshape = self.shape_of(blk, x)
        yshape = self.shape_of(blk, y)
        oshape = self.shape_of(blk, out)
        if None in (xshape, yshape, oshape):
            return self._generic_rule(op, blk, oi)
        a = int(op.attr("x_num_col_dims") or 1)
        b = int(op.attr("y_num_col_dims") or 1)
        xs = normalize_spec(self.spec_of(blk, x), len(xshape))
        ys = normalize_spec(self.spec_of(blk, y), len(yshape))
        osp = list(normalize_spec((), len(oshape)))
        for d in range(min(a, len(osp))):
            osp[d] = xs[d]
        for d in range(b, len(yshape)):
            od = a + (d - b)
            if od < len(osp):
                osp[od] = ys[d]
        self._contract(op, blk, oi, x, y,
                       list(range(a, len(xshape))), list(range(b)), out)
        out_specs = {out: tuple(osp)}
        return out_specs

    def _rule_matmul(self, op, blk, oi) -> Dict[str, Spec]:
        x = (op.input("X") or [EMPTY])[0]
        y = (op.input("Y") or [EMPTY])[0]
        out = (op.output("Out") or [EMPTY])[0]
        xshape = self.shape_of(blk, x)
        yshape = self.shape_of(blk, y)
        oshape = self.shape_of(blk, out)
        if None in (xshape, yshape, oshape) or len(xshape) < 2 \
                or len(yshape) < 2:
            return self._generic_rule(op, blk, oi)
        tx = bool(op.attr("transpose_X"))
        ty = bool(op.attr("transpose_Y"))
        xs = normalize_spec(self.spec_of(blk, x), len(xshape))
        ys = normalize_spec(self.spec_of(blk, y), len(yshape))
        osp = list(normalize_spec((), len(oshape)))
        # batch dims: join of the two operands' leading dims
        for d in range(len(oshape) - 2):
            for sp, shape in ((xs, xshape), (ys, yshape)):
                off = len(oshape) - len(shape)
                di = d - off
                if 0 <= di < len(shape) - 2 and sp[di] is not None \
                        and shape[di] == oshape[d]:
                    osp[d] = osp[d] or sp[di]
        m_dim = -1 if tx else -2
        n_dim = -2 if ty else -1
        osp[-2] = xs[m_dim]
        osp[-1] = ys[n_dim]
        k_x = len(xshape) + (-2 if tx else -1)
        k_y = len(yshape) + (-1 if ty else -2)
        self._contract(op, blk, oi, x, y, [k_x], [k_y], out)
        return {out: tuple(osp)}

    def _rule_conv2d(self, op, blk, oi) -> Dict[str, Spec]:
        x = (op.input("Input") or [EMPTY])[0]
        w = (op.input("Filter") or [EMPTY])[0]
        out = (op.output("Output") or [EMPTY])[0]
        xshape = self.shape_of(blk, x)
        wshape = self.shape_of(blk, w)
        oshape = self.shape_of(blk, out)
        if None in (xshape, wshape, oshape) or len(oshape) < 4:
            return self._generic_rule(op, blk, oi)
        xs = normalize_spec(self.spec_of(blk, x), len(xshape))
        ws = normalize_spec(self.spec_of(blk, w), len(wshape))
        osp = list(normalize_spec((), len(oshape)))
        osp[0] = xs[0]          # batch
        osp[1] = ws[0]          # out channels follow the filter's Co
        for d in (2, 3):        # spatial sharding needs halo exchange:
            if xs[d] is not None:               # reshard conservative
                self.collective("reshard", xs[d], x,
                                self.bytes_of(blk, x), blk, oi,
                                "spatially sharded conv input (halo "
                                "exchange not modelled)")
        self._contract(op, blk, oi, x, w, [1], [1], out)
        return {out: tuple(osp)}

    def _rule_attention(self, op, blk, oi) -> Dict[str, Spec]:
        q = (op.input("Q") or [EMPTY])[0]
        out = (op.output("Out") or [EMPTY])[0]
        qshape = self.shape_of(blk, q)
        oshape = self.shape_of(blk, out)
        if qshape is None or oshape is None:
            return self._generic_rule(op, blk, oi)
        qs = normalize_spec(self.spec_of(blk, q), len(qshape))
        # K/V rotated around the ring when the sequence dim is sharded:
        # wire volume == one all-gather of K and V
        for slot in ("K", "V"):
            name = (op.input(slot) or [EMPTY])[0]
            if name == EMPTY:
                continue
            sp = self.specs.get(name)
            shape = self.shape_of(blk, name)
            if sp is None or shape is None or len(shape) < 2:
                continue
            seq_axis = normalize_spec(sp, len(shape))[-2]
            if seq_axis is not None:
                self.collective("all_gather", seq_axis, name,
                                self.bytes_of(blk, name), blk, oi,
                                "ring/sequence-parallel attention K/V "
                                "rotation")
        out_specs = {out: normalize_spec(qs, len(oshape))}
        for extra in op.output_arg_names:
            if extra != EMPTY and extra != out:
                out_specs[extra] = self._join_elementwise(op, blk, oi, extra)
        return out_specs

    # -- layout/shape ops -------------------------------------------------
    def _rule_reshape(self, op, blk, oi) -> Dict[str, Spec]:
        x = (op.input("X") or [EMPTY])[0]
        out = (op.output("Out") or [EMPTY])[0]
        xshape = self.shape_of(blk, x)
        oshape = self.shape_of(blk, out)
        out_specs: Dict[str, Spec] = {}
        for extra in op.output_arg_names:    # XShape echo: replicated
            if extra not in (EMPTY, out):
                out_specs[extra] = REPLICATED
        if xshape is None or oshape is None:
            out_specs[out] = REPLICATED
            return out_specs
        xs = normalize_spec(self.spec_of(blk, x), len(xshape))
        osp = list(normalize_spec((), len(oshape)))
        carried: Set[str] = set()
        # leading dims carry while the prefix sizes agree (batch survives
        # [B, H, W] -> [B, H*W]); trailing dims likewise from the right
        for d in range(min(len(xshape), len(oshape))):
            if xshape[d] != oshape[d]:
                break
            if xs[d] is not None:
                osp[d] = xs[d]
                carried.add(xs[d])
        for d in range(1, min(len(xshape), len(oshape)) + 1):
            if xshape[-d] != oshape[-d] or osp[-d] is not None:
                break
            if xs[-d] is not None and xs[-d] not in carried:
                osp[-d] = xs[-d]
                carried.add(xs[-d])
        lost = [a for a in xs if a is not None and a not in carried]
        if lost:
            self.collective("all_gather", lost, x, self.bytes_of(blk, x),
                            blk, oi,
                            f"'{op.type}' folds a dim sharded on "
                            f"{lost} into a new shape")
        out_specs[out] = tuple(osp)
        return out_specs

    def _rule_transpose(self, op, blk, oi) -> Dict[str, Spec]:
        x = (op.input("X") or [EMPTY])[0]
        out = (op.output("Out") or [EMPTY])[0]
        xshape = self.shape_of(blk, x)
        perm = op.attr("axis")
        out_specs: Dict[str, Spec] = {}
        for extra in op.output_arg_names:
            if extra not in (EMPTY, out):
                out_specs[extra] = REPLICATED
        if xshape is None or not perm:
            out_specs[out] = REPLICATED
            return out_specs
        xs = normalize_spec(self.spec_of(blk, x), len(xshape))
        out_specs[out] = tuple(xs[p] if 0 <= p < len(xs) else None
                               for p in perm)
        return out_specs

    def _rule_concat(self, op, blk, oi) -> Dict[str, Spec]:
        out = (op.output("Out") or [EMPTY])[0]
        axis = int(op.attr("axis") or 0)
        sp = self._join_elementwise(op, blk, oi, out)
        oshape = self.shape_of(blk, out)
        if oshape is None:
            return {out: REPLICATED}
        if axis < 0:
            axis += len(oshape)
        sp = list(normalize_spec(sp, len(oshape)))
        for in_name in op.input_arg_names:
            isp = self.specs.get(in_name)
            ishape = self.shape_of(blk, in_name)
            if isp is None or ishape is None or axis >= len(ishape):
                continue
            a = normalize_spec(isp, len(ishape))[axis]
            if a is not None:
                self.collective("all_gather", a, in_name,
                                self.bytes_of(blk, in_name), blk, oi,
                                "concat along a sharded dim")
        if 0 <= axis < len(sp):
            sp[axis] = None    # the concatenated dim cannot stay sharded
        return {out: tuple(sp)}

    def _rule_slice(self, op, blk, oi) -> Dict[str, Spec]:
        x = (op.input("Input") or op.input("X") or [EMPTY])[0]
        out = (op.output("Out") or [EMPTY])[0]
        xshape = self.shape_of(blk, x)
        oshape = self.shape_of(blk, out)
        if xshape is None or oshape is None or len(xshape) != len(oshape):
            return self._generic_rule(op, blk, oi)
        xs = normalize_spec(self.spec_of(blk, x), len(xshape))
        osp = []
        for d in range(len(xshape)):
            if xshape[d] == oshape[d]:
                osp.append(xs[d])
            else:
                if xs[d] is not None:
                    self.collective("all_gather", xs[d], x,
                                    self.bytes_of(blk, x), blk, oi,
                                    "slicing a sharded dim")
                osp.append(None)
        return {out: tuple(osp)}

    def _rule_lookup_table(self, op, blk, oi) -> Dict[str, Spec]:
        w = (op.input("W") or [EMPTY])[0]
        ids = (op.input("Ids") or [EMPTY])[0]
        out = (op.output("Out") or [EMPTY])[0]
        oshape = self.shape_of(blk, out)
        if oshape is None:
            return self._generic_rule(op, blk, oi)
        ids_spec = self.spec_of(blk, ids)
        w_spec = self.spec_of(blk, w)
        osp = list(normalize_spec((), len(oshape)))
        if ids_spec:
            osp[0] = ids_spec[0]
        if len(w_spec) >= 2 and w_spec[1] is not None:
            osp[-1] = w_spec[1]
        if w_spec and w_spec[0] is not None:
            # vocab-sharded table: the gather lowers to per-shard partial
            # one-hot contractions + an all-reduce of the dense result
            self.collective("all_reduce", w_spec[0], out,
                            self.bytes_of(blk, out), blk, oi,
                            "vocab-sharded embedding lookup")
        return {out: tuple(osp)}

    def _rule_fill_like(self, op, blk, oi) -> Dict[str, Spec]:
        # fill_constant_batch_size_like: dim0 follows the reference input
        out = (op.output("Out") or [EMPTY])[0]
        ref = (op.input("Input") or [EMPTY])[0]
        oshape = self.shape_of(blk, out)
        if oshape is None:
            return {out: REPLICATED} if out != EMPTY else {}
        osp = list(normalize_spec((), len(oshape)))
        rsp = self.specs.get(ref)
        if rsp and rsp[0] is not None:
            osp[0] = rsp[0]
        return {out: tuple(osp)}

    # -- the optimizer update (PT738/PT739/PT740 + the ZeRO rewrite) ------
    def _rule_optimizer(self, op, blk, oi) -> Dict[str, Spec]:
        param = (op.input("Param") or [EMPTY])[0]
        grad = (op.input("Grad") or [EMPTY])[0]
        p_spec = self.spec_of(blk, param)
        g_spec = self.spec_of(blk, grad)
        p_shape = self.shape_of(blk, param)
        if p_shape is not None:
            p_spec = normalize_spec(p_spec, len(p_shape))
            g_spec = normalize_spec(g_spec, len(p_shape))
        out_specs: Dict[str, Spec] = {}
        if g_spec != p_spec and (is_sharded(g_spec) or is_sharded(p_spec)):
            self.emit("PT738",
                      f"op '{op.type}': gradient '{grad}' arrives "
                      f"{format_spec(g_spec)} but param '{param}' is "
                      f"{format_spec(p_spec)} — the grad is resharded "
                      f"every step",
                      blk, oi, op, dedup_key=("PT738", param))
            self.collective("reshard",
                            [a for a in set(g_spec) | set(p_spec) if a],
                            grad, self.bytes_of(blk, grad), blk, oi,
                            "grad/param layout disagreement")
        dp_like = None
        for slot in _OPT_STATE_SLOTS:
            for name in op.input(slot):
                if name == EMPTY:
                    continue
                s_spec = self.spec_of(blk, name)
                s_shape = self.shape_of(blk, name)
                if s_shape is not None:
                    s_spec = normalize_spec(s_spec, len(s_shape))
                ndim = max(len(s_spec), len(p_spec))
                if normalize_spec(s_spec, ndim) \
                        == normalize_spec(p_spec, ndim):
                    continue
                if not is_sharded(s_spec) and not is_sharded(p_spec):
                    continue
                zero_axis = s_spec[0] if s_spec else None
                if (zero_axis == "dp" and not is_sharded(p_spec)
                        and all(a is None for a in s_spec[1:])):
                    dp_like = name
                    continue
                self.emit("PT739",
                          f"op '{op.type}': optimizer state '{name}' is "
                          f"{format_spec(s_spec)} but param '{param}' is "
                          f"{format_spec(p_spec)} — not the ZeRO "
                          f"dim-0-over-dp layout; the update resharding "
                          f"is paid every step",
                          blk, oi, op, dedup_key=("PT739", name))
        if dp_like is not None:
            self.emit("PT740",
                      f"op '{op.type}': ZeRO layout on '{param}' — "
                      f"optimizer state (e.g. '{dp_like}') sharded over "
                      f"'dp', param replicated: each step pays a grad "
                      f"reduce-scatter + a param all-gather",
                      blk, oi, op, dedup_key=("PT740", param))
            # the grad's earlier all-reduce becomes a reduce-scatter into
            # the sharded update, and the fresh param is all-gathered:
            # rewrite the recorded event rather than double-count
            ar = self._ar_by_var.pop(grad, None)
            if ar is not None and ar in self.collectives:
                self.collectives.remove(ar)
            self.collective("reduce_scatter", "dp", grad,
                            self.bytes_of(blk, grad), blk, oi,
                            "ZeRO-1: grads reduce-scattered into the "
                            "sharded update")
            self.collective("all_gather", "dp", param,
                            self.bytes_of(blk, param), blk, oi,
                            "ZeRO-1: fresh params all-gathered after the "
                            "sharded update")
        # in-place contract: every output keeps its own var's assigned spec
        for out in op.output_arg_names:
            if out != EMPTY:
                out_specs[out] = self.spec_of(blk, out)
        return out_specs


def _rule_same_as_input(slot_in: str, slot_out: str):
    def rule(self: _Propagator, op, blk, oi) -> Dict[str, Spec]:
        x = (op.input(slot_in) or [EMPTY])[0]
        out = (op.output(slot_out) or [EMPTY])[0]
        oshape = self.shape_of(blk, out)
        sp = self.spec_of(blk, x)
        out_specs = {out: normalize_spec(sp, len(oshape))
                     if oshape is not None else REPLICATED}
        for extra in op.output_arg_names:
            if extra not in (EMPTY, out):
                out_specs[extra] = self._join_elementwise(
                    op, blk, oi, extra)
        return out_specs
    return rule


_RULES = {
    "mul": _Propagator._rule_mul,
    "matmul": _Propagator._rule_matmul,
    "conv2d": _Propagator._rule_conv2d,
    "depthwise_conv2d": _Propagator._rule_conv2d,
    "fused_multihead_attention": _Propagator._rule_attention,
    "reshape2": _Propagator._rule_reshape,
    "squeeze2": _Propagator._rule_reshape,
    "unsqueeze2": _Propagator._rule_reshape,
    "flatten2": _Propagator._rule_reshape,
    "transpose2": _Propagator._rule_transpose,
    "concat": _Propagator._rule_concat,
    "slice": _Propagator._rule_slice,
    "lookup_table": _Propagator._rule_lookup_table,
    "fill_constant_batch_size_like": _Propagator._rule_fill_like,
    "batch_norm": _rule_same_as_input("X", "Y"),
    "layer_norm": _rule_same_as_input("X", "Y"),
    "softmax": _rule_same_as_input("X", "Out"),
    "dropout": _rule_same_as_input("X", "Out"),
    "softmax_with_cross_entropy": _rule_same_as_input("Logits", "Softmax"),
}

# optimizer ops share one rule, detected by slots at dispatch time
for _t in ("sgd", "momentum", "lars_momentum", "adam", "adamw", "adamax",
           "adagrad", "decayed_adagrad", "adadelta", "rmsprop", "ftrl"):
    _RULES[_t] = _Propagator._rule_optimizer


# ---------------------------------------------------------------------------
# the entry points
# ---------------------------------------------------------------------------

def propagate_sharding(program, mesh: Dict[str, int],
                       param_specs: Optional[Dict[str, Sequence]] = None,
                       feed_spec: Optional[Sequence] = None,
                       feed_names: Sequence[str] = (),
                       fetch_names: Sequence[str] = (),
                       batch_size: int = 1,
                       liveness_info: Optional[dict] = None,
                       large_bytes: int = LARGE_BYTES_DEFAULT
                       ) -> ShardingAnalysis:
    """Propagate shard specs from the per-param assignment + feed spec
    through every op of ``program`` (sub-blocks walked at their owning
    op). Returns the :class:`ShardingAnalysis`; diagnostics accumulate on
    ``analysis.diagnostics`` (the registered pass forwards them to the
    PassContext)."""
    prop = _Propagator(program, mesh, batch_size, large_bytes)
    gb = program.global_block
    fetch = {getattr(f, "name", f) for f in (fetch_names or ())}
    dp = prop.mesh.get("dp", 1)
    if feed_spec is None:
        feed_spec = ("dp",) if dp > 1 else ()

    feeds = {v.name for v in gb.vars.values() if v.is_data}
    feeds.update(feed_names or ())

    # 1. feeds: batch-sharded (PT742 when the mesh has dp but the feed
    #    spec does not engage it)
    for name in sorted(feeds):
        v = prop.var(gb, name)
        if v is None:
            continue
        sp = prop.validate(name, feed_spec, gb, "feed")
        prop.specs[name] = sp
        if dp > 1 and "dp" not in {a for a in sp if a}:
            prop.emit("PT742",
                      f"feed '{name}' is not sharded over 'dp' "
                      f"(mesh dp={dp}) — the global batch rides every "
                      f"chip whole; data parallelism is not engaged",
                      gb, None, dedup_key=("PT742", name))

    # 2. params / persistable state: the caller's assignment
    assigned: Dict[str, Spec] = {}
    param_specs = dict(param_specs or {})
    for blk in program.blocks:
        for v in blk.vars.values():
            if not v.persistable or v.name in feeds:
                continue
            raw = param_specs.get(v.name, ())
            sp = prop.validate(v.name, raw, blk, "param")
            assigned[v.name] = sp
            prop.specs[v.name] = sp

    # 3. the walk
    prop.run_block(gb)

    # 4. state-loop / donation / fetch checks on the final specs
    persistable = {v.name for blk in program.blocks
                   for v in blk.vars.values() if v.persistable}
    for name in sorted(persistable):
        in_spec = assigned.get(name, REPLICATED)
        out_spec = prop.specs.get(name, in_spec)
        shape = prop.shape_of(gb, name)
        ndim = len(shape) if shape else max(len(in_spec), len(out_spec))
        if normalize_spec(in_spec, ndim) != normalize_spec(out_spec, ndim):
            prop.emit("PT737",
                      f"persistable '{name}' enters the step "
                      f"{format_spec(in_spec)} but is produced "
                      f"{format_spec(out_spec)} — the training loop pays "
                      f"this layout change every step",
                      gb, None, dedup_key=("PT737", name))
            prop.collective("reshard",
                            [a for a in set(in_spec) | set(out_spec) if a],
                            name, prop.bytes_of(gb, name), gb,
                            max(len(gb.ops) - 1, 0),
                            "state layout change across the step "
                            "boundary")
            if liveness_info is not None:
                cands = liveness_info.get("cands", set())
                unsafe = liveness_info.get("unsafe", {})
                if name in cands and name not in unsafe:
                    prop.emit(
                        "PT741",
                        f"'{name}' is liveness-proven donatable but its "
                        f"input layout {format_spec(in_spec)} differs "
                        f"from its output layout {format_spec(out_spec)}"
                        f" — the donated buffer cannot be reused in "
                        f"place; the step pays an extra copy",
                        gb, None, dedup_key=("PT741", name))

    for name in sorted(fetch):
        sp = prop.specs.get(name)
        if is_sharded(sp):
            prop.emit("PT743",
                      f"fetch '{name}' is {format_spec(sp)} — the "
                      f"executor pins fetches replicated, so every step "
                      f"all-gathers it",
                      gb, None, dedup_key=("PT743", name))
            prop.collective("all_gather",
                            [a for a in sp if a is not None], name,
                            prop.bytes_of(gb, name), gb,
                            max(len(gb.ops) - 1, 0),
                            "sharded value fetched (fetches are pinned "
                            "replicated)")

    return ShardingAnalysis(
        mesh=prop.mesh, batch_size=prop.batch,
        var_specs=dict(prop.specs),
        param_specs=assigned,
        feed_spec=normalize_spec(feed_spec, len(tuple(feed_spec or ()))),
        collectives=list(prop.collectives),
        diagnostics=list(prop.diags))


def check_sharding(program, ctx) -> Optional[ShardingAnalysis]:
    """The registered ``sharding_check`` pass body. Inputs come from
    ``ctx.options``:

    * ``mesh``      — ``{"dp": 8, ...}``; absent => silent no-op (None).
    * ``specs``     — per-param spec dict; default: derived from the
      program via ``parallel.sharding.extract_param_specs`` (honouring
      ``options["zero"]`` / an ``options["build_strategy"]``).
    * ``feed_spec`` — default ``("dp",)`` when the mesh has dp.
    * ``large_bytes`` — PT736 threshold (default 1 MiB).
    """
    mesh = ctx.options.get("mesh")
    if not mesh:
        return None
    specs = ctx.options.get("specs")
    feed_spec = ctx.options.get("feed_spec")
    if specs is None:
        from ..parallel.sharding import extract_param_specs

        bs = ctx.options.get("build_strategy")
        zero = bool(ctx.options.get("zero"))
        specs, derived_feed = extract_param_specs(
            program, mesh, build_strategy=bs, zero=zero)
        if feed_spec is None:
            feed_spec = derived_feed
    live_info = ctx.analysis("liveness")
    analysis = propagate_sharding(
        program, mesh,
        param_specs=specs,
        feed_spec=feed_spec,
        feed_names=ctx.feed_names,
        fetch_names=ctx.fetch_names,
        batch_size=ctx.batch_size,
        liveness_info=live_info,
        large_bytes=int(ctx.options.get("large_bytes",
                                        LARGE_BYTES_DEFAULT)))
    for d in analysis.diagnostics:
        ctx.report(d)
    return analysis
