"""Static FLOP / byte cost model over the Program IR (Pass ``cost_model``).

The MFU push (ROADMAP item 4; CODA arXiv 2605.19269, "Learning to
Optimize Tensor Programs" arXiv 1805.08166) needs per-program FLOP/byte
accounting the framework never computed: measured TF/s is only meaningful
against the program's MODEL FLOPs, and fusion/autotuning decisions need
arithmetic intensity (FLOPs per byte moved). This pass derives both from
the ``infer_shape`` metadata already recorded on every var at build time —
no execution, no tracing, one walk over the ops.

Convention (docs/PERF_NOTES.md "Cost model"): **one multiply-add = 2
FLOPs** (the 6ND convention the BERT analytics already used). Matmul-class
ops are exact MAC counts; normalization/activation/optimizer ops use small
per-element constants (they are <2% of any matmul-bearing program);
unknown ops default to one FLOP per output element. Backward ops of the
matmul class cost exactly 2x their forward (dgrad + wgrad), computed from
the forward slots the grad op carries.

Consumers:

* ``monitor`` caches one :class:`CostReport` per (program, batch) and
  turns measured step durations into ``executor_mfu`` / achieved-TF/s
  gauges (per program serial and shape bucket);
* ``ServingEngine`` emits the same per (bucket) after every batch;
* ``bench.py`` reports cost-model FLOPs next to the hand-derived
  analytic counts (the two must agree within 10% — the
  ``tools/trace_check.py`` CI gate asserts it);
* registered as analysis pass ``cost_model`` so lint pipelines and
  custom passes can require it (``ctx.analysis("cost_model")``).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ..core import registry
from .liveness import _var_bytes

__all__ = ["CostReport", "estimate_cost", "op_flops", "check_cost_model",
           "MATMUL_CLASS", "CommsReport", "estimate_comms",
           "comms_compute_ratio"]

EMPTY = "@EMPTY@"

# ops whose grads cost exactly 2x forward (dgrad + wgrad / dQKV)
MATMUL_CLASS = frozenset({"conv2d", "mul", "matmul",
                          "fused_multihead_attention"})

# small per-element constants for the non-matmul tail (normalizations,
# activations with transcendentals, optimizers). Deliberately coarse:
# on any matmul-bearing program these are noise, and the model's
# accuracy contract (±10% of analytic counts) is gated on the real
# ResNet-50/BERT programs by tools/trace_check.py.
_PER_ELEM = {
    "relu": 1, "relu6": 1, "leaky_relu": 2, "sigmoid": 4, "tanh": 6,
    "gelu": 10, "swish": 5, "elu": 3, "softplus": 4, "softsign": 2,
    "exp": 4, "log": 4, "sqrt": 2, "rsqrt": 2, "square": 1, "abs": 1,
    "scale": 2, "cast": 1, "dropout": 2, "softmax": 5,
    "batch_norm": 5, "layer_norm": 8, "instance_norm": 8,
    "group_norm": 8, "softmax_with_cross_entropy": 7,
    "cross_entropy": 3, "cross_entropy2": 3, "mean": 1, "sum": 1,
    "momentum": 4, "sgd": 2, "adam": 12, "adamax": 8, "adagrad": 6,
    "rmsprop": 8, "lars_momentum": 8,
}


@dataclasses.dataclass
class CostReport:
    """Per-program static cost at one batch size."""

    batch_size: int
    flops_total: float          # fwd + bwd + optimizer, 2 FLOPs per MAC
    flops_forward: float
    flops_backward: float
    flops_optimizer: float      # optimize + lr_sched role ops
    flops_by_op_type: Dict[str, float]
    activation_bytes: int       # non-persistable op outputs, batch-resolved
    param_bytes: int            # persistable vars
    n_ops: int
    unknown_ops: List[str]      # op types costed by the 1-FLOP/elem default

    @property
    def flops_per_byte(self) -> float:
        """Arithmetic intensity against activations + params (the
        roofline x-axis; a coarse lower bound — reuse within fused
        regions only helps)."""
        denom = self.activation_bytes + self.param_bytes
        return self.flops_total / denom if denom else 0.0

    def mfu(self, seconds_per_step: float,
            peak_tflops: Optional[float] = None) -> float:
        """Model FLOP utilisation of one measured step."""
        if peak_tflops is None:
            from ..flags import flag

            peak_tflops = float(flag("device_peak_tflops"))
        if seconds_per_step <= 0 or peak_tflops <= 0:
            return 0.0
        return self.flops_total / seconds_per_step / (peak_tflops * 1e12)

    def to_dict(self) -> dict:
        top = sorted(self.flops_by_op_type.items(),
                     key=lambda kv: -kv[1])[:12]
        return {"batch_size": self.batch_size,
                "flops_total": self.flops_total,
                "flops_forward": self.flops_forward,
                "flops_backward": self.flops_backward,
                "flops_optimizer": self.flops_optimizer,
                "gflops_total": round(self.flops_total / 1e9, 3),
                "flops_by_op_type": {k: v for k, v in top},
                "activation_bytes": self.activation_bytes,
                "param_bytes": self.param_bytes,
                "flops_per_byte": round(self.flops_per_byte, 2),
                "n_ops": self.n_ops,
                "unknown_ops": sorted(set(self.unknown_ops))}


# ---------------------------------------------------------------------------
# shape helpers
# ---------------------------------------------------------------------------

def _shape(blk, name: str, batch: int) -> Optional[Tuple[int, ...]]:
    """Recorded (build-time infer_shape) shape with -1 dims resolved to
    ``batch`` — the same resolution rule as ``memory_plan``."""
    if name == EMPTY or not blk.has_var_recursive(name):
        return None
    v = blk._var_recursive(name)
    if v.shape is None:
        return None
    return tuple(int(batch) if int(d) < 0 else int(d) for d in v.shape)


def _numel(shape: Optional[Tuple[int, ...]]) -> int:
    if shape is None:
        return 0
    n = 1
    for d in shape:
        n *= max(int(d), 0)
    return n


def _slot_shape(blk, op, slot: str, batch: int):
    # grad ops carry the forward slots renamed: '__out__Output' (the
    # forward output fed back in) and 'Output@GRAD' share the forward
    # output's shape, so a matmul-class grad can be costed from its own
    # slots without looking up the forward op
    for s in (slot, "__out__" + slot, slot + "@GRAD"):
        names = op.input(s) or op.output(s)
        if names:
            return _shape(blk, names[0], batch)
    return None


def _out_numel(blk, op, batch: int) -> int:
    return sum(_numel(_shape(blk, n, batch))
               for n in op.output_arg_names if n != EMPTY)


# ---------------------------------------------------------------------------
# per-op FLOP rules
# ---------------------------------------------------------------------------

def _flops_conv2d(blk, op, batch: int) -> Optional[float]:
    out = _slot_shape(blk, op, "Output", batch)
    filt = _slot_shape(blk, op, "Filter", batch)
    if out is None or filt is None or len(filt) < 4:
        return None
    groups = max(1, int(op.attr("groups") or 1))
    # Filter is [Co, Cin/groups, kh, kw]: macs per output element =
    # (Cin/groups)*kh*kw; groups is already folded into the filter shape
    macs_per_out = filt[1] * filt[2] * filt[3]
    del groups
    return 2.0 * _numel(out) * macs_per_out


def _flops_mul(blk, op, batch: int) -> Optional[float]:
    x = _slot_shape(blk, op, "X", batch)
    y = _slot_shape(blk, op, "Y", batch)
    if x is None or y is None:
        return None
    a = int(op.attr("x_num_col_dims") or 1)
    b = int(op.attr("y_num_col_dims") or 1)
    m = _numel(x[:a])
    k = _numel(x[a:])
    n = _numel(y[b:])
    return 2.0 * m * k * n


def _flops_matmul(blk, op, batch: int) -> Optional[float]:
    x = _slot_shape(blk, op, "X", batch)
    out = _slot_shape(blk, op, "Out", batch)
    if x is None or out is None or not x:
        return None
    k = x[-2] if op.attr("transpose_X") else x[-1]
    return 2.0 * _numel(out) * int(k)


def _flops_attention(blk, op, batch: int) -> Optional[float]:
    q = _slot_shape(blk, op, "Q", batch)
    k = _slot_shape(blk, op, "K", batch)
    if q is None or len(q) < 4:
        return None
    b, h, s_q, dh = q[-4], q[-3], q[-2], q[-1]
    s_k = k[-2] if k is not None and len(k) >= 2 else s_q
    # QK^T (2*b*h*s_q*s_k*dh) + PV (2*b*h*s_q*s_k*dh); causal masking
    # halves the useful work but the kernel still computes the tiles, so
    # count the full rectangle (this is a COST model, not a utility one)
    return 4.0 * b * h * s_q * s_k * dh


_MATMUL_RULES = {
    "conv2d": _flops_conv2d,
    "depthwise_conv2d": _flops_conv2d,
    "mul": _flops_mul,
    "matmul": _flops_matmul,
    "fused_multihead_attention": _flops_attention,
}


def op_flops(blk, op, batch: int) -> Tuple[float, bool]:
    """(flops, known_rule) for one op at ``batch``. Grad ops of the
    matmul class cost 2x their forward rule computed from the forward
    slots the grad op carries; other grads and unknown ops default to
    one FLOP per output element."""
    t = op.type
    if t in ("feed", "fetch", "fill_constant", "lookup_table",
             "lookup_table_grad", "shape", "recompute_segment"):
        return 0.0, True
    if t in _MATMUL_RULES:
        f = _MATMUL_RULES[t](blk, op, batch)
        if f is not None:
            return f, True
        return float(_out_numel(blk, op, batch)), False
    if t.endswith("_grad"):
        base = t[:-5]
        if base in _MATMUL_RULES:
            f = _MATMUL_RULES[base](blk, op, batch)
            if f is not None:
                return 2.0 * f, True
        c = _PER_ELEM.get(base)
        if c is not None:
            return float(c) * _out_numel(blk, op, batch), True
        # grads of registered ops: 1 FLOP per grad-output element is a
        # fair default (elementwise/view grads); unregistered stay unknown
        return (float(_out_numel(blk, op, batch)),
                registry.has_op(base))
    c = _PER_ELEM.get(t)
    if c is not None:
        return float(c) * _out_numel(blk, op, batch), True
    if t == "pool2d":
        out = _slot_shape(blk, op, "Out", batch)
        x = _slot_shape(blk, op, "X", batch)
        if op.attr("global_pooling"):
            return float(_numel(x)), True
        ks = op.attr("ksize") or op.attr("pool_size") or 1
        kk = _numel(tuple(ks)) if isinstance(ks, (list, tuple)) else int(ks)
        return float(_numel(out)) * max(1, kk), True
    return float(_out_numel(blk, op, batch)), registry.has_op(t)


# ---------------------------------------------------------------------------
# the program walk
# ---------------------------------------------------------------------------

def estimate_cost(program, batch_size: int = 1) -> CostReport:
    """One :class:`CostReport` for the global block at ``batch_size``
    (sub-block ops — while/cond bodies — are counted once; the model has
    no trip counts, and none of the zoo's hot programs loop)."""
    from ..framework import OpRole

    batch = max(1, int(batch_size))
    by_type: Dict[str, float] = {}
    fwd = bwd = opt = 0.0
    unknown: List[str] = []
    n_ops = 0
    act_bytes = 0
    seen_out: set = set()
    for blk in program.blocks:
        for op in blk.ops:
            if op.type in ("feed", "fetch"):
                continue
            n_ops += 1
            f, known = op_flops(blk, op, batch)
            if not known:
                unknown.append(op.type)
            if f:
                by_type[op.type] = by_type.get(op.type, 0.0) + f
                role = op.attrs.get("__op_role__", OpRole.Forward)
                if role == OpRole.Backward:
                    bwd += f
                elif role in (OpRole.Optimize, OpRole.LRSched):
                    opt += f
                else:
                    fwd += f
            for name in op.output_arg_names:
                if name == EMPTY or name in seen_out \
                        or not blk.has_var(name):
                    continue
                seen_out.add(name)
                v = blk.var(name)
                if not v.persistable:
                    act_bytes += _var_bytes(v, batch)[0]
    param_bytes = sum(_var_bytes(v, batch)[0]
                      for b in program.blocks
                      for v in b.vars.values() if v.persistable)
    return CostReport(batch_size=batch, flops_total=fwd + bwd + opt,
                      flops_forward=fwd, flops_backward=bwd,
                      flops_optimizer=opt, flops_by_op_type=by_type,
                      activation_bytes=int(act_bytes),
                      param_bytes=int(param_bytes), n_ops=n_ops,
                      unknown_ops=unknown)


def check_cost_model(program, ctx) -> CostReport:
    """The registered ``cost_model`` analysis pass body: estimate at the
    context's batch size; the report is cached on the PassContext
    (``ctx.analysis("cost_model")``). Reports no diagnostics — cost is
    information, not a finding."""
    return estimate_cost(program, batch_size=ctx.batch_size)


# ---------------------------------------------------------------------------
# per-op collective volumes (from sharding_check spec transitions)
# ---------------------------------------------------------------------------

# per-chip wire bytes of one collective over an axis of size n, as a
# fraction of the FULL tensor bytes (ring algorithms; docs/PERF_NOTES.md
# "Collective volumes"):
#   all_reduce     2*(n-1)/n   (reduce-scatter + all-gather)
#   all_gather       (n-1)/n
#   reduce_scatter   (n-1)/n
#   reshard          (n-1)/n   (all-to-all-class layout change, upper bound)
def _wire_fraction(kind: str, n: int) -> float:
    if n <= 1:
        return 0.0
    f = (n - 1) / n
    return 2.0 * f if kind == "all_reduce" else f


@dataclasses.dataclass
class CommsReport:
    """Per-chip collective wire volume of one step under a sharding
    assignment (derived from ``sharding_check`` spec transitions — the
    static face of the AllReduceOpHandles the reference builder placed
    by hand)."""

    mesh: Dict[str, int]
    events: List[dict]              # CollectiveEvent.to_dict + wire bytes
    wire_bytes_by_kind: Dict[str, int]
    total_wire_bytes: int           # per chip, per step

    @property
    def gbytes_per_step(self) -> float:
        return self.total_wire_bytes / 1e9

    def comms_seconds(self, ici_gbytes_per_s: Optional[float] = None
                      ) -> float:
        """Predicted time on the wire per step (per chip), against the
        effective ICI bandwidth (``FLAGS_ici_gbytes_per_s``)."""
        if ici_gbytes_per_s is None:
            from ..flags import flag

            ici_gbytes_per_s = float(flag("ici_gbytes_per_s"))
        if ici_gbytes_per_s <= 0:
            return 0.0
        return self.total_wire_bytes / (ici_gbytes_per_s * 1e9)

    def to_dict(self) -> dict:
        return {"mesh": dict(self.mesh),
                "total_wire_bytes_per_chip": self.total_wire_bytes,
                "gbytes_per_step": round(self.gbytes_per_step, 6),
                "wire_bytes_by_kind": dict(self.wire_bytes_by_kind),
                "events": self.events}


def estimate_comms(analysis) -> CommsReport:
    """Convert a :class:`sharding_check.ShardingAnalysis`'s collective
    events into per-chip wire volumes."""
    mesh = dict(analysis.mesh)
    by_kind: Dict[str, int] = {}
    events: List[dict] = []
    total = 0
    for ev in analysis.collectives:
        n = ev.axis_size(mesh)
        wire = int(ev.bytes_full * _wire_fraction(ev.kind, n))
        d = ev.to_dict()
        d["wire_bytes_per_chip"] = wire
        events.append(d)
        by_kind[ev.kind] = by_kind.get(ev.kind, 0) + wire
        total += wire
    return CommsReport(mesh=mesh, events=events,
                       wire_bytes_by_kind=by_kind, total_wire_bytes=total)


def comms_compute_ratio(comms: CommsReport, cost: CostReport,
                        peak_tflops: Optional[float] = None,
                        ici_gbytes_per_s: Optional[float] = None) -> float:
    """Predicted comms-vs-compute ratio of one step: time on the wire over
    time in the MXUs, both per chip (compute FLOPs divide by the mesh's
    device count — the data-parallel split; >1.0 means the step is
    predicted communication-bound)."""
    if peak_tflops is None:
        from ..flags import flag

        peak_tflops = float(flag("device_peak_tflops"))
    n_dev = 1
    for s in comms.mesh.values():
        n_dev *= int(s)
    if peak_tflops <= 0 or cost.flops_total <= 0:
        return 0.0
    compute_s = (cost.flops_total / max(n_dev, 1)) / (peak_tflops * 1e12)
    if compute_s <= 0:
        return 0.0
    return comms.comms_seconds(ici_gbytes_per_s) / compute_s
