"""Pass 5 — liveness & effect analysis over ``Program`` blocks.

The reference Fluid stack dedicates an entire pass family
(paddle/fluid/framework/ir/memory_optimize_pass/: reference_count_pass,
memory_reuse_pass, eager_deletion_pass) to static liveness so buffers can be
reused without changing program semantics. In the XLA rebuild most buffer
reuse is the compiler's job, but the *scope-level* decisions — which
persistable buffers may be donated to the compiled step, and how much memory
a program needs at its hottest op — still require the same analysis. This
module is that layer:

* ``classify_op_effects`` — per-op effect classification: pure / in-place
  alias / RNG / collective / side-effecting / control-flow.
* ``block_liveness``      — def/use chains and live intervals per var, with
  conservative cross-block capture for ``while``/``cond``/``recurrent``
  sub-blocks (a sub-block read counts as a read at the owning op's index,
  via the verifier's ``_block_reads``).
* ``safe_donation_set``   — the PROVEN donation set consumed by
  ``executor.analyze_block_io``: a scope var is donatable only if every
  read precedes (or coincides with) its last write and it is not fetched.
  Replaces the old ``state_in ∩ state_out`` heuristic, which could donate a
  buffer the fetch list still observes.
* ``memory_plan``         — linear-scan peak-memory estimate of live bytes
  per op index (weights / gradients / optimizer state / activations split
  out), surfaced as ``Program.memory_plan()`` and ``tools/mem_report.py``.
* ``check_liveness``      — the PT5xx diagnostic pass wired into
  ``verify_program`` / ``FLAGS_check_program`` / ``tools/lint_program.py``
  (code table in docs/ANALYSIS.md).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..core import registry
from .diagnostics import Diagnostic
from .verifier import EMPTY, _block_reads, _raw_attr_var_names, _site

__all__ = [
    "OpEffects", "classify_op_effects", "VarLive", "block_liveness",
    "donation_candidates", "safe_donation_set", "donation_report",
    "MemoryPlan", "VarPlanEntry", "memory_plan", "check_liveness",
    "PURE", "INPLACE", "RNG", "COLLECTIVE", "SIDE_EFFECT", "CONTROL_FLOW",
]


# ---------------------------------------------------------------------------
# effect classification
# ---------------------------------------------------------------------------

PURE = "pure"                  # output depends only on inputs/attrs
INPLACE = "inplace"            # writes an output var that is also an input
RNG = "rng"                    # draws from the per-op PRNG stream
COLLECTIVE = "collective"      # cross-replica communication
SIDE_EFFECT = "side_effect"    # observable outside the value graph
CONTROL_FLOW = "control_flow"  # runs a sub-block (while/cond/recurrent)

# none of these are registered today (collectives are GSPMD-inserted), but
# transpiler-era program dumps may carry them — classify, don't crash
_COLLECTIVE_TYPES = frozenset({
    "allreduce", "broadcast", "allgather", "reduce_scatter", "barrier",
    "send", "recv", "send_barrier", "fetch_barrier",
})
_SIDE_EFFECT_TYPES = frozenset({
    "feed", "fetch", "print", "py_func", "save", "load",
    "save_combine", "load_combine",
})


@dataclasses.dataclass(frozen=True)
class OpEffects:
    """Effect summary of one op (reference: OpDesc attr flags + the
    memory_optimize_pass' op classification tables)."""

    kind: str
    # output names that alias an input name (in-place rebinding of the var)
    inplace: Tuple[str, ...] = ()
    sub_block: Optional[int] = None

    @property
    def eliminable(self) -> bool:
        """May the op be dropped when nothing reads its outputs? RNG and
        in-place ops are value-only here (keys are derived per-op, not from
        a mutable global stream), so only communication, sub-blocks and
        true side effects pin an op."""
        return self.kind not in (SIDE_EFFECT, COLLECTIVE, CONTROL_FLOW)


def classify_op_effects(op) -> OpEffects:
    ins = {n for n in op.input_arg_names if n != EMPTY}
    inplace = tuple(sorted({n for n in op.output_arg_names
                            if n != EMPTY and n in ins}))
    sub = op.attrs.get("sub_block")
    sub = sub if isinstance(sub, int) else None
    t = op.type
    if t in _SIDE_EFFECT_TYPES:
        kind = SIDE_EFFECT
    elif t.startswith("c_") or t in _COLLECTIVE_TYPES:
        kind = COLLECTIVE
    elif sub is not None or (registry.has_op(t) and registry.get_op_def(t).raw):
        kind = CONTROL_FLOW
    elif registry.has_op(t) and registry.get_op_def(t).needs_rng:
        kind = RNG
    elif inplace:
        kind = INPLACE
    else:
        kind = PURE
    return OpEffects(kind=kind, inplace=inplace, sub_block=sub)


# ---------------------------------------------------------------------------
# per-block liveness
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class VarLive:
    """Def/use chain of one var within one block. Sub-block accesses are
    charged to the owning raw op's index (conservative: the whole loop body
    counts as one program point)."""

    name: str
    defs: List[int] = dataclasses.field(default_factory=list)
    uses: List[int] = dataclasses.field(default_factory=list)
    live_in: bool = False   # value enters the block from feed/scope
    live_out: bool = False  # value must survive the block (persistable/fetch)

    @property
    def first_def(self) -> Optional[int]:
        return self.defs[0] if self.defs else None

    @property
    def last_def(self) -> Optional[int]:
        return self.defs[-1] if self.defs else None

    @property
    def last_use(self) -> Optional[int]:
        return self.uses[-1] if self.uses else None

    def interval(self, n_ops: int) -> Optional[Tuple[int, int]]:
        """Half-open [start, end) op-index range where the var's buffer is
        live; None for a var with no events (dead declaration)."""
        events = self.defs + self.uses
        if not events and not (self.live_in and self.live_out):
            return None
        start = 0 if self.live_in else min(events)
        end = n_ops if self.live_out else (max(events) + 1 if events else n_ops)
        return (start, max(end, start))


def _op_accesses(program, op, memo) -> Tuple[Set[str], Set[str]]:
    """(reads, writes) of one op, folding sub-block reads into the op."""
    reads = {n for n in op.input_arg_names if n != EMPTY}
    writes = {n for n in op.output_arg_names if n != EMPTY}
    sub = op.attrs.get("sub_block")
    if isinstance(sub, int) and 0 <= sub < len(program.blocks):
        reads.update(_block_reads(program, sub, memo))
        reads.update(_raw_attr_var_names(op))
    return reads, writes


def block_liveness(block, feed_names: Sequence[str] = (),
                   fetch_names: Sequence[str] = ()) -> Dict[str, VarLive]:
    """Dataflow liveness for one block. Reads inside nested sub-blocks count
    as reads at the owning op's index, so a ``while`` body reading an outer
    var keeps it live across the loop (and blocks its donation unless the
    loop itself rewrites it)."""
    program = block.program
    memo: Dict[int, Set[str]] = {}
    feed = set(feed_names)
    fetch = set(fetch_names)
    persistable = {v.name for v in block.vars.values() if v.persistable}

    live: Dict[str, VarLive] = {}

    def rec(name: str) -> VarLive:
        vl = live.get(name)
        if vl is None:
            vl = live[name] = VarLive(name)
        return vl

    for oi, op in enumerate(block.ops):
        reads, writes = _op_accesses(program, op, memo)
        for n in reads:
            rec(n).uses.append(oi)
        for n in writes:
            rec(n).defs.append(oi)

    for n, vl in live.items():
        fd, fu = vl.first_def, (vl.uses[0] if vl.uses else None)
        # live-in: fed, or read before (or at) the first local write — a
        # read at the defining op's own index observes the incoming value
        # (read-modify-write ops like sgd's Param -> ParamOut)
        vl.live_in = (n in feed
                      or (fu is not None and (fd is None or fu <= fd)))
        vl.live_out = n in fetch or n in persistable
    return live


# ---------------------------------------------------------------------------
# proven-safe buffer donation
# ---------------------------------------------------------------------------

def donation_candidates(block, feed_names: Sequence[str] = (),
                        fetch_names: Sequence[str] = ()) -> Set[str]:
    """The OLD heuristic's set: scope vars both read into the step and
    re-written as persistables (``state_in ∩ state_out``). The proven set
    is a subset of this."""
    cands, _, _ = _donation_analysis(block, feed_names, fetch_names)
    return cands


def _donation_analysis(block, feed_names: Sequence[str] = (),
                       fetch_names: Sequence[str] = ()
                       ) -> Tuple[Set[str], Dict[str, str],
                                  Dict[str, VarLive]]:
    feed = set(feed_names)
    fetch = set(fetch_names)
    live = block_liveness(block, feed_names, fetch_names)
    persistable = {v.name for v in block.vars.values() if v.persistable}
    cands = {n for n, vl in live.items()
             if vl.live_in and vl.defs and n in persistable
             and n not in feed}
    unsafe: Dict[str, str] = {}
    for n in sorted(cands):
        vl = live[n]
        if n in fetch:
            unsafe[n] = ("fetched: the caller's fetch result and the scope "
                         "could observe a consumed buffer")
        elif vl.last_use is not None and vl.last_use > vl.last_def:
            unsafe[n] = (f"read at op {vl.last_use} after its last write "
                         f"(op {vl.last_def}); the old buffer is not "
                         f"provably dead")
    return cands, unsafe, live


def safe_donation_set(block, feed_names: Sequence[str] = (),
                      fetch_names: Sequence[str] = ()) -> Set[str]:
    """Scope vars whose input buffers are PROVEN safe to donate to the
    compiled step: read into the step, re-written as persistables, never
    read after the last write, and not in the fetch list. Always a subset
    of the old ``state_in ∩ state_out`` heuristic — donation decisions are
    identical or strictly safer."""
    cands, unsafe, _ = _donation_analysis(block, feed_names, fetch_names)
    return cands - set(unsafe)


def donation_report(block, feed_names: Sequence[str] = (),
                    fetch_names: Sequence[str] = ()) -> Dict[str, str]:
    """name -> 'donated' or the reason donation was refused (debug aid)."""
    cands, unsafe, _ = _donation_analysis(block, feed_names, fetch_names)
    return {n: unsafe.get(n, "donated") for n in sorted(cands)}


# ---------------------------------------------------------------------------
# peak-memory plan (linear scan over live intervals)
# ---------------------------------------------------------------------------

WEIGHT = "weight"
OPTIMIZER_STATE = "optimizer_state"
GRADIENT = "gradient"
ACTIVATION = "activation"
PERSISTABLE_OTHER = "persistable_other"
SUB_BLOCK = "sub_block"
COLLECTIVE_STAGING = "collective_staging"  # per-chip plans only

_CLASSES = (WEIGHT, GRADIENT, OPTIMIZER_STATE, ACTIVATION,
            PERSISTABLE_OTHER, SUB_BLOCK)


def _classify_var(v) -> str:
    if getattr(v, "is_optimizer_state", False):
        return OPTIMIZER_STATE
    if getattr(v, "trainable", None) is not None:  # Parameter duck-type
        return WEIGHT
    if v.name.endswith("@GRAD"):
        return GRADIENT
    if v.persistable:
        return PERSISTABLE_OTHER
    return ACTIVATION


def _var_bytes(v, batch_size: int) -> Tuple[int, bool]:
    """(bytes, had_dynamic_dims). -1/None dims are resolved to batch_size —
    the plan is an estimate parameterized on batch, not a measurement."""
    if v.shape is None:
        return 0, True
    from ..core.types import np_dtype

    try:
        item = int(np_dtype(v.dtype).itemsize)
    except Exception:
        item = 4
    numel, dynamic = 1, False
    for d in v.shape:
        d = int(d) if d is not None else -1
        if d < 0:
            d, dynamic = int(batch_size), True
        numel *= d
    return numel * item, dynamic


@dataclasses.dataclass
class VarPlanEntry:
    name: str
    cls: str
    bytes: int          # PLANNED bytes: per-chip when the plan has a mesh
    start: int
    end: int            # half-open [start, end)
    shape: Optional[tuple]
    dtype: str
    site: str           # build site of the first producing op, if any
    dynamic: bool       # bytes include batch-resolved -1 dims
    # per-chip mode only (sharding_check specs); None on the single-device
    # path so its dict form stays bit-identical to the pre-mesh planner
    spec: Optional[tuple] = None
    global_bytes: Optional[int] = None

    def to_dict(self) -> dict:
        d = {"name": self.name, "class": self.cls, "bytes": self.bytes,
             "start": self.start, "end": self.end,
             "shape": list(self.shape) if self.shape else None,
             "dtype": self.dtype, "site": self.site,
             "dynamic": self.dynamic}
        if self.spec is not None:
            d["spec"] = list(self.spec)
            d["global_bytes"] = self.global_bytes
        return d


def _fmt_bytes(b: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if b < 1024 or unit == "GiB":
            return f"{b:.1f} {unit}" if unit != "B" else f"{b} B"
        b /= 1024.0
    return f"{b:.1f} GiB"


@dataclasses.dataclass
class MemoryPlan:
    """Linear-scan live-byte estimate for one block (reference: the
    memory_optimize_pass' MemOptVarInfo reference-count schedule, recast as
    a static plan). ``timeline[i]`` is the estimated bytes live while op
    ``i`` runs; sub-block peaks are charged at the owning op's index."""

    block_idx: int
    n_ops: int
    batch_size: int
    entries: List[VarPlanEntry]
    timeline: List[int]
    class_timeline: Dict[str, List[int]]
    sub_plans: Dict[int, "MemoryPlan"]
    # per-chip mode (Program.memory_plan(mesh=...)): the mesh shape and
    # the collective staging bytes charged per op index; None/empty on the
    # single-device path, which is byte-identical to the pre-mesh planner
    mesh: Optional[Dict[str, int]] = None
    staging_timeline: Optional[List[int]] = None

    @property
    def peak_bytes(self) -> int:
        return max(self.timeline) if self.timeline else 0

    @property
    def peak_op_idx(self) -> int:
        if not self.timeline:
            return 0
        return max(range(len(self.timeline)), key=self.timeline.__getitem__)

    def by_class_at(self, oi: int) -> Dict[str, int]:
        return {c: t[oi] for c, t in self.class_timeline.items()
                if t and t[oi]}

    def live_at(self, oi: int) -> List[VarPlanEntry]:
        return [e for e in self.entries if e.start <= oi < e.end]

    def top_hot_spots(self, n: int = 10) -> List[VarPlanEntry]:
        """Largest live ranges at the peak op — the buffers a
        rematerialization / reuse pass would attack first."""
        peak = self.peak_op_idx
        return sorted(self.live_at(peak),
                      key=lambda e: (-e.bytes, e.start, e.name))[:n]

    def to_dict(self) -> dict:
        peak = self.peak_op_idx
        d = {
            "block_idx": self.block_idx,
            "n_ops": self.n_ops,
            "batch_size": self.batch_size,
            "peak_bytes": self.peak_bytes,
            "peak_op_idx": peak,
            "by_class_at_peak": self.by_class_at(peak),
            "hot_spots": [e.to_dict() for e in self.top_hot_spots()],
            "sub_block_peaks": {str(oi): p.peak_bytes
                                for oi, p in self.sub_plans.items()},
        }
        if self.mesh is not None:
            d["mesh"] = dict(self.mesh)
            d["per_chip"] = True
            if self.staging_timeline:
                d["staging_at_peak"] = self.staging_timeline[peak] \
                    if peak < len(self.staging_timeline) else 0
                d["staging_peak_bytes"] = max(self.staging_timeline)
        return d

    def format(self, top: int = 10) -> str:
        peak = self.peak_op_idx
        chip = ""
        if self.mesh is not None:
            chip = (" PER CHIP on mesh "
                    + "x".join(f"{k}={v}" for k, v in self.mesh.items()))
        lines = [f"block {self.block_idx}: {self.n_ops} ops, peak "
                 f"{_fmt_bytes(self.peak_bytes)}{chip} at op {peak} "
                 f"(batch={self.batch_size})"]
        breakdown = self.by_class_at(peak)
        if breakdown:
            lines.append("  at peak: " + ", ".join(
                f"{c} {_fmt_bytes(b)}" for c, b in sorted(
                    breakdown.items(), key=lambda kv: -kv[1])))
        lines.append(f"  top {top} live-range hot spots at peak:")
        for e in self.top_hot_spots(top):
            span = f"[{e.start},{e.end})"
            dyn = " (batch-resolved)" if e.dynamic else ""
            lines.append(f"    {_fmt_bytes(e.bytes):>10}  {e.cls:<17} "
                         f"{e.name:<32} live {span}{dyn}")
            if e.site:
                lines.append(f"               built at {e.site}")
        return "\n".join(lines)


def memory_plan(program, feed_names: Sequence[str] = (),
                fetch_names: Sequence[str] = (), batch_size: int = 1,
                block_idx: int = 0, _seen: Optional[Set[int]] = None,
                mesh: Optional[Dict[str, int]] = None,
                specs: Optional[Dict[str, tuple]] = None,
                staging: Optional[Dict[tuple, int]] = None) -> MemoryPlan:
    """Linear-scan peak-memory estimate for ``program.blocks[block_idx]``.

    Sub-blocks are planned recursively and their peak charged at the owning
    op's index (the whole loop body is one program point — conservative for
    a ``while`` whose true peak is inside the body).

    With ``mesh``/``specs`` (propagated shard specs from
    ``analysis.sharding_check``; see ``Program.memory_plan(mesh=...)``)
    the plan is **per chip**: each var's live bytes divide by its spec's
    shard count (replicated tensors — and vars with no spec, including
    every sub-block-only var — count whole: a conservative OVER-estimate,
    never under), and ``staging`` charges collective scratch at the
    emitting op's index. With ``mesh=None`` (the default) the code path
    and numbers are identical to the single-device planner."""
    _seen = set() if _seen is None else _seen
    _seen.add(block_idx)
    block = program.blocks[block_idx]
    n_ops = max(len(block.ops), 1)
    live = block_liveness(block, feed_names, fetch_names)
    per_chip = mesh is not None
    if per_chip:
        from .sharding_check import spec_divisor

    entries: List[VarPlanEntry] = []
    for name, vl in sorted(live.items()):
        v = block.vars.get(name)
        if v is None:
            continue  # sub-block-local name or scope alias; charged there
        span = vl.interval(n_ops)
        if span is None:
            continue
        nbytes, dynamic = _var_bytes(v, batch_size)
        site = ""
        if vl.defs:
            site = block.ops[vl.defs[0]].attrs.get("op_callstack", "") or ""
        spec = None
        global_bytes = None
        if per_chip:
            spec = tuple((specs or {}).get(name, ()))
            global_bytes = nbytes
            nbytes //= spec_divisor(spec, mesh, v.shape, batch_size)
        entries.append(VarPlanEntry(
            name=name, cls=_classify_var(v), bytes=nbytes,
            start=span[0], end=span[1], shape=v.shape,
            dtype=str(v.dtype), site=site, dynamic=dynamic,
            spec=spec, global_bytes=global_bytes))

    timeline = [0] * n_ops
    class_timeline = {c: [0] * n_ops for c in _CLASSES}
    for e in entries:
        for i in range(e.start, min(e.end, n_ops)):
            timeline[i] += e.bytes
            class_timeline[e.cls][i] += e.bytes

    staging_timeline: Optional[List[int]] = None
    if per_chip and staging:
        staging_timeline = [0] * n_ops
        for (bidx, oi), nbytes in staging.items():
            if bidx == block_idx and 0 <= oi < n_ops:
                staging_timeline[oi] += int(nbytes)
                timeline[oi] += int(nbytes)
        # its own class bucket so by_class_at(peak) / format() reconcile
        # with the reported peak (single-device plans never get the key)
        class_timeline[COLLECTIVE_STAGING] = list(staging_timeline)

    sub_plans: Dict[int, MemoryPlan] = {}
    for oi, op in enumerate(block.ops):
        sub = op.attrs.get("sub_block")
        if (isinstance(sub, int) and 0 <= sub < len(program.blocks)
                and sub not in _seen):
            sp = memory_plan(program, (), (), batch_size, sub, _seen,
                             mesh=mesh, specs=specs, staging=staging)
            sub_plans[oi] = sp
            timeline[oi] += sp.peak_bytes
            class_timeline[SUB_BLOCK][oi] += sp.peak_bytes

    return MemoryPlan(block_idx=block_idx, n_ops=len(block.ops),
                      batch_size=batch_size, entries=entries,
                      timeline=timeline, class_timeline=class_timeline,
                      sub_plans=sub_plans,
                      mesh=dict(mesh) if per_chip else None,
                      staging_timeline=staging_timeline)


# ---------------------------------------------------------------------------
# PT5xx diagnostic pass (wired into verify_program; docs/ANALYSIS.md)
# ---------------------------------------------------------------------------

def _global_reads(program) -> Set[str]:
    # _block_reads already folds _raw_attr_var_names in for every
    # sub-block-owning op, so a plain union over all blocks is complete
    memo: Dict[int, Set[str]] = {}
    reads: Set[str] = set()
    for blk in program.blocks:
        reads.update(_block_reads(program, blk.idx, memo))
    return reads


def check_liveness(program, diags: List[Diagnostic],
                   fetch_names: Sequence[str],
                   donation: Optional[tuple] = None) -> None:
    """``donation`` lets a caller that already ran ``_donation_analysis``
    on the global block (the registered liveness pass caches it on the
    PassContext) hand it in instead of paying the dataflow scan twice."""
    fetch = set(fetch_names or ())
    persistable = {v.name for blk in program.blocks
                   for v in blk.vars.values() if v.persistable}
    gb = program.blocks[0]
    feeds = {v.name for v in gb.vars.values() if v.is_data}

    # PT500 — donation-unsafe fetch: the fetched var is also updated in
    # place by the step; analyze_block_io now refuses to donate it, and the
    # finding explains the (silent) conservatism.
    cands, unsafe, live = donation if donation is not None \
        else _donation_analysis(gb, feeds, fetch)
    for n in sorted(cands & fetch):
        ld = live[n].last_def
        op = gb.ops[ld] if ld is not None else None
        diags.append(Diagnostic(
            "PT500",
            f"var '{n}' is updated in place and fetched — its buffer is "
            f"excluded from donation (a donated buffer could be consumed "
            f"while the fetch still references it)",
            gb.idx, ld, op.type if op else None, _site(op) if op else ""))

    global_reads = _global_reads(program)
    all_writes: Set[str] = set()
    for blk in program.blocks:
        for op in blk.ops:
            all_writes.update(n for n in op.output_arg_names if n != EMPTY)

    # owner chain for PT504: sub-block idx -> (owning block, owning op)
    owner: Dict[int, tuple] = {}
    for blk in program.blocks:
        for op in blk.ops:
            sub = op.attrs.get("sub_block")
            if isinstance(sub, int) and 0 <= sub < len(program.blocks):
                owner[sub] = (blk, op)

    def escape_names(bidx: int) -> Set[str]:
        """Names a sub-block write can escape through: the Out slots of the
        owning raw-op chain up to the global block."""
        names: Set[str] = set()
        seen: Set[int] = set()
        while bidx in owner and bidx not in seen:
            seen.add(bidx)
            blk, op = owner[bidx]
            names.update(op.output_arg_names)
            bidx = blk.idx
        return names

    for blk in program.blocks:
        # PT501 — write-after-fetch: an explicit fetch op's var is rewritten
        # later in the block. The compiled step fetches FINAL values, so the
        # fetch would observe the post-write value, diverging from the
        # reference's fetch-at-op-position semantics.
        writes_at: Dict[str, List[int]] = {}
        for oi, op in enumerate(blk.ops):
            for n in op.output_arg_names:
                if n != EMPTY:
                    writes_at.setdefault(n, []).append(oi)
        for oi, op in enumerate(blk.ops):
            if op.type != "fetch":
                continue
            for n in op.input_arg_names:
                later = [w for w in writes_at.get(n, []) if w > oi]
                if later:
                    diags.append(Diagnostic(
                        "PT501",
                        f"var '{n}' is written (op {later[0]}) after its "
                        f"fetch op {oi}; the compiled step fetches final "
                        f"values, so the fetch observes the later write",
                        blk.idx, oi, op.type, _site(op)))

        # PT502 — dead op: effect-free op none of whose outputs is ever
        # read, fetched or persistable (op-level view of PT203).
        for oi, op in enumerate(blk.ops):
            eff = classify_op_effects(op)
            if not eff.eliminable:
                continue
            outs = [n for n in op.output_arg_names if n != EMPTY]
            if outs and all(n not in global_reads and n not in fetch
                            and n not in persistable for n in outs):
                diags.append(Diagnostic(
                    "PT502",
                    f"dead op: no output of '{op.type}' "
                    f"({', '.join(sorted(outs))}) is read, fetched or "
                    f"persistable — the op computes nothing observable",
                    blk.idx, oi, op.type, _site(op)))

        # PT503 — dead var: declared but never read or written anywhere.
        for v in blk.vars.values():
            if v.is_data or v.persistable:
                continue
            n = v.name
            if (n not in global_reads and n not in all_writes
                    and n not in fetch):
                diags.append(Diagnostic(
                    "PT503",
                    f"dead var: '{n}' is declared in block {blk.idx} but no "
                    f"op reads or writes it",
                    blk.idx, None, None, ""))

        # PT504 — persistable rebound inside a sub-block: the compiled
        # step's state threading (analyze_block_io) only scans the global
        # block, so a persistable written in a sub-block without escaping
        # through the owning op's outputs silently never reaches the scope.
        if blk.parent_idx >= 0:
            escapes = escape_names(blk.idx)
            reported: Set[str] = set()
            for oi, op in enumerate(blk.ops):
                for n in op.output_arg_names:
                    if (n != EMPTY and n in persistable
                            and n not in escapes and n not in reported):
                        reported.add(n)
                        diags.append(Diagnostic(
                            "PT504",
                            f"persistable '{n}' is written inside sub-block "
                            f"{blk.idx} but is not an output of the owning "
                            f"control-flow op — the scope will never "
                            f"observe the update",
                            blk.idx, oi, op.type, _site(op)))
