"""Uniform pass framework over the Program IR (ROADMAP item 5).

The reference stack organises every IR-level analysis and transform behind
``ir::Pass``/``PassRegistry`` (118 pass files); this reproduction had grown
six ad-hoc passes — the four verifier passes, liveness, auto-remat — each
with its own entry point, plus transforms scattered across ``backward.py``
and the transpilers, with no shared caching and no invariant checking
between them. This module is the uniform layer:

* ``Pass`` — base class; ``kind`` is ``ANALYSIS`` (produces diagnostics
  and/or a result object, never mutates the program) or ``TRANSFORM``
  (returns a rebuilt ``Program``; the original is never mutated in place).
* ``PassRegistry`` / ``@register_pass`` — named passes with declared
  dependencies (``requires=("liveness",)`` runs and caches the liveness
  pass first) and invalidations (``invalidates="*"`` drops every cached
  analysis after the transform runs).
* ``PassContext`` — per-pipeline analysis cache shared across passes
  (``donation_race`` and ``dead_code`` read the one cached ``liveness``
  result), dropped when a transform invalidates.
* ``PassManager.run_pipeline`` — dependency-ordered execution with
  pre/post verification: at ``FLAGS_check_program`` level >= 2 every
  transform pass is bracketed by ``verify_program`` and a pass that
  introduces NEW error-severity findings is refused with
  ``PassVerificationError`` naming the pass. Per-pass wall time and run
  counts land on the ``paddle_tpu.monitor`` registry
  (``pass_runs_total`` / ``pass_duration_seconds``).

``FLAGS_check_program`` levels: 0 = off, 1 = verify each program once
before execution (the PR 1 behaviour), 2 = additionally re-verify after
every transform pass (the pipeline invariant). The executor routes both
``FLAGS_check_program`` and ``FLAGS_auto_recompute`` through
``run_verify_pipeline`` / ``run_transform_pipeline`` below.

Built-in passes (docs/ANALYSIS.md has the full table):

| name              | kind      | requires    | what |
|-------------------|-----------|-------------|------|
| schema            | analysis  | —           | PT10x slot/attr conformance |
| dataflow          | analysis  | —           | PT20x def-before-use, dead writes |
| lowerability      | analysis  | —           | PT30x missing lower rules |
| shape_replay      | analysis  | —           | PT40x per-op infer_shape drift |
| liveness          | analysis  | —           | PT50x + def/use chains (cached) |
| dtype_shape_check | analysis  | —           | PT70x whole-program replay |
| donation_race     | analysis  | liveness    | PT71x donation/alias races |
| dead_code         | analysis  | —           | PT72x transitively dead ops |
| cost_model        | analysis  | —           | FLOP/byte CostReport (no diagnostics) |
| numerics_check    | analysis  | —           | PT90x interval/precision flow + quantizability |
| auto_remat        | transform | —           | Pass 6 rebuild (FLAGS_auto_recompute) |
| dce               | transform | dead_code   | opt-in dead-op elimination |
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .diagnostics import (Diagnostic, ProgramVerificationError, Severity,
                          format_diagnostics)

__all__ = [
    "ANALYSIS", "TRANSFORM", "Pass", "FunctionPass", "PassRegistry",
    "register_pass", "get_pass_registry", "PassContext", "PipelineResult",
    "PassManager", "PassVerificationError", "default_pass_manager",
    "run_verify_pipeline", "run_transform_pipeline", "program_fingerprint",
    "clear_analysis_caches", "ALL_ANALYSIS_PASSES", "VERIFY_PASSES",
]

ANALYSIS = "analysis"
TRANSFORM = "transform"

# the PR 1-6 verifier pipeline (identical diagnostics to the pre-manager
# check_program) and the full static-analysis suite the lint CLI drives
VERIFY_PASSES = ("schema", "dataflow", "lowerability", "shape_replay",
                 "liveness")
# sharding_check is a silent no-op without a mesh option, and
# numerics_check is one linear walk on a findings-free program, so the
# full lint pipeline can always include both
ALL_ANALYSIS_PASSES = VERIFY_PASSES + ("dtype_shape_check", "donation_race",
                                       "dead_code", "sharding_check",
                                       "numerics_check")

class PassVerificationError(ProgramVerificationError):
    """A transform pass broke the pipeline invariant: ``verify_program``
    found error-severity diagnostics after the transform that the input
    program did not have. Carries the offending pass name."""

    def __init__(self, pass_name: str, diags: List[Diagnostic]):
        self.pass_name = pass_name
        ValueError.__init__(
            self,
            f"transform pass '{pass_name}' broke the program invariant — "
            f"post-transform verify_program found new error(s) "
            f"(FLAGS_check_program>=2):\n" + format_diagnostics(diags))
        self.diagnostics = diags


def program_fingerprint(program) -> tuple:
    """(serial, version, op count) — the executor's cache identity: serial
    survives GC aliasing, version counts appends + ``set_attr`` mutations,
    op count catches removals (which bump no counter)."""
    return (int(getattr(program, "_serial", -1)),
            int(getattr(program, "_version", 0)),
            sum(len(b.ops) for b in program.blocks))


# ---------------------------------------------------------------------------
# passes and the registry
# ---------------------------------------------------------------------------

class Pass:
    """One registered IR pass. Subclass and implement ``run``, or register
    a plain function with ``@register_pass`` (wrapped in ``FunctionPass``).

    ``run(program, ctx)`` contract by kind:

    * ANALYSIS — never mutates ``program``; reports findings with
      ``ctx.report(Diagnostic(...))``; its return value is cached on the
      context (``ctx.analysis(name)``) until a transform invalidates it.
    * TRANSFORM — returns the replacement ``Program``, or any object with
      a ``.program`` attribute (e.g. ``RematDecision``), or ``None`` for
      "no change". Must never mutate the input program in place: the
      pre/post verify bracket and the analysis caches both rely on the
      input staying intact.
    """

    name: str = ""
    kind: str = ANALYSIS
    requires: Tuple[str, ...] = ()
    invalidates: Tuple[str, ...] = ()   # "*" (as a 1-tuple) drops everything

    def run(self, program, ctx: "PassContext"):
        raise NotImplementedError

    def __repr__(self):
        return (f"<{type(self).__name__} {self.name!r} kind={self.kind} "
                f"requires={self.requires}>")


class FunctionPass(Pass):
    """A plain ``fn(program, ctx)`` registered as a pass."""

    def __init__(self, fn: Callable, name: str, kind: str,
                 requires: Sequence[str] = (),
                 invalidates: Sequence[str] = ()):
        self.fn = fn
        self.name = name
        self.kind = kind
        self.requires = tuple(requires)
        self.invalidates = tuple(invalidates)
        self.__doc__ = fn.__doc__

    def run(self, program, ctx: "PassContext"):
        return self.fn(program, ctx)


class PassRegistry:
    """Name -> ``Pass`` table with snapshot/restore for test isolation
    (the conftest autouse fixture resets registrations between tests, the
    same pattern as the PR 1 flag/clip resets)."""

    def __init__(self):
        self._passes: Dict[str, Pass] = {}

    def register(self, p: Pass, override: bool = False) -> Pass:
        if not p.name:
            raise ValueError("pass has no name")
        if p.kind not in (ANALYSIS, TRANSFORM):
            raise ValueError(f"pass '{p.name}': kind must be '{ANALYSIS}' "
                             f"or '{TRANSFORM}', got {p.kind!r}")
        if p.name in self._passes and not override:
            raise ValueError(f"pass '{p.name}' is already registered "
                             f"(pass override=True to replace)")
        self._passes[p.name] = p
        return p

    def get(self, name: str) -> Pass:
        p = self._passes.get(name)
        if p is None:
            raise KeyError(f"unknown pass '{name}' — registered: "
                           f"{sorted(self._passes)}")
        return p

    def has(self, name: str) -> bool:
        return name in self._passes

    def names(self) -> List[str]:
        return sorted(self._passes)

    def passes(self) -> List[Pass]:
        return [self._passes[n] for n in sorted(self._passes)]

    # -- test isolation ---------------------------------------------------
    def snapshot(self) -> Dict[str, Pass]:
        return dict(self._passes)

    def restore(self, snap: Dict[str, Pass]) -> None:
        self._passes = dict(snap)


_default_registry = PassRegistry()


def get_pass_registry() -> PassRegistry:
    _ensure_builtin_passes()
    return _default_registry


def register_pass(name: str, kind: str = ANALYSIS,
                  requires: Sequence[str] = (),
                  invalidates: Sequence[str] = (),
                  registry: Optional[PassRegistry] = None,
                  override: bool = False):
    """Decorator registering a function or ``Pass`` subclass:

    >>> @register_pass("my_lint", requires=("liveness",))
    ... def my_lint(program, ctx):
    ...     live = ctx.analysis("liveness")
    ...     ...
    """
    reg = registry if registry is not None else _default_registry

    def deco(obj):
        if isinstance(obj, type) and issubclass(obj, Pass):
            inst = obj()
            inst.name = name
            inst.kind = kind
            inst.requires = tuple(requires)
            inst.invalidates = tuple(invalidates)
            reg.register(inst, override=override)
            return obj
        reg.register(FunctionPass(obj, name, kind, requires, invalidates),
                     override=override)
        return obj

    return deco


# ---------------------------------------------------------------------------
# the context: shared analysis cache + diagnostics sink
# ---------------------------------------------------------------------------

class PassContext:
    """Carries one pipeline's inputs (feeds/fetches/batch/options) and the
    analysis cache. Analyses run at most once per context; a transform
    pass invalidates what it declares (``"*"`` for everything), so e.g.
    ``donation_race`` reads the one cached ``liveness`` result."""

    def __init__(self, program, feed_names: Sequence[str] = (),
                 fetch_names: Sequence[str] = (), batch_size: int = 1,
                 options: Optional[Dict[str, Any]] = None,
                 registry: Optional[PassRegistry] = None):
        self.program = program
        self.feed_names = tuple(feed_names or ())
        self.fetch_names = tuple(getattr(f, "name", f)
                                 for f in (fetch_names or ()))
        self.batch_size = max(int(batch_size), 1)
        self.options: Dict[str, Any] = dict(options or {})
        self.registry = registry if registry is not None \
            else get_pass_registry()
        self.diagnostics: List[Diagnostic] = []
        self._cache: Dict[str, Any] = {}
        self._cache_diags: Dict[str, List[Diagnostic]] = {}
        self._running: List[str] = []   # cycle guard for analysis(...)
        # (start, end) windows claimed by nested analysis() runs, per
        # in-flight frame — keeps each pass' recorded diagnostics disjoint
        self._frames: List[List[Tuple[int, int]]] = []

    # -- diagnostics ------------------------------------------------------
    def report(self, diag: Diagnostic) -> None:
        self.diagnostics.append(diag)

    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics
                if d.severity == Severity.ERROR]

    # -- analysis cache ---------------------------------------------------
    def analysis(self, name: str):
        """Result of analysis pass ``name``, running it on demand (and
        caching). The pass' diagnostics are recorded exactly once no
        matter how many passes request the result: windows claimed by a
        nested ``analysis()`` call (a dependency run on demand inside
        another pass) are excluded from the caller's own window."""
        if name in self._cache:
            return self._cache[name]
        p = self.registry.get(name)
        if p.kind != ANALYSIS:
            raise ValueError(f"pass '{name}' is a {p.kind} pass — only "
                             f"analysis results can be cached/required")
        if name in self._running:
            raise ValueError(f"analysis dependency cycle: "
                             f"{' -> '.join(self._running + [name])}")
        for dep in p.requires:
            self.analysis(dep)
        sink_start = len(self.diagnostics)
        self._running.append(name)
        self._frames.append([])
        t0 = time.perf_counter()
        try:
            value = p.run(self.program, self)
        finally:
            self._running.pop()
            nested = self._frames.pop()
        _record_pass_metrics(name, p.kind, time.perf_counter() - t0)
        sink_end = len(self.diagnostics)
        own = [d for i, d in enumerate(self.diagnostics[sink_start:],
                                       sink_start)
               if not any(s <= i < e for s, e in nested)]
        self._cache[name] = value
        self._cache_diags[name] = own
        if self._frames:
            # tell the enclosing pass this whole window (nested runs
            # included — their ranges nest inside ours) is spoken for
            self._frames[-1].append((sink_start, sink_end))
        return value

    def has_analysis(self, name: str) -> bool:
        return name in self._cache

    def invalidate(self, names: Sequence[str] = ("*",)) -> None:
        """Drop cached analyses (a transform ran). ``("*",)`` drops all."""
        if "*" in names:
            self._cache.clear()
            self._cache_diags.clear()
        else:
            for n in names:
                self._cache.pop(n, None)
                self._cache_diags.pop(n, None)

    # -- rebinding after a transform --------------------------------------
    def rebind(self, program) -> None:
        """Point the context at a transform's output program. Cached
        analyses were computed on the OLD program, so the caller (the
        manager) invalidates per the pass declaration before rebinding."""
        self.program = program


def _record_pass_metrics(name: str, kind: str, seconds: float,
                         cached: bool = False) -> None:
    from .. import monitor

    monitor.record_pass(name, kind, seconds, cached=cached)


# ---------------------------------------------------------------------------
# the manager
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class PipelineResult:
    """Outcome of one ``run_pipeline`` call."""

    program: Any                       # the (possibly transformed) Program
    diagnostics: List[Diagnostic]
    values: Dict[str, Any]             # pass name -> return value
    timings: List[Tuple[str, str, float]]  # (name, kind, seconds)
    context: PassContext
    changed: bool = False              # did any transform swap the program

    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics
                if d.severity == Severity.ERROR]


class PassManager:
    """Dependency-ordered pass execution over one registry, with the
    pre/post verification bracket. One default instance serves the
    executor hooks and the CLI tools (``default_pass_manager()``)."""

    def __init__(self, registry: Optional[PassRegistry] = None):
        self._registry = registry

    @property
    def registry(self) -> PassRegistry:
        return self._registry if self._registry is not None \
            else get_pass_registry()

    # -- ordering ---------------------------------------------------------
    def resolve(self, passes: Sequence[str]) -> List[str]:
        """Requested passes plus their transitive ``requires``, in
        dependency order (a required pass runs before its dependent);
        explicit request order is preserved otherwise."""
        reg = self.registry
        order: List[str] = []
        visiting: List[str] = []

        def visit(name: str) -> None:
            if name in order:
                return
            if name in visiting:
                raise ValueError(f"pass dependency cycle: "
                                 f"{' -> '.join(visiting + [name])}")
            p = reg.get(name)
            visiting.append(name)
            for dep in p.requires:
                visit(dep)
            visiting.pop()
            order.append(name)

        for name in passes:
            visit(name)
        return order

    # -- execution --------------------------------------------------------
    def run_pipeline(self, program, passes: Sequence[str],
                     feed_names: Sequence[str] = (),
                     fetch_names: Sequence[str] = (),
                     batch_size: int = 1,
                     options: Optional[Dict[str, Any]] = None,
                     verify: Optional[str] = None,
                     context: Optional[PassContext] = None
                     ) -> PipelineResult:
        """Run ``passes`` (dependency-expanded, in order) over ``program``.

        ``verify`` controls the invariant bracket:

        * ``None`` (default) — derive from ``FLAGS_check_program``:
          level >= 2 behaves like ``"strict"``, else ``"none"``.
        * ``"none"``  — no bracketing (analysis findings still collect).
        * ``"strict"`` — ``verify_program`` before the pipeline and after
          every transform pass; a transform that introduces NEW
          error-severity findings raises ``PassVerificationError``.

        Never mutates ``program``; the (possibly rebuilt) program is
        ``result.program``.

        A fresh ``PassContext`` is built per call (so programs mutated
        without a version bump, and flag flips, are always re-analysed);
        pass ``context=`` to carry one context across pipeline calls when
        the caller can vouch the program and flags are unchanged. Within
        one pipeline analyses always share: ``donation_race`` reads the
        one cached ``liveness`` result.
        """
        from .verifier import verify_program

        if verify is None:
            from ..flags import flag

            verify = "strict" if int(flag("check_program")) >= 2 else "none"
        order = self.resolve(passes)
        ctx = context if context is not None else PassContext(
            program, feed_names, fetch_names, batch_size, options,
            registry=self.registry)
        if options and ctx.options is not options:
            ctx.options.update(options)

        # baseline keyed by per-code COUNTS: messages embed op indices, so
        # a transform that merely renumbers ops must not make an old error
        # look new — only a code whose count grew blames the pass
        baseline_errors: Dict[str, int] = {}
        if verify == "strict":
            for d in verify_program(program, fetch_names=ctx.fetch_names):
                if d.severity == Severity.ERROR:
                    baseline_errors[d.code] = baseline_errors.get(
                        d.code, 0) + 1

        values: Dict[str, Any] = {}
        timings: List[Tuple[str, str, float]] = []
        pipeline_diags: List[Diagnostic] = []
        current = program
        changed = False
        for name in order:
            p = self.registry.get(name)
            if p.kind == ANALYSIS:
                cached = ctx.has_analysis(name)
                t0 = time.perf_counter()
                values[name] = ctx.analysis(name)
                if cached:
                    # the pass already ran on this program version (earlier
                    # pipeline or a requires= dependency); replay its
                    # recorded findings into this pipeline's window
                    _record_pass_metrics(name, p.kind, 0.0, cached=True)
                else:
                    timings.append((name, p.kind,
                                    time.perf_counter() - t0))
                pipeline_diags.extend(ctx._cache_diags.get(name, ()))
                continue
            # transform — framed like an analysis run so diagnostics from
            # any on-demand ctx.analysis() inside it stay with that
            # analysis' window instead of double-counting here
            sink = len(ctx.diagnostics)
            ctx._frames.append([])
            t0 = time.perf_counter()
            try:
                out = p.run(current, ctx)
            finally:
                seconds = time.perf_counter() - t0
                nested = ctx._frames.pop()
            _record_pass_metrics(name, p.kind, seconds)
            timings.append((name, p.kind, seconds))
            values[name] = out
            pipeline_diags.extend(
                d for i, d in enumerate(ctx.diagnostics[sink:], sink)
                if not any(s <= i < e for s, e in nested))
            new_prog = out
            if new_prog is not None and not _is_program(new_prog):
                new_prog = getattr(out, "program", None)
            if new_prog is None or new_prog is current:
                continue
            if verify == "strict":
                post = [d for d in verify_program(
                            new_prog, fetch_names=ctx.fetch_names)
                        if d.severity == Severity.ERROR]
                post_counts: Dict[str, int] = {}
                for d in post:
                    post_counts[d.code] = post_counts.get(d.code, 0) + 1
                grown = {c for c, n in post_counts.items()
                         if n > baseline_errors.get(c, 0)}
                if grown:
                    raise PassVerificationError(
                        name, [d for d in post if d.code in grown])
            ctx.invalidate(p.invalidates or ("*",))
            ctx.rebind(new_prog)
            current = new_prog
            changed = True

        return PipelineResult(
            program=current, diagnostics=pipeline_diags,
            values=values, timings=timings, context=ctx, changed=changed)


def _is_program(obj) -> bool:
    from ..framework import Program

    return isinstance(obj, Program)


# ---------------------------------------------------------------------------
# built-in pass registration (lazy: verifier/liveness/remat import us back)
# ---------------------------------------------------------------------------

def _ensure_builtin_passes() -> None:
    if "schema" in _default_registry._passes:
        return
    from . import builtin_passes

    builtin_passes.register_builtins(_default_registry)


_default_manager: Optional[PassManager] = None


def default_pass_manager() -> PassManager:
    """The process-wide manager the executor hooks and CLI tools share.
    Reset (with the registry) by the test-suite conftest."""
    global _default_manager
    if _default_manager is None:
        _default_manager = PassManager()
    return _default_manager


def clear_analysis_caches() -> None:
    """Drop the default manager and with it any state it holds — the test
    isolation hook the conftest fixture pairs with the registry restore.
    (Contexts are per-pipeline today, so this guards future manager-held
    caching rather than live state.)"""
    global _default_manager
    _default_manager = None


# ---------------------------------------------------------------------------
# executor-facing entry points
# ---------------------------------------------------------------------------

def run_verify_pipeline(program, fetch_names: Sequence[str] = (),
                        passes: Sequence[str] = VERIFY_PASSES
                        ) -> List[Diagnostic]:
    """The FLAGS_check_program hook body: run the verifier pipeline through
    the manager and raise ``ProgramVerificationError`` on error-severity
    findings — diagnostics identical to the pre-manager ``check_program``,
    now with per-pass monitor timings and shared analysis caching."""
    result = default_pass_manager().run_pipeline(
        program, passes, fetch_names=fetch_names, verify="none")
    if any(d.severity == Severity.ERROR for d in result.diagnostics):
        raise ProgramVerificationError(result.diagnostics)
    return result.diagnostics


def run_transform_pipeline(program, passes: Sequence[str],
                           feed_names: Sequence[str] = (),
                           fetch_names: Sequence[str] = (),
                           batch_size: int = 1,
                           options: Optional[Dict[str, Any]] = None
                           ) -> PipelineResult:
    """The FLAGS_auto_recompute (and future fusion/layout/sharding) hook
    body: run transform passes through the shared manager. Pre/post
    verification applies at FLAGS_check_program level >= 2."""
    return default_pass_manager().run_pipeline(
        program, passes, feed_names=feed_names, fetch_names=fetch_names,
        batch_size=batch_size, options=options)
