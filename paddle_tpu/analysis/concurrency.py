"""Source-level concurrency static analysis (the PT800 family).

Fluid 1.5's ParallelExecutor scheduled multi-device work from a statically
analyzed SSA dependency graph; this rebuild replaced that discipline with
free-threaded Python — the executor, the serving dispatch thread and the
whole fleet router/supervisor/breaker stack now hold ~25 distinct lock
sites, and concurrency bugs (sleeps under the compile-cache lock, torn
dict iteration, unguarded cross-thread fields) kept arriving one review
pass at a time.  This module turns that review pass into machinery: an
``ast``-based analysis over the ``paddle_tpu`` *source itself*, in the
same diagnostic idiom as the Program-IR passes but over Python functions
instead of IR ops.

What it builds per module tree:

* a **lock inventory** — every ``threading.Lock/RLock/Condition`` (and
  ``Event``) attribute, module-level lock, and every lock created through
  the witness factories ``monitor.lockwitness.make_lock/make_rlock/
  make_condition`` (whose string-literal name becomes the lock's
  canonical id, guaranteeing static and runtime names agree);
* a **lock-order graph** — edges ``A -> B`` wherever ``B`` is acquired
  (directly by a nested ``with``, or transitively through a resolved
  call) while ``A`` is held.  ``threading.Condition(lock)`` aliases to
  its underlying lock, so ``with self._work:`` and ``with self._lock:``
  are one node;
* three diagnostics:

  ========  ==========================================================
  PT800     cycle in the lock-order graph (incl. re-acquiring a
            non-reentrant ``Lock`` through a call chain)
  PT801     blocking call while holding a lock: ``time.sleep``,
            socket/HTTP I/O, ``subprocess`` waits, ``Event.wait()``
            without timeout, ``Thread.join()`` without timeout,
            ``block_until_ready``, unbounded ``queue`` ops — found
            directly or through the call-graph approximation
  PT802     attribute of a thread-spawning class reachable from more
            than one thread entry point, written at least once, with
            at least one access outside any lock region
  ========  ==========================================================

The analysis is deliberately an *approximation*: calls are resolved by
name through ``self``-methods, annotated attribute/parameter types,
local constructor assignments and intra-package module aliases;
unresolved calls are ignored (no finding is better than a speculative
one — the runtime lock witness covers the gap from the other side, see
``paddle_tpu.monitor.lockwitness``).  Findings carry a stable ``key``
in ``Diagnostic.op_type`` so ``tools/lint_concurrency.py`` can match
its allowlist on ``(code, key)`` exactly like ``tools/lint_program.py``
matches ``(code, op_type)``.
"""
from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .diagnostics import Diagnostic

__all__ = [
    "LockDef", "LockEdge", "ConcurrencyReport",
    "analyze_paths", "analyze_package", "static_edge_set",
    "package_source_files",
]

# fully-qualified module functions that block the calling thread
_BLOCKING_FUNCS = {
    "time.sleep": "time.sleep",
    "subprocess.run": "subprocess.run",
    "subprocess.call": "subprocess.call",
    "subprocess.check_call": "subprocess.check_call",
    "subprocess.check_output": "subprocess.check_output",
    "socket.create_connection": "socket.create_connection",
    "urllib.request.urlopen": "urllib.request.urlopen",
    "select.select": "select.select",
    "os.system": "os.system",
}

# receiver kinds inferred for attribute calls; method names that block
_BLOCKING_METHODS = {
    "popen": ("wait", "communicate"),
    "thread": ("join",),
    "queue": ("get", "put", "join"),
    "socket": ("connect", "accept", "recv", "sendall", "makefile"),
    "httpconn": ("connect", "request", "getresponse"),
    "httpresp": ("read",),
}

_LOCK_KINDS = ("lock", "rlock", "condition")


@dataclasses.dataclass
class LockDef:
    """One named lock site (an attribute, module global or factory call)."""
    id: str                    # canonical name (witness literal when present)
    kind: str                  # lock | rlock | condition | event | unknown
    module: str
    cls: Optional[str]
    attr: str
    line: int
    reentrant: bool
    alias_of: Optional[str] = None   # Condition(lock): underlying lock id

    @property
    def node(self) -> str:
        """Graph node this site acquires (conditions collapse onto their
        underlying lock)."""
        return self.alias_of or self.id


@dataclasses.dataclass
class LockEdge:
    src: str
    dst: str
    site: str      # file:line of the inner acquisition
    via: str = ""  # call chain when the edge crosses a function boundary


@dataclasses.dataclass
class ConcurrencyReport:
    locks: Dict[str, LockDef]
    edges: List[LockEdge]
    diagnostics: List[Diagnostic]
    modules: List[str]
    functions: int

    def edge_set(self) -> Set[Tuple[str, str]]:
        return {(e.src, e.dst) for e in self.edges}

    def to_dict(self) -> dict:
        return {
            "modules": list(self.modules),
            "functions": self.functions,
            "locks": {
                lid: {"kind": d.kind, "module": d.module, "class": d.cls,
                      "attr": d.attr, "line": d.line,
                      "reentrant": d.reentrant, "alias_of": d.alias_of}
                for lid, d in sorted(self.locks.items())
            },
            "edges": [{"src": e.src, "dst": e.dst, "site": e.site,
                       "via": e.via}
                      for e in sorted(self.edges,
                                      key=lambda e: (e.src, e.dst, e.site))],
            "diagnostics": [
                {"code": d.code, "severity": d.severity, "key": d.op_type,
                 "message": d.message, "site": d.site}
                for d in self.diagnostics
            ],
        }


# --------------------------------------------------------------------------
# per-module collection
# --------------------------------------------------------------------------

@dataclasses.dataclass
class _FuncInfo:
    key: Tuple[str, Optional[str], str]   # (module, class, name)
    site: str
    # events recorded during the body walk
    acquires: List[Tuple[Tuple[str, ...], str, str]] = \
        dataclasses.field(default_factory=list)      # (held, node, site)
    calls: List[Tuple[Tuple[str, ...], Tuple, str]] = \
        dataclasses.field(default_factory=list)      # (held, callee, site)
    blocking: List[Tuple[Tuple[str, ...], str, str]] = \
        dataclasses.field(default_factory=list)      # (held, what, site)
    attr_events: List[Tuple[str, bool, bool, str]] = \
        dataclasses.field(default_factory=list)  # (attr, write, locked, site)
    thread_targets: List[Tuple[Tuple, str]] = \
        dataclasses.field(default_factory=list)      # (callee key, site)

    @property
    def qualname(self) -> str:
        mod, cls, name = self.key
        return f"{mod}.{cls}.{name}" if cls else f"{mod}.{name}"


@dataclasses.dataclass
class _ClassInfo:
    module: str
    name: str
    bases: List[str]
    locks: Dict[str, LockDef] = dataclasses.field(default_factory=dict)
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)
    methods: Dict[str, _FuncInfo] = dataclasses.field(default_factory=dict)
    prop_types: Dict[str, str] = dataclasses.field(default_factory=dict)


class _ModuleCollector:
    """First pass over one module: imports, classes, lock inventory."""

    def __init__(self, module: str, relpath: str, tree: ast.Module,
                 is_package: bool = False):
        self.module = module
        self.relpath = relpath
        self.tree = tree
        self.is_package = is_package
        self.imports: Dict[str, str] = {}     # local alias -> dotted module
        self.symbols: Dict[str, Tuple[str, str]] = {}  # name -> (module, sym)
        self.classes: Dict[str, _ClassInfo] = {}
        self.module_locks: Dict[str, LockDef] = {}
        self.module_funcs: Dict[str, ast.AST] = {}
        self.module_instances: Dict[str, str] = {}  # global -> class name

    def collect(self):
        # imports are collected from the WHOLE tree (function-level
        # lazy imports are the repo's cycle-avoidance idiom and still
        # name lock-owning modules, e.g. the engine's late
        # ``from ..resilience import graceful as _graceful``)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                base = self._resolve_from(node)
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.symbols[a.asname or a.name] = (base, a.name)
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                self._collect_class(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.module_funcs[node.name] = node
            elif isinstance(node, ast.Assign):
                self._module_lock(node)

    def _resolve_from(self, node: ast.ImportFrom) -> str:
        if node.level == 0:
            return node.module or ""
        parts = self.module.split(".")
        # a package __init__ IS its own level-1 base: ``from .hooks
        # import dispatch`` in monitor/__init__.py means monitor.hooks,
        # not a sibling of monitor
        strip = node.level - (1 if self.is_package else 0)
        base = parts[:len(parts) - strip] if strip else parts
        if node.module:
            base.append(node.module)
        return ".".join(base)

    # -- lock/type inventory ---------------------------------------------

    def _module_lock(self, node: ast.Assign):
        if len(node.targets) != 1 or not isinstance(node.targets[0], ast.Name):
            return
        name = node.targets[0].id
        info = self._lock_expr(node.value, None)
        if info is None:
            # module-level singleton: ``_collector = SpanCollector()`` —
            # method calls on the global resolve to the class
            t = _ctor_class(node.value)
            if t:
                self.module_instances[name] = t
            return
        kind, reentrant, literal, alias = info
        lid = literal or f"{self.module}.{name}"
        self.module_locks[name] = LockDef(
            id=lid, kind=kind, module=self.module, cls=None, attr=name,
            line=node.lineno, reentrant=reentrant, alias_of=alias)

    def _collect_class(self, node: ast.ClassDef):
        ci = _ClassInfo(module=self.module, name=node.name,
                        bases=[b.id for b in node.bases
                               if isinstance(b, ast.Name)])
        self.classes[node.name] = ci
        for stmt in node.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                # class-level lock (shared across instances)
                info = self._lock_expr(stmt.value, ci)
                if info:
                    kind, reentrant, literal, alias = info
                    attr = stmt.targets[0].id
                    lid = literal or f"{self.module}.{node.name}.{attr}"
                    ci.locks[attr] = LockDef(
                        id=lid, kind=kind, module=self.module, cls=node.name,
                        attr=attr, line=stmt.lineno, reentrant=reentrant,
                        alias_of=alias)
            elif isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name):
                # annotated class field (dataclass idiom): the annotation
                # types the attr — `future: ServingFuture` is how the
                # request record names its future, and resolving
                # r.future._settle() through it is what lets the static
                # graph predict the ServingEngine._lock ->
                # ServingFuture._lock runtime edge
                t = _ann_class(stmt.annotation)
                if t:
                    ci.attr_types.setdefault(stmt.target.id, t)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                is_prop = any(
                    (isinstance(d, ast.Name) and d.id == "property")
                    or (isinstance(d, ast.Attribute) and d.attr in
                        ("property", "cached_property"))
                    for d in stmt.decorator_list)
                if is_prop and stmt.returns is not None:
                    t = _ann_class(stmt.returns)
                    if t:
                        ci.prop_types[stmt.name] = t
                self._scan_method_attrs(ci, stmt)

    def _scan_method_attrs(self, ci: _ClassInfo, fn):
        """self.X = threading.Lock()/make_lock(...)/ClassName(...)/param."""
        ann: Dict[str, str] = {}
        for arg in list(fn.args.args) + list(fn.args.kwonlyargs):
            if arg.annotation is not None:
                t = _ann_class(arg.annotation)
                if t:
                    ann[arg.arg] = t
        for sub in ast.walk(fn):
            if not (isinstance(sub, ast.Assign) and len(sub.targets) == 1):
                continue
            tgt = sub.targets[0]
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                continue
            attr = tgt.attr
            info = self._lock_expr(sub.value, ci)
            if info:
                kind, reentrant, literal, alias = info
                if attr not in ci.locks:
                    lid = literal or f"{self.module}.{ci.name}.{attr}"
                    ci.locks[attr] = LockDef(
                        id=lid, kind=kind, module=self.module, cls=ci.name,
                        attr=attr, line=sub.lineno, reentrant=reentrant,
                        alias_of=alias)
                continue
            # self.x = ClassName(...)
            t = _ctor_class(sub.value)
            if t:
                ci.attr_types.setdefault(attr, t)
                continue
            # self.x = param  (annotated, or named like a lock)
            if isinstance(sub.value, ast.Name):
                pname = sub.value.id
                if pname in ann:
                    t = ann[pname]
                    if t in ("Lock", "RLock"):
                        ci.locks.setdefault(attr, LockDef(
                            id=f"{self.module}.{ci.name}.{attr}",
                            kind="unknown", module=self.module, cls=ci.name,
                            attr=attr, line=sub.lineno, reentrant=True))
                    else:
                        ci.attr_types.setdefault(attr, t)
                elif "lock" in pname.lower() and attr not in ci.locks:
                    # untyped lock-ish parameter (the registry's shared
                    # lock idiom): a lock node, assumed reentrant so an
                    # unknowable kind never fabricates a PT800 self-cycle
                    ci.locks.setdefault(attr, LockDef(
                        id=f"{self.module}.{ci.name}.{attr}",
                        kind="unknown", module=self.module, cls=ci.name,
                        attr=attr, line=sub.lineno, reentrant=True))

    def _lock_expr(self, value, ci: Optional[_ClassInfo]):
        """Recognize a lock-constructing expression.

        Returns (kind, reentrant, literal_name_or_None, alias_of_or_None)
        or None.
        """
        if not isinstance(value, ast.Call):
            return None
        fname = _dotted(value.func)
        if not fname:
            return None
        tail = fname.split(".")[-1]
        head = fname.split(".")[0]
        is_threading = (head == "threading" or fname == tail)
        if tail == "Lock" and is_threading:
            return ("lock", False, None, None)
        if tail == "RLock" and is_threading:
            return ("rlock", True, None, None)
        if tail == "Event" and is_threading:
            return ("event", False, None, None)
        if tail == "Condition" and is_threading:
            alias = self._cond_alias(value, ci)
            return ("condition", True, None, alias)
        if tail in ("make_lock", "make_rlock", "make_condition"):
            literal = None
            if value.args and isinstance(value.args[0], ast.Constant) \
                    and isinstance(value.args[0].value, str):
                literal = value.args[0].value
            if tail == "make_lock":
                return ("lock", False, literal, None)
            if tail == "make_rlock":
                return ("rlock", True, literal, None)
            alias = self._cond_alias(value, ci, arg_idx=1)
            return ("condition", True, literal if alias is None else None,
                    alias)
        return None

    def _cond_alias(self, call: ast.Call, ci: Optional[_ClassInfo],
                    arg_idx: int = 0) -> Optional[str]:
        """Condition(lock) / make_condition(name, lock): underlying lock."""
        args = call.args[arg_idx:]
        if not args:
            return None
        a = args[0]
        if isinstance(a, ast.Attribute) and isinstance(a.value, ast.Name) \
                and a.value.id == "self" and ci and a.attr in ci.locks:
            return ci.locks[a.attr].node
        if isinstance(a, ast.Name) and a.id in self.module_locks:
            return self.module_locks[a.id].node
        return None


def _dotted(node) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _ann_class(node) -> Optional[str]:
    """Class name out of an annotation (unwraps Optional[X] / 'X')."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        name = node.value.strip()
        return name.split("[")[0].split(".")[-1] if name else None
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Subscript):
        base = _dotted(node.value) or ""
        if base.split(".")[-1] in ("Optional", "Union"):
            inner = node.slice
            if isinstance(inner, ast.Tuple):
                for el in inner.elts:
                    t = _ann_class(el)
                    if t and t != "None":
                        return t
                return None
            return _ann_class(inner)
        return None
    return None


def _ctor_class(value) -> Optional[str]:
    """'Foo' for ``Foo(...)`` / ``mod.Foo(...)`` constructor calls."""
    if not isinstance(value, ast.Call):
        return None
    name = _dotted(value.func)
    if not name:
        return None
    tail = name.split(".")[-1]
    if tail and tail[0].isupper():
        return tail
    return None


def _has_timeout(call: ast.Call) -> bool:
    if call.args:
        return True
    return any(kw.arg in ("timeout", "block") for kw in call.keywords)


# --------------------------------------------------------------------------
# function-body walk
# --------------------------------------------------------------------------

class _Analyzer:
    def __init__(self):
        self.collectors: Dict[str, _ModuleCollector] = {}
        self.class_index: Dict[str, List[_ClassInfo]] = {}
        self.funcs: Dict[Tuple, _FuncInfo] = {}
        self.relpaths: Dict[str, str] = {}

    # -- loading ---------------------------------------------------------

    def load(self, path: str, module: str, relpath: str):
        with open(path, "r", encoding="utf-8") as f:
            src = f.read()
        tree = ast.parse(src, filename=relpath)
        col = _ModuleCollector(
            module, relpath, tree,
            is_package=os.path.basename(path) == "__init__.py")
        col.collect()
        self.collectors[module] = col
        self.relpaths[module] = relpath
        for ci in col.classes.values():
            self.class_index.setdefault(ci.name, []).append(ci)

    def find_class(self, name: str, prefer_module: str) -> \
            Optional[_ClassInfo]:
        cands = self.class_index.get(name, [])
        if not cands:
            return None
        for ci in cands:
            if ci.module == prefer_module:
                return ci
        return cands[0] if len(cands) == 1 else None

    # -- walking ---------------------------------------------------------

    def walk_all(self):
        for module, col in self.collectors.items():
            for cname, ci in col.classes.items():
                node = None
                for stmt in col.tree.body:
                    if isinstance(stmt, ast.ClassDef) and stmt.name == cname:
                        node = stmt
                        break
                if node is None:
                    continue
                for stmt in node.body:
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._walk_function(col, ci, stmt)
            for fname, fnode in col.module_funcs.items():
                self._walk_function(col, None, fnode)

    def _walk_function(self, col: _ModuleCollector,
                       ci: Optional[_ClassInfo], fn,
                       name_override: Optional[str] = None):
        key = (col.module, ci.name if ci else None,
               name_override or fn.name)
        info = _FuncInfo(key=key, site=f"{col.relpath}:{fn.lineno}")
        self.funcs[key] = info
        env: Dict[str, str] = {}    # local var -> class name
        kinds: Dict[str, str] = {}  # local var -> receiver kind
        for arg in list(fn.args.args) + list(fn.args.kwonlyargs):
            if arg.annotation is not None:
                t = _ann_class(arg.annotation)
                if t:
                    env[arg.arg] = t
        self._walk_body(col, ci, info, fn.body, (), env, kinds)

    # the core recursive walk; ``held`` is a tuple of lock node ids
    def _walk_body(self, col, ci, info, stmts, held, env, kinds):
        for stmt in stmts:
            self._walk_stmt(col, ci, info, stmt, held, env, kinds)

    def _walk_stmt(self, col, ci, info, stmt, held, env, kinds):
        site = f"{col.relpath}:{stmt.lineno}"
        if isinstance(stmt, ast.With):
            inner = held
            for item in stmt.items:
                node = self._lock_of(col, ci, item.context_expr, env)
                if node is not None:
                    info.acquires.append((inner, node, site))
                    inner = inner + (node,)
                else:
                    # not a lock: still scan the expression for calls
                    self._walk_expr(col, ci, info, item.context_expr,
                                    inner, env, kinds)
                    # a class-instance context manager runs __enter__ and
                    # __exit__ with everything acquired so far still held
                    # (RecordEvent's __exit__ takes the profiler lock)
                    cm = item.context_expr
                    ckey = self._callee_key(col, ci, cm.func, env, kinds) \
                        if isinstance(cm, ast.Call) else None
                    if ckey is None and not isinstance(cm, ast.Call):
                        ckey = self._callee_key(col, ci, cm, env, kinds)
                    cm_cls = None
                    if ckey and ckey[1] is not None \
                            and ckey[2] == "__init__":
                        cm_cls = (ckey[0], ckey[1])
                    elif ckey and ckey[1] is None:
                        # factory function: the return annotation names
                        # the context-manager class (trace.span -> Span)
                        fcol = self.collectors.get(ckey[0])
                        node = fcol.module_funcs.get(ckey[2]) \
                            if fcol else None
                        ret = _ann_class(getattr(node, "returns", None)) \
                            if node is not None else None
                        if ret:
                            cm_cls = (ckey[0], ret)
                    if cm_cls is not None:
                        ccol = self.collectors.get(cm_cls[0])
                        cci = ccol.classes.get(cm_cls[1]) if ccol else None
                        if cci is None:
                            cci = self.find_class(cm_cls[1], cm_cls[0])
                        if cci is not None:
                            for dunder in ("__enter__", "__exit__"):
                                mkey = self._method_in(cci, dunder)
                                if mkey:
                                    info.calls.append((inner, mkey, site))
            self._walk_body(col, ci, info, stmt.body, inner, env, kinds)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: analyzed as its own pseudo-function so a
            # Thread(target=inner) entry point resolves to it
            nested_name = f"{info.key[2]}.<locals>.{stmt.name}"
            self._walk_function(col, ci, stmt, name_override=nested_name)
            env[stmt.name] = ""       # not a class instance
            kinds[stmt.name] = "localfunc:" + nested_name
            return
        if isinstance(stmt, ast.ClassDef):
            return  # nested helper class: out of model
        if isinstance(stmt, ast.Assign):
            self._track_assign(col, ci, stmt, env, kinds)
            for tgt in stmt.targets:
                self._record_attr_target(ci, info, tgt, held, site)
            self._walk_expr(col, ci, info, stmt.value, held, env, kinds)
            return
        if isinstance(stmt, ast.AugAssign):
            self._record_attr_target(ci, info, stmt.target, held, site)
            # an augmented write also reads
            self._record_attr_read(ci, info, stmt.target, held, site)
            self._walk_expr(col, ci, info, stmt.value, held, env, kinds)
            return
        # generic: recurse into child statements with the same held set,
        # and scan expressions
        for field in ast.iter_child_nodes(stmt):
            if isinstance(field, ast.stmt):
                self._walk_stmt(col, ci, info, field, held, env, kinds)
            elif isinstance(field, ast.expr):
                self._walk_expr(col, ci, info, field, held, env, kinds)
            elif isinstance(field, (ast.excepthandler,)):
                self._walk_body(col, ci, info, field.body, held, env, kinds)

    def _walk_expr(self, col, ci, info, expr, held, env, kinds):
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._record_call(col, ci, info, node, held, env, kinds)
            elif isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                self._record_attr_read(ci, info, node, held,
                                       f"{col.relpath}:{node.lineno}")

    # -- events ----------------------------------------------------------

    def _record_attr_target(self, ci, info, tgt, held, site):
        if isinstance(tgt, ast.Tuple):
            for el in tgt.elts:
                self._record_attr_target(ci, info, el, held, site)
            return
        if isinstance(tgt, ast.Subscript):
            # self.d[k] = v mutates self.d
            tgt = tgt.value
        if isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name) \
                and tgt.value.id == "self" and ci is not None:
            info.attr_events.append((tgt.attr, True, bool(held), site))

    def _record_attr_read(self, ci, info, node, held, site):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self" and ci is not None:
            info.attr_events.append((node.attr, False, bool(held), site))

    def _track_assign(self, col, ci, stmt, env, kinds):
        if len(stmt.targets) != 1 or not isinstance(stmt.targets[0],
                                                    ast.Name):
            return
        name = stmt.targets[0].id
        t = _ctor_class(stmt.value)
        if t:
            env[name] = t
            k = self._ctor_kind(stmt.value)
            if k:
                kinds[name] = k
            return
        # plan = active_plan(): a resolvable call whose return annotation
        # names the class types the local — this is what lets
        # `plan.hit(site)` (fault_point) resolve to FaultPlan.hit and
        # predict the caller-held-lock -> FaultPlan._lock edge
        if isinstance(stmt.value, ast.Call):
            ckey = self._callee_key(col, ci, stmt.value.func, env, kinds)
            ret = self._return_class(ckey) if ckey else None
            if ret:
                env[name] = ret
                return
        # x = self.attr  (typed attr or property)
        if isinstance(stmt.value, ast.Attribute) \
                and isinstance(stmt.value.value, ast.Name) \
                and stmt.value.value.id == "self" and ci is not None:
            attr = stmt.value.attr
            if attr in ci.attr_types:
                env[name] = ci.attr_types[attr]
            elif attr in ci.prop_types:
                env[name] = ci.prop_types[attr]
            elif attr in ci.locks and ci.locks[attr].kind == "event":
                kinds[name] = "event"

    def _return_class(self, key: Tuple) -> Optional[str]:
        """Class named by the resolved callee's return annotation (the
        class itself for a ``__init__`` key)."""
        mod, cls, fname = key
        if cls is not None and fname == "__init__":
            return cls
        c = self.collectors.get(mod)
        if c is None:
            return None
        node = None
        if cls is None:
            node = c.module_funcs.get(fname)
        else:
            for stmt in c.tree.body:
                if isinstance(stmt, ast.ClassDef) and stmt.name == cls:
                    for s in stmt.body:
                        if isinstance(s, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)) \
                                and s.name == fname:
                            node = s
                            break
                    break
        if node is None or getattr(node, "returns", None) is None:
            return None
        return _ann_class(node.returns)

    def _ctor_kind(self, call: ast.Call) -> Optional[str]:
        name = _dotted(call.func) or ""
        tail = name.split(".")[-1]
        return {"Popen": "popen", "Thread": "thread", "Queue": "queue",
                "LifoQueue": "queue", "PriorityQueue": "queue",
                "socket": "socket", "HTTPConnection": "httpconn",
                "HTTPSConnection": "httpconn", "Event": "event",
                }.get(tail)

    # -- lock resolution -------------------------------------------------

    def _class_lock(self, ci: Optional[_ClassInfo],
                    attr: str) -> Optional[LockDef]:
        """Lock attribute lookup through the MRO approximation (subclass
        engines inherit ``_lock``/``_work`` from ServingEngine)."""
        seen: Set[str] = set()
        cur = ci
        while cur and cur.name not in seen:
            seen.add(cur.name)
            if attr in cur.locks:
                return cur.locks[attr]
            nxt = None
            for b in cur.bases:
                nxt = self.find_class(b, cur.module)
                if nxt:
                    break
            cur = nxt
        return None

    def _lock_of(self, col, ci, expr, env) -> Optional[str]:
        """Lock graph node acquired by ``with <expr>:`` (or None)."""
        d = self._lock_def_of(col, ci, expr, env)
        if d is not None and d.kind in _LOCK_KINDS + ("unknown",):
            return d.node
        return None

    def _lock_def_of(self, col, ci, expr, env) -> Optional[LockDef]:
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name):
                if base.id == "self" and ci is not None:
                    return self._class_lock(ci, expr.attr)
                # local var with inferred class type
                t = env.get(base.id)
                if t:
                    other = self.find_class(t, col.module)
                    if other:
                        return self._class_lock(other, expr.attr)
                # imported module global: mod.LOCK
                if base.id in col.imports or base.id in col.symbols:
                    target = self._module_of_alias(col, base.id)
                    if target and target in self.collectors:
                        return self.collectors[target].module_locks.get(
                            expr.attr)
                return None
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self" and ci is not None:
                # self.attr.LOCK where attr type is known
                t = ci.attr_types.get(base.attr) \
                    or ci.prop_types.get(base.attr)
                if t:
                    other = self.find_class(t, col.module)
                    if other:
                        return self._class_lock(other, expr.attr)
            return None
        if isinstance(expr, ast.Name):
            if expr.id in col.module_locks:
                return col.module_locks[expr.id]
            if expr.id in col.symbols:
                mod, sym = col.symbols[expr.id]
                if mod in self.collectors:
                    return self.collectors[mod].module_locks.get(sym)
        return None

    def _module_of_alias(self, col, alias: str) -> Optional[str]:
        if alias in col.symbols:
            mod, sym = col.symbols[alias]
            cand = f"{mod}.{sym}" if mod else sym
            if cand in self.collectors:
                return cand
            return mod if mod in self.collectors else None
        if alias in col.imports:
            return col.imports[alias]
        return None

    # -- call recording --------------------------------------------------

    def _record_call(self, col, ci, info, call: ast.Call, held, env, kinds):
        site = f"{col.relpath}:{call.lineno}"
        # thread entry points
        name = _dotted(call.func) or ""
        tail = name.split(".")[-1]
        if tail == "Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    tkey = self._callee_key(col, ci, kw.value, env, kinds)
                    if tkey:
                        info.thread_targets.append((tkey, site))
        # blocking?
        what = self._blocking_what(col, ci, call, env, kinds, held)
        if what:
            info.blocking.append((held, what, site))
        # call-graph edge
        ckey = self._callee_key(col, ci, call.func, env, kinds)
        if ckey:
            info.calls.append((held, ckey, site))
        # a local function passed as a callable argument is conservatively
        # invoked by the callee with the caller's locks still held
        # (call_with_retry(_build) and friends run it synchronously);
        # Thread targets are excluded — a new thread starts with NO locks
        if tail != "Thread":
            for arg in list(call.args) + [kw.value for kw in call.keywords]:
                if isinstance(arg, ast.Name) \
                        and kinds.get(arg.id, "").startswith("localfunc:"):
                    nested = (col.module, ci.name if ci else None,
                              kinds[arg.id].split(":", 1)[1])
                    info.calls.append((held, nested, site))

    def _callee_key(self, col, ci, func, env, kinds) -> Optional[Tuple]:
        """(module, class, name) the call/reference resolves to, or None."""
        if isinstance(func, ast.Name):
            nm = func.id
            if kinds.get(nm, "").startswith("localfunc:"):
                return (col.module, ci.name if ci else None,
                        kinds[nm].split(":", 1)[1])
            if nm in col.module_funcs:
                return (col.module, None, nm)
            if nm in col.symbols:
                mod, sym = col.symbols[nm]
                if mod in self.collectors:
                    c = self.collectors[mod]
                    if sym in c.module_funcs:
                        return (mod, None, sym)
                    if sym in c.classes:
                        return (mod, sym, "__init__")
            if nm in col.classes:
                return (col.module, nm, "__init__")
            return None
        if not isinstance(func, ast.Attribute):
            return None
        meth = func.attr
        base = func.value
        if isinstance(base, ast.Name):
            if base.id == "self" and ci is not None:
                target = self._method_in(ci, meth)
                if target:
                    return target
                return None
            t = env.get(base.id) or col.module_instances.get(base.id)
            if not t and base.id in col.symbols:
                # imported module-level singleton
                mod, sym = col.symbols[base.id]
                c = self.collectors.get(mod)
                if c:
                    t = c.module_instances.get(sym)
            if t:
                other = self.find_class(t, col.module)
                if other:
                    return self._method_in(other, meth)
                return None
            target_mod = self._module_of_alias(col, base.id)
            if target_mod and target_mod in self.collectors:
                c = self.collectors[target_mod]
                if meth in c.module_funcs:
                    return (target_mod, None, meth)
                if meth in c.classes:
                    return (target_mod, meth, "__init__")
            return None
        if isinstance(base, ast.Attribute) and isinstance(base.value,
                                                          ast.Name):
            t = None
            if base.value.id == "self" and ci is not None:
                t = ci.attr_types.get(base.attr) \
                    or ci.prop_types.get(base.attr)
            else:
                # r.future._settle() where r's class is known (annotated
                # param / tracked local) and its class types the attr
                t0 = env.get(base.value.id)
                rcls = self.find_class(t0, col.module) if t0 else None
                if rcls is not None:
                    t = rcls.attr_types.get(base.attr) \
                        or rcls.prop_types.get(base.attr)
            if t:
                other = self.find_class(t, col.module)
                if other:
                    return self._method_in(other, meth)
        if isinstance(base, ast.Call):
            # get_tracker().observe(...): the accessor's return annotation
            # names the receiver class
            inner = self._callee_key(col, ci, base.func, env, kinds)
            if inner is not None:
                mod, cls, fname = inner
                if cls is not None and fname == "__init__":
                    # ClassName(...).method()
                    icol = self.collectors.get(mod)
                    icls = icol.classes.get(cls) if icol else None
                    if icls is not None:
                        return self._method_in(icls, meth)
                icol = self.collectors.get(mod)
                node = icol.module_funcs.get(fname) if icol and cls is None \
                    else None
                ret = _ann_class(node.returns) \
                    if node is not None and getattr(node, "returns", None) \
                    else None
                if ret:
                    other = self.find_class(ret, mod)
                    if other is not None:
                        return self._method_in(other, meth)
        return None

    def _method_in(self, ci: _ClassInfo, meth: str) -> Optional[Tuple]:
        seen = set()
        cur: Optional[_ClassInfo] = ci
        while cur and cur.name not in seen:
            seen.add(cur.name)
            key = (cur.module, cur.name, meth)
            if key in self.funcs or self._class_has_method(cur, meth):
                return key
            nxt = None
            for b in cur.bases:
                nxt = self.find_class(b, cur.module)
                if nxt:
                    break
            cur = nxt
        return None

    def _class_has_method(self, ci: _ClassInfo, meth: str) -> bool:
        col = self.collectors.get(ci.module)
        if not col:
            return False
        for stmt in col.tree.body:
            if isinstance(stmt, ast.ClassDef) and stmt.name == ci.name:
                return any(isinstance(s, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                           and s.name == meth for s in stmt.body)
        return False

    # -- blocking detection ----------------------------------------------

    def _blocking_what(self, col, ci, call, env, kinds, held) -> \
            Optional[str]:
        name = _dotted(call.func)
        if name:
            resolved = self._resolve_func_name(col, name)
            if resolved in _BLOCKING_FUNCS:
                return _BLOCKING_FUNCS[resolved]
        if not isinstance(call.func, ast.Attribute):
            return None
        meth = call.func.attr
        if meth == "block_until_ready":
            return "block_until_ready"
        recv = call.func.value
        kind = self._receiver_kind(col, ci, recv, env, kinds)
        if kind == "event" and meth == "wait" and not _has_timeout(call):
            return "Event.wait (no timeout)"
        if kind == "condition" and meth == "wait":
            # Condition.wait releases its own lock; only waiting while
            # holding a *different* lock blocks other threads
            d = self._lock_def_of(col, ci, recv, env)
            own = {d.node} if d is not None else set()
            others = [h for h in held if h not in own]
            if others:
                return "Condition.wait holding another lock"
            return None
        if kind in _BLOCKING_METHODS and meth in _BLOCKING_METHODS[kind]:
            if meth in ("wait", "join", "get", "put", "communicate") \
                    and _has_timeout(call):
                return None
            if meth.endswith("_nowait"):
                return None
            return f"{kind}.{meth}"
        return None

    def _resolve_func_name(self, col, dotted_name: str) -> str:
        head, _, rest = dotted_name.partition(".")
        if head in col.imports:
            base = col.imports[head]
            return f"{base}.{rest}" if rest else base
        if head in col.symbols:
            mod, sym = col.symbols[head]
            full = f"{mod}.{sym}" if mod else sym
            return f"{full}.{rest}" if rest else full
        return dotted_name

    def _receiver_kind(self, col, ci, recv, env, kinds) -> Optional[str]:
        d = self._lock_def_of(col, ci, recv, env)
        if d is not None:
            return d.kind
        if isinstance(recv, ast.Name):
            return kinds.get(recv.id)
        if isinstance(recv, ast.Attribute) \
                and isinstance(recv.value, ast.Name) \
                and recv.value.id == "self" and ci is not None:
            t = ci.attr_types.get(recv.attr)
            return {"Popen": "popen", "Thread": "thread", "Queue": "queue",
                    "HTTPConnection": "httpconn", "Event": "event",
                    }.get(t or "")
        if isinstance(recv, ast.Call):
            return self._ctor_kind(recv)
        return None


# --------------------------------------------------------------------------
# graph construction + diagnostics
# --------------------------------------------------------------------------

def _transitive_sets(analyzer: _Analyzer):
    """Fixed point of acquires*(f) and blocking*(f) over the call graph."""
    acquires: Dict[Tuple, Set[str]] = {}
    blocking: Dict[Tuple, Dict[str, str]] = {}   # what -> via path
    for key, fn in analyzer.funcs.items():
        acquires[key] = {node for _, node, _ in fn.acquires}
        blocking[key] = {what: fn.qualname for _, what, _ in fn.blocking}
    changed = True
    while changed:
        changed = False
        for key, fn in analyzer.funcs.items():
            for _, callee, _ in fn.calls:
                if callee not in acquires:
                    continue
                extra = acquires[callee] - acquires[key]
                if extra:
                    acquires[key] |= extra
                    changed = True
                for what, via in blocking[callee].items():
                    if what not in blocking[key]:
                        blocking[key][what] = via
                        changed = True
    return acquires, blocking


def _guard_sets(analyzer: _Analyzer) -> Dict[Tuple, Set[str]]:
    """Locks held at EVERY resolved call site of each function.

    The repo's ``_foo_locked`` helper idiom puts state access in methods
    whose body never names the lock — the caller holds it.  This is the
    meet-over-call-sites dataflow that recovers that: ``guard(f)`` is the
    intersection over all resolved calls to ``f`` of (locks lexically
    held at the site ∪ the caller's own guard).  Functions with no
    resolved caller (entry points, public API) have an empty guard.
    Optimistic (greatest-fixpoint) iteration, so mutually recursive
    helpers that are only ever entered under the lock keep it.
    """
    guard: Dict[Tuple, Optional[Set[str]]] = \
        {k: None for k in analyzer.funcs}        # None = unknown (top)
    callers: Dict[Tuple, List[Tuple[Tuple, Tuple[str, ...]]]] = {}
    for key, fn in analyzer.funcs.items():
        for held, callee, _ in fn.calls:
            if callee in guard:
                callers.setdefault(callee, []).append((key, held))
    changed = True
    while changed:
        changed = False
        for callee, sites in callers.items():
            inbound: Optional[Set[str]] = None
            for caller_key, held in sites:
                g = guard.get(caller_key)
                if g is None and callers.get(caller_key):
                    continue           # caller still unresolved: skip
                eff = set(held) | (g or set())
                inbound = set(eff) if inbound is None else (inbound & eff)
            if inbound is None:
                continue
            prev = guard[callee]
            if prev is not None:
                inbound &= prev        # enforce monotone descent
                if inbound == prev:
                    continue
            guard[callee] = inbound
            changed = True
    return {k: (v or set()) for k, v in guard.items()}


def _find_cycles(nodes: Set[str], edges: Set[Tuple[str, str]]) -> \
        List[List[str]]:
    """SCCs with more than one node, plus self-loops (Tarjan)."""
    adj: Dict[str, List[str]] = {n: [] for n in nodes}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, [])
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    counter = [0]
    out: List[List[str]] = []

    def strongconnect(v):
        # iterative Tarjan to stay clear of recursion limits
        work = [(v, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            recurse = False
            succ = adj.get(node, [])
            for i in range(pi, len(succ)):
                w = succ[i]
                if w not in index:
                    work[-1] = (node, i + 1)
                    work.append((w, 0))
                    recurse = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if recurse:
                continue
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1 or (node, node) in edges:
                    out.append(sorted(scc))
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])

    for n in sorted(adj):
        if n not in index:
            strongconnect(n)
    return out


def _analyze(analyzer: _Analyzer) -> ConcurrencyReport:
    analyzer.walk_all()
    acquires, blocking = _transitive_sets(analyzer)
    guards = _guard_sets(analyzer)

    # lock inventory
    locks: Dict[str, LockDef] = {}
    for col in analyzer.collectors.values():
        for d in col.module_locks.values():
            locks.setdefault(d.id, d)
        for ci in col.classes.values():
            for d in ci.locks.values():
                locks.setdefault(d.id, d)
    reentrant_nodes = {d.node for d in locks.values()
                       if d.reentrant or d.kind == "unknown"}

    edges: List[LockEdge] = []
    edge_keys: Set[Tuple[str, str]] = set()
    diags: List[Diagnostic] = []
    diag_keys: Set[Tuple[str, str]] = set()

    def add_diag(code, key, message, site):
        if (code, key) in diag_keys:
            return
        diag_keys.add((code, key))
        diags.append(Diagnostic(code=code, message=message,
                                op_type=key, site=site))

    def add_edge(src, dst, site, via=""):
        if src == dst:
            if src not in reentrant_nodes:
                add_diag(
                    "PT800", src,
                    f"non-reentrant lock '{src}' re-acquired while already "
                    f"held{' via ' + via if via else ''} — guaranteed "
                    f"self-deadlock", site)
            return
        if (src, dst) not in edge_keys:
            edge_keys.add((src, dst))
            edges.append(LockEdge(src=src, dst=dst, site=site, via=via))

    for key, fn in analyzer.funcs.items():
        guard = guards.get(key, set())
        for held, node, site in fn.acquires:
            for h in set(held) | guard:
                add_edge(h, node, site)
        for held, callee, site in fn.calls:
            eff = set(held) | guard
            if not eff or callee not in acquires:
                continue
            callee_fn = analyzer.funcs.get(callee)
            via = callee_fn.qualname if callee_fn else ".".join(
                str(p) for p in callee if p)
            for node in acquires[callee]:
                for h in eff:
                    add_edge(h, node, site, via=via)
        # PT801: direct blocking under a held (or guard-implied) lock
        for held, what, site in fn.blocking:
            eff = set(held) | guard
            if eff:
                add_diag(
                    "PT801", f"{fn.qualname}+{what}",
                    f"{fn.qualname} calls {what} while holding "
                    f"{', '.join(sorted(eff))}", site)
        # PT801: blocking reached through a resolved call
        for held, callee, site in fn.calls:
            eff = set(held) | guard
            if not eff or callee not in blocking:
                continue
            for what, via in blocking[callee].items():
                add_diag(
                    "PT801", f"{fn.qualname}+{what}",
                    f"{fn.qualname} calls {via} (which reaches {what}) "
                    f"while holding {', '.join(sorted(eff))}", site)

    # PT800: cycles across the whole graph
    nodes = {d.node for d in locks.values()} \
        | {e.src for e in edges} | {e.dst for e in edges}
    for cycle in _find_cycles(nodes, edge_keys):
        key = "->".join(cycle)
        samples = [e for e in edges
                   if e.src in cycle and e.dst in cycle][:4]
        sites = "; ".join(f"{e.src}->{e.dst} at {e.site}" for e in samples)
        add_diag("PT800", key,
                 f"lock-order cycle between {', '.join(cycle)} ({sites})",
                 samples[0].site if samples else "")

    # PT802: unguarded cross-thread attributes
    _pt802(analyzer, guards, add_diag)

    return ConcurrencyReport(
        locks=locks, edges=edges, diagnostics=diags,
        modules=sorted(analyzer.collectors),
        functions=len(analyzer.funcs))


def _pt802(analyzer: _Analyzer, guards: Dict[Tuple, Set[str]], add_diag):
    # thread targets per class: (module, cls) -> {method name, ...}
    targets: Dict[Tuple[str, str], Set[str]] = {}
    for fn in analyzer.funcs.values():
        for tkey, _ in fn.thread_targets:
            mod, cls, name = tkey
            if cls is not None:
                targets.setdefault((mod, cls), set()).add(name)
    for (mod, cls), entry_names in sorted(targets.items()):
        col = analyzer.collectors.get(mod)
        ci = col.classes.get(cls) if col else None
        if ci is None:
            continue
        methods = {key[2]: fn for key, fn in analyzer.funcs.items()
                   if key[0] == mod and key[1] == cls}
        # transitive same-class closure of each thread entry point
        contexts: Dict[str, Set[str]] = {}
        for entry in entry_names:
            closure, frontier = set(), [entry]
            while frontier:
                m = frontier.pop()
                if m in closure or m not in methods:
                    continue
                closure.add(m)
                for _, callee, _ in methods[m].calls:
                    if callee[0] == mod and callee[1] == cls:
                        frontier.append(callee[2])
            contexts[entry] = closure
        thread_methods = set().union(*contexts.values()) if contexts else set()
        # attr -> events tagged with context label
        by_attr: Dict[str, List[Tuple[str, bool, bool, str]]] = {}
        for mname, fn in methods.items():
            if mname == "__init__" or mname.startswith("__init__.<locals>"):
                continue   # construction happens-before thread start
            labels = [e for e, cl in contexts.items() if mname in cl]
            label = labels[0] if labels else (
                "caller" if mname not in thread_methods else mname)
            guarded_fn = bool(guards.get((mod, cls, mname)))
            for attr, write, locked, site in fn.attr_events:
                by_attr.setdefault(attr, []).append(
                    (label, write, locked or guarded_fn, site))
        for attr, events in sorted(by_attr.items()):
            # locks/conditions/events (incl. inherited) are thread-safe
            if analyzer._class_lock(ci, attr) is not None \
                    or ci.attr_types.get(attr) == "Thread":
                continue
            ctxs = {label for label, _, _, _ in events}
            if len(ctxs) < 2:
                continue
            writes = [e for e in events if e[1]]
            unguarded = [e for e in events if not e[2]]
            if not writes or not unguarded:
                continue
            add_diag(
                "PT802", f"{cls}.{attr}",
                f"{cls}.{attr} is accessed from thread entry points "
                f"{sorted(c for c in ctxs if c != 'caller')} and "
                f"{'the caller side' if 'caller' in ctxs else 'nothing else'}"
                f" with {len(writes)} write(s) and {len(unguarded)} "
                f"unguarded access(es), e.g. {unguarded[0][3]}",
                unguarded[0][3])


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------

def package_source_files(root: Optional[str] = None) -> List[str]:
    """Every .py file under the ``paddle_tpu`` package directory."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for f in sorted(filenames):
            if f.endswith(".py"):
                out.append(os.path.join(dirpath, f))
    return sorted(out)


def _module_name(path: str, root: Optional[str]) -> Tuple[str, str]:
    """(dotted module name, display relpath) for one source file."""
    apath = os.path.abspath(path)
    if root:
        aroot = os.path.abspath(root)
        if apath.startswith(aroot + os.sep):
            rel = os.path.relpath(apath, os.path.dirname(aroot))
            mod = rel[:-3].replace(os.sep, ".")
            if mod.endswith(".__init__"):
                mod = mod[:-len(".__init__")]
            return mod, rel
    base = os.path.basename(apath)[:-3]
    return base, os.path.basename(apath)


def analyze_paths(paths: Sequence[str],
                  root: Optional[str] = None) -> ConcurrencyReport:
    """Analyze an explicit set of .py files (fixtures, subsets)."""
    analyzer = _Analyzer()
    for p in paths:
        mod, rel = _module_name(p, root)
        analyzer.load(p, mod, rel)
    return _analyze(analyzer)


def analyze_package(root: Optional[str] = None) -> ConcurrencyReport:
    """Analyze the whole ``paddle_tpu`` package (the CI gate input)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return analyze_paths(package_source_files(root), root=root)


def static_edge_set(report: Optional[ConcurrencyReport] = None) -> \
        Set[Tuple[str, str]]:
    """The static lock-order edge set the runtime witness gates against."""
    if report is None:
        report = analyze_package()
    return report.edge_set()
