"""Structured diagnostics for the program verifier.

The reference stack surfaces malformed ProgramDescs as C++ enforce failures
at op-construction time (op_registry.h schema checks, OpProto required-slot
enforcement); this rebuild constructs graphs in pure Python, so the same bug
class used to surface deep inside a JAX trace. ``paddle_tpu.analysis`` turns
them back into build-site diagnostics: every finding is a ``Diagnostic`` with
a stable code (documented in docs/ANALYSIS.md), a severity, the op's position
and the user call site recorded by the ``op_callstack`` attr.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

__all__ = ["Diagnostic", "Severity", "CODES", "ProgramVerificationError",
           "format_diagnostics"]


class Severity:
    ERROR = "error"      # the program cannot lower / computes garbage
    WARNING = "warning"  # suspicious; lowers, but likely not what was meant
    INFO = "info"        # observation (dead code etc.); never gates


# code -> (severity, one-line meaning). The single source of truth used by
# the verifier, the tests and docs/ANALYSIS.md.
CODES = {
    # -- pass 1: schema conformance ------------------------------------
    "PT100": (Severity.ERROR,
              "op type is not in the registry (and is not an auto-grad op)"),
    "PT101": (Severity.ERROR, "required input slot absent or empty"),
    "PT102": (Severity.ERROR, "input slot not declared by the op's schema"),
    "PT103": (Severity.ERROR, "required output slot absent or empty"),
    "PT104": (Severity.ERROR, "output slot not declared by the op's schema"),
    "PT105": (Severity.ERROR, "required attr missing"),
    "PT106": (Severity.WARNING, "attr not declared by the op's schema"),
    "PT107": (Severity.ERROR, "non-duplicable slot holds more than one var"),
    # -- pass 2: dataflow ----------------------------------------------
    "PT200": (Severity.ERROR,
              "var is read before the op that produces it (use-before-def)"),
    "PT201": (Severity.WARNING,
              "var is read but never produced, fed or scope-initialized"),
    "PT202": (Severity.WARNING,
              "write-after-write: earlier value is dead (never read)"),
    "PT203": (Severity.INFO,
              "op output is never read, not fetched and not persistable"),
    # -- pass 3: lowerability ------------------------------------------
    "PT300": (Severity.ERROR, "op's OpDef has no lower rule"),
    "PT301": (Severity.WARNING,
              "grad op whose forward op declares grad=None"),
    "PT302": (Severity.WARNING,
              "needs_rng op under FLAGS_cudnn_deterministic"),
    # -- pass 4: shape/dtype replay ------------------------------------
    "PT400": (Severity.WARNING,
              "replayed infer_shape disagrees with recorded var shape"),
    "PT401": (Severity.WARNING,
              "replayed infer_shape disagrees with recorded var dtype"),
    # -- pass 5: liveness & effects ------------------------------------
    "PT500": (Severity.WARNING,
              "donation-unsafe fetch: var is updated in place AND fetched; "
              "its buffer is excluded from donation"),
    "PT501": (Severity.WARNING,
              "write-after-fetch: var is rewritten after an explicit fetch "
              "op (compiled steps fetch final values)"),
    "PT502": (Severity.INFO,
              "dead op: no output is read, fetched or persistable"),
    "PT503": (Severity.INFO,
              "dead var: declared but never read or written by any op"),
    "PT504": (Severity.ERROR,
              "persistable var written inside a sub-block never escapes to "
              "the scope (state threading only scans the global block)"),
    # -- pass: dtype/shape consistency (whole-program replay) ----------
    "PT700": (Severity.ERROR,
              "op's infer_shape fails under whole-program replay — the "
              "producer/consumer metadata contract is broken"),
    "PT701": (Severity.WARNING,
              "producer/consumer shape mismatch: whole-program replay "
              "propagates a shape a later consumer's record disagrees "
              "with"),
    "PT702": (Severity.WARNING,
              "producer/consumer dtype mismatch: whole-program replay "
              "propagates a dtype a later consumer's record disagrees "
              "with"),
    "PT703": (Severity.WARNING,
              "conflicting producers: two ops write the same var with "
              "different inferred shape/dtype"),
    "PT704": (Severity.INFO,
              "consumer reads a var with no recorded shape — propagation "
              "is blind past this boundary"),
    # -- pass: donation/alias race detector ----------------------------
    "PT710": (Severity.INFO,
              "donation race avoided: the state_in∩state_out heuristic "
              "would donate the var but a later op still reads it after "
              "its last write — the liveness proof refuses it (safe, but "
              "costs a host copy per step)"),
    "PT711": (Severity.WARNING,
              "unordered double write: two ops write the var with no "
              "data dependency or intervening read ordering them"),
    "PT712": (Severity.WARNING,
              "donated buffer aliased into a fetch: a fetched var is a "
              "view of a donated var taken before its in-place update"),
    "PT713": (Severity.WARNING,
              "op writes a feed var in place — the fed host buffer and "
              "the scope copy can diverge"),
    # -- pass: dead/unreachable code lint -------------------------------
    "PT720": (Severity.WARNING,
              "transitively dead op: every output flows only into other "
              "dead ops (never reaches a fetch, persistable or effect)"),
    "PT721": (Severity.INFO,
              "unused output: one output of an otherwise-live op is "
              "never read, fetched or persistable"),
    "PT722": (Severity.WARNING,
              "unreachable sub-block: no op references the block via its "
              "sub_block attr"),
    # -- pass: static SPMD sharding analysis (sharding_check) -----------
    "PT730": (Severity.ERROR,
              "sharding spec references a mesh axis the mesh does not "
              "have"),
    "PT731": (Severity.ERROR,
              "sharding spec names more dims than the var has"),
    "PT732": (Severity.ERROR,
              "one mesh axis shards two different dims of the same var"),
    "PT733": (Severity.ERROR,
              "shard-indivisible dim: the dim size is not divisible by "
              "the mesh axis size"),
    "PT734": (Severity.WARNING,
              "inconsistent input specs: dims that must agree elementwise "
              "arrive with different shardings — GSPMD inserts a reshard "
              "to reconcile them"),
    "PT735": (Severity.WARNING,
              "unsatisfiable contraction: the contracted dims of a "
              "matmul-class op arrive sharded over different axes — no "
              "partial-sum layout satisfies both without resharding"),
    "PT736": (Severity.WARNING,
              "implicit full replication: a large tensor produced from "
              "sharded inputs comes out fully replicated — every chip "
              "holds (and pays for) the whole value"),
    "PT737": (Severity.WARNING,
              "resharding inside the training loop: a persistable var is "
              "produced with a different layout than it enters with — "
              "every step pays the layout change"),
    "PT738": (Severity.WARNING,
              "gradient spec disagrees with its param's spec at the "
              "optimizer update — the grad is resharded every step"),
    "PT739": (Severity.WARNING,
              "optimizer-state spec disagrees with its param's spec "
              "outside the recognized ZeRO dim-0-over-dp layout"),
    "PT740": (Severity.INFO,
              "ZeRO layout: optimizer state sharded over dp against a "
              "replicated param — each step pays a grad reduce-scatter "
              "plus a param all-gather (the intended trade)"),
    "PT741": (Severity.WARNING,
              "donation invalidated by resharding: the liveness proof "
              "donates the buffer but its input and output layouts "
              "differ, so in-place reuse is impossible (extends PT710)"),
    "PT742": (Severity.WARNING,
              "feed not sharded over the mesh's dp axis: the global "
              "batch rides every chip whole — data parallelism is not "
              "engaged"),
    "PT743": (Severity.WARNING,
              "sharded fetch: the executor pins fetches replicated, so "
              "every step all-gathers the fetched value"),
    "PT744": (Severity.INFO,
              "no sharding propagation rule for this op: specs are "
              "conservatively replicated past it"),
    # -- epilogue_fusion transform (analysis/epilogue_fusion.py) --------
    "PT750": (Severity.INFO,
              "GEMM-epilogue chain fused into one fused_gemm_epilogue op"),
    "PT751": (Severity.INFO,
              "fusion refused: a chain intermediate is fetched — the "
              "caller observes the unfused value"),
    "PT752": (Severity.INFO,
              "fusion refused: a chain intermediate has more than one "
              "consumer — fusing would recompute or break a reader"),
    "PT753": (Severity.INFO,
              "fusion refused: program carries backward/optimizer ops "
              "(epilogue fusion only proves forward-only rewrites)"),
    "PT754": (Severity.WARNING,
              "fusion fidelity witness failed — the program runs "
              "untransformed (never a wrong program)"),
    "PT755": (Severity.INFO,
              "fused chain has no kernel tiling on this backend — the "
              "dense replay of the original op rules will run"),
    "PT756": (Severity.INFO,
              "fusion refused: an op between the chain's ops rewrites a "
              "var the chain reads — the fused op's relocated reads "
              "would see the redefined value"),
    # -- source-level concurrency analysis (analysis/concurrency.py) ----
    # These three codes lint the framework's own Python source (lock
    # attributes, with-regions, thread entry points), not a Program IR;
    # Diagnostic.site carries file:line instead of an op_callstack.
    "PT800": (Severity.ERROR,
              "lock-order cycle: the static lock-order graph (nested "
              "with-regions + calls made while holding a lock) contains "
              "a cycle — two threads taking the locks in opposing order "
              "deadlock"),
    "PT801": (Severity.WARNING,
              "blocking call under a held lock: time.sleep, socket/HTTP "
              "I/O, subprocess waits, Event.wait() without timeout, "
              "block_until_ready or an unbounded queue op runs while a "
              "lock is held — every other thread needing the lock stalls "
              "for the full blocking duration"),
    "PT802": (Severity.WARNING,
              "unguarded cross-thread attribute: reachable from more "
              "than one thread entry point with at least one write and "
              "at least one access outside any lock region"),
    # -- numerics / precision analysis (analysis/numerics.py) -----------
    "PT900": (Severity.ERROR,
              "broken quant/dequant pairing: a fake-quant output is "
              "consumed where the int8 rewrite contract does not hold "
              "(non-GEMM consumer), or the quantized value is never "
              "consumed at all"),
    "PT901": (Severity.WARNING,
              "dead or non-persistable moving-average scale state in a "
              "training program: the running activation scale is not "
              "persistable (reset every step) or its update is never "
              "written back in place (the moving average never "
              "advances)"),
    "PT902": (Severity.ERROR,
              "overflowing cast: the statically-proven value interval "
              "exceeds the target dtype's finite range"),
    "PT903": (Severity.WARNING,
              "reduction accumulated in low precision: a reduce/"
              "layer_norm-family op sums a float16/bfloat16 input into a "
              "float16/bfloat16 output with no upcast around the "
              "accumulation"),
    "PT904": (Severity.WARNING,
              "AMP loss-scale coverage gap: loss scaling is active "
              "(check_finite_and_unscale present) but a gradient reaches "
              "an optimizer update without passing through unscale"),
    "PT905": (Severity.WARNING,
              "nonfinite-producing op: log/sqrt/rsqrt/div on an interval "
              "statically proven to contain 0 or negatives, with no "
              "guard narrowing the operand first"),
    "PT906": (Severity.INFO,
              "quantizable GEMM/conv site: eligible for int8 epilogue "
              "lowering (the quantizability work-list the int8 PR "
              "consumes)"),
}


@dataclasses.dataclass
class Diagnostic:
    code: str
    message: str
    block_idx: int = 0
    op_idx: Optional[int] = None
    op_type: Optional[str] = None
    site: str = ""  # user call site from the op's op_callstack attr

    @property
    def severity(self) -> str:
        return CODES[self.code][0]

    def __str__(self) -> str:
        loc = f"block {self.block_idx}"
        if self.op_idx is not None:
            loc += f" op {self.op_idx}"
        if self.op_type:
            loc += f" ({self.op_type})"
        s = f"{self.code} {self.severity}: {self.message} [{loc}]"
        if self.site:
            s += f"\n    created at {self.site}"
        return s


def format_diagnostics(diags: List[Diagnostic]) -> str:
    if not diags:
        return "no findings"
    order = {Severity.ERROR: 0, Severity.WARNING: 1, Severity.INFO: 2}
    by_sev = sorted(diags, key=lambda d: (order[d.severity], d.block_idx,
                                          d.op_idx if d.op_idx is not None
                                          else -1))
    counts = {}
    for d in diags:
        counts[d.severity] = counts.get(d.severity, 0) + 1
    head = ", ".join(f"{counts[s]} {s}(s)" for s in
                     (Severity.ERROR, Severity.WARNING, Severity.INFO)
                     if s in counts)
    return head + "\n" + "\n".join(str(d) for d in by_sev)


class ProgramVerificationError(ValueError):
    """Raised by ``check_program`` when error-severity findings exist; carries
    the full diagnostic list so callers can inspect programmatically."""

    def __init__(self, diags: List[Diagnostic]):
        self.diagnostics = diags
        errors = [d for d in diags if d.severity == Severity.ERROR]
        super().__init__(
            f"program verification failed with {len(errors)} error(s) "
            f"(FLAGS_check_program; see docs/ANALYSIS.md):\n"
            + format_diagnostics(diags))
