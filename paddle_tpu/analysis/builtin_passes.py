"""Registration of the built-in IR passes on the default PassRegistry.

The six pre-manager passes (verifier passes 1–4, liveness pass 5, auto-remat
pass 6) migrate here unchanged — their pass functions still live in
``verifier.py`` / ``liveness.py`` / ``remat.py``; this module only wraps
them in the ``Pass`` protocol — plus the three new static-analysis families
from ``static_checks.py`` and the opt-in DCE transform. Loaded lazily by
``pass_manager.get_pass_registry()`` so the import graph stays acyclic.
"""
from __future__ import annotations

from typing import List

from .diagnostics import Diagnostic
from .pass_manager import ANALYSIS, TRANSFORM, FunctionPass, PassRegistry

__all__ = ["register_builtins"]


# -- passes 1-4: the schema/dataflow/lowerability/shape_replay verifier ----

def _verifier_pass(name: str):
    def run(program, ctx) -> List[Diagnostic]:
        from .verifier import _PASS_FNS

        diags: List[Diagnostic] = []
        _PASS_FNS[name](program, diags, set(ctx.fetch_names))
        for d in diags:
            ctx.report(d)
        return diags

    run.__name__ = f"{name}_pass"
    return run


# -- pass 5: liveness (diagnostics + the cached def/use + donation data) ---

def _liveness_pass(program, ctx):
    """PT50x diagnostics plus the shared analysis products: the global
    block's ``VarLive`` chains and the donation analysis (candidates,
    refusals) that donation_race reuses from the cache. The dataflow scan
    runs ONCE — the triple is handed to check_liveness rather than
    recomputed inside it."""
    from .liveness import _donation_analysis, check_liveness

    gb = program.global_block
    feeds = {v.name for v in gb.vars.values() if v.is_data}
    feeds.update(ctx.feed_names)
    cands, unsafe, live = _donation_analysis(gb, sorted(feeds),
                                             ctx.fetch_names)
    diags: List[Diagnostic] = []
    check_liveness(program, diags, list(ctx.fetch_names),
                   donation=(cands, unsafe, live))
    for d in diags:
        ctx.report(d)
    return {"diagnostics": diags, "live": live, "cands": cands,
            "unsafe": unsafe, "feeds": feeds}


# -- pass 6: auto-remat (FLAGS_auto_recompute) -----------------------------

def _auto_remat_pass(program, ctx):
    """Transform wrapper over ``auto_recompute_program`` (analysis/remat.py).
    Options: ``budget_mb`` (FLAGS_remat_budget_mb). Returns the
    ``RematDecision`` — the manager swaps in ``decision.program`` and the
    executor reads the decision from ``result.values["auto_remat"]``."""
    from .remat import auto_recompute_program

    return auto_recompute_program(
        program,
        feed_names=list(ctx.feed_names),
        fetch_names=list(ctx.fetch_names),
        batch_size=ctx.batch_size,
        budget_mb=int(ctx.options.get("budget_mb", 0) or 0))


# -- the new static-analysis families --------------------------------------

def _dtype_shape_pass(program, ctx):
    from .static_checks import check_dtype_shape

    return check_dtype_shape(program, ctx)


def _donation_race_pass(program, ctx):
    from .static_checks import check_donation_race

    return check_donation_race(program, ctx)


def _dead_code_pass(program, ctx):
    from .static_checks import check_dead_code

    return check_dead_code(program, ctx)


def _cost_model_pass(program, ctx):
    from .cost_model import check_cost_model

    return check_cost_model(program, ctx)


def _sharding_check_pass(program, ctx):
    """Static SPMD sharding analysis (PT730-PT744): propagate shard specs
    from ctx.options' mesh + per-param assignment through every op; a
    silent no-op (None) when no mesh is supplied, so generic pipelines can
    always include the pass. Consumes the cached liveness donation
    analysis for the PT741 donation-invalidation lint."""
    from .sharding_check import check_sharding

    return check_sharding(program, ctx)


def _numerics_check_pass(program, ctx):
    """Numerics/precision analysis (analysis/numerics.py, PT900-PT906):
    value-interval + dtype-precision propagation over the recorded
    infer_shape metadata, the quant/dequant pairing contract, AMP
    loss-scale coverage and the PT906 quantizability work-list. Options:
    ``numerics_calibration`` — witness-observed abs-max seeds. Like
    sharding_check, findings-free programs pay one linear walk, so the
    full lint pipeline always includes the pass."""
    from .numerics import check_numerics

    return check_numerics(program, ctx)


def _epilogue_fusion_pass(program, ctx):
    """GEMM-epilogue fusion (analysis/epilogue_fusion.py, PT750-PT755):
    rewrite mul/matmul -> bias/activation/residual/layer_norm chains into
    fused_gemm_epilogue ops, gated by the per-chain fidelity witness.
    Consumes the cached liveness chains for the single-consumer and
    fetched-intermediate proofs. Returns the ``FusionDecision`` — the
    manager swaps in ``decision.program`` and the executor reads the
    decision from ``result.values["epilogue_fusion"]``."""
    from .epilogue_fusion import epilogue_fusion_pass

    return epilogue_fusion_pass(program, ctx)


def _dce_pass(program, ctx):
    """Opt-in dead-code elimination, proven by the fidelity witness in
    ``static_checks.dce_program`` (refuses rather than risk a wrong
    program). Reuses the cached dead_code report."""
    from .static_checks import dce_program

    report = ctx.analysis("dead_code")
    return dce_program(program, ctx.fetch_names, report=report)


def register_builtins(reg: PassRegistry) -> None:
    for name in ("schema", "dataflow", "lowerability", "shape_replay"):
        reg.register(FunctionPass(_verifier_pass(name), name, ANALYSIS))
    reg.register(FunctionPass(_liveness_pass, "liveness", ANALYSIS))
    reg.register(FunctionPass(_dtype_shape_pass, "dtype_shape_check",
                              ANALYSIS))
    reg.register(FunctionPass(_donation_race_pass, "donation_race",
                              ANALYSIS, requires=("liveness",)))
    # dead_code derives its mark-and-sweep from the effect classifier
    # directly; it does NOT consume the liveness chains, so it declares no
    # dependency (requesting only dead_code must not drag PT50x findings in)
    reg.register(FunctionPass(_dead_code_pass, "dead_code", ANALYSIS))
    reg.register(FunctionPass(_cost_model_pass, "cost_model", ANALYSIS))
    reg.register(FunctionPass(_sharding_check_pass, "sharding_check",
                              ANALYSIS, requires=("liveness",)))
    reg.register(FunctionPass(_numerics_check_pass, "numerics_check",
                              ANALYSIS))
    reg.register(FunctionPass(_auto_remat_pass, "auto_remat", TRANSFORM,
                              invalidates=("*",)))
    reg.register(FunctionPass(_epilogue_fusion_pass, "epilogue_fusion",
                              TRANSFORM, requires=("liveness",),
                              invalidates=("*",)))
    reg.register(FunctionPass(_dce_pass, "dce", TRANSFORM,
                              requires=("dead_code",),
                              invalidates=("*",)))
