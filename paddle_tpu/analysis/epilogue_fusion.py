"""Pass 7 — GEMM-epilogue fusion (the CODA rewrite as a registered
transform pass).

The Pallas kernel layer fused softmax into attention (flash_attention, PR
of the kernel round) because XLA cannot keep the score matrix out of HBM;
this pass applies the same treatment to the other matmul-shaped hot path:
the ``mul``/``matmul`` → bias-add → activation → residual-add → layer_norm
chains every fc/FFN builder emits. Matched chains rewrite into ONE
``fused_gemm_epilogue`` op (ops/fused_gemm.py) whose TPU lowering applies
the whole epilogue on the in-VMEM f32 accumulator tile
(kernels/fused_gemm.py) — and whose dense fallback replays the original op
rules bit-exactly, so a fused program is never numerically stranded off
accelerator.

Safety model — the DCE/auto-remat pattern: refuse, never a wrong program.

* **Structural gates** (per chain, via the cached liveness analysis):
  every intermediate must have exactly ONE consumer (the next chain op),
  must not be fetched, persistable, fed, or read from a sub-block; the
  chain order must be exactly the kernel's epilogue order
  (bias → activation → residual → layer_norm). layer_norm's Mean/Variance
  outputs must be dead (forward-only programs — grad ops would read them).
* **Program gate**: any backward/optimize/lr op refuses the whole program
  (PT753) — epilogue fusion only proves forward-only rewrites, and the
  fused op deliberately registers ``grad=None``.
* **Fidelity witness** (PT754): for every distinct chain signature the
  original ops and the fused op are BOTH executed over seeded concrete
  inputs through the real lowering rules (AMP policy included). On the
  dense route the comparison is exact bits (the fallback replays the same
  rules in the same order); on the kernel route it is the declared
  per-dtype tolerance (f32 accumulation reorders the sums). Any mismatch
  refuses the entire program.

The rewritten program is a fresh ``Program`` (own ``_serial``), so executor
compile caches never alias fused and plain variants. Wiring:
``Executor._maybe_epilogue_fusion`` under ``FLAGS_epilogue_fusion``;
counters in docs/OBSERVABILITY.md; methodology in docs/PERF_NOTES.md;
PT750–PT755 in docs/ANALYSIS.md.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..framework import OpRole, Program
from .diagnostics import Diagnostic
from .verifier import EMPTY, _site

__all__ = [
    "FusedChain", "FusionDecision", "WITNESS_TOLERANCES",
    "find_fusable_chains", "fuse_epilogues", "has_fusable_ops",
    "epilogue_fusion_pass",
]

# declared witness tolerances on the KERNEL route, by compute dtype: the
# kernel accumulates in f32 and applies the epilogue before one final cast,
# so it differs from the unfused chain by summation order and intermediate
# rounding. The DENSE route is compared with exact bits (tolerance 0) —
# it replays the original op rules. docs/PERF_NOTES.md "Epilogue fusion".
WITNESS_TOLERANCES: Dict[str, Tuple[float, float]] = {
    "float32": (2e-4, 1e-5),      # (rtol, atol)
    "bfloat16": (2e-2, 2e-2),
    "float16": (2e-2, 2e-2),
}

_BASE_TYPES = ("mul", "matmul")
_ACT_TYPES = ("relu", "gelu")

# chain stages, in the kernel's fixed epilogue order
_S_BASE, _S_BIAS, _S_ACT, _S_RES = 0, 1, 2, 3


@dataclasses.dataclass
class FusedChain:
    """One matched mul/matmul→epilogue chain (global-block op indices)."""

    op_indices: List[int]            # base first, in program order
    out_name: str                    # the chain's surviving output
    attrs: Dict[str, object]         # fused_gemm_epilogue attrs
    inputs: Dict[str, str]           # slot -> var name (X/Y/Bias/...)
    dead_outputs: List[str]          # e.g. layer_norm Mean/Variance
    epilogue: str                    # human label: 'bias+gelu', ...

    def label(self) -> str:
        return self.epilogue


@dataclasses.dataclass
class FusionDecision:
    """Outcome of one epilogue-fusion attempt (monitor/bench payload)."""

    applied: bool
    program: Program                 # transformed, or the original
    reason: str
    n_fused: int = 0
    n_refused: int = 0
    chains: List[dict] = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return {"applied": self.applied, "reason": self.reason,
                "fused": self.n_fused, "refused": self.n_refused,
                "chains": list(self.chains)}


def has_fusable_ops(program: Program) -> bool:
    """Cheap pre-filter for the executor hook: a forward-only program with
    at least one mul/matmul. Everything else passes through without paying
    a pipeline run."""
    saw_base = False
    for op in program.global_block.ops:
        if op.attrs.get("__op_role__", OpRole.Forward) != OpRole.Forward:
            return False
        if op.type in _BASE_TYPES:
            saw_base = True
    return saw_base


def _sole_reads(op, name: str) -> bool:
    """The op reads ``name`` through exactly one slot position."""
    return sum(1 for n in op.input_arg_names if n == name) == 1


def _static_shape(var, batch: int = 8):
    if var is None or var.shape is None:
        return None
    return tuple(batch if d == -1 else int(d) for d in var.shape)


def find_fusable_chains(program: Program, live: Dict[str, object],
                        fetch_names: Sequence[str],
                        diags: Optional[List[Diagnostic]] = None
                        ) -> List[FusedChain]:
    """Match fusable chains in the global block.

    ``live`` is the cached liveness analysis' VarLive map — its ``uses``
    lists fold sub-block reads into the owning op's index, so an
    intermediate read inside a while body correctly counts as an extra
    consumer. Refusal diagnostics (PT751/PT752/PT755) are appended to
    ``diags`` for chains that matched the grammar but failed a gate.
    """
    gb = program.global_block
    fetch = {getattr(f, "name", f) for f in (fetch_names or ())}
    diags = diags if diags is not None else []
    claimed: set = set()
    chains: List[FusedChain] = []

    def var(name):
        return gb.vars.get(name)

    def refusal(code, msg, oi, op):
        diags.append(Diagnostic(code, msg, gb.idx, oi, op.type, _site(op)))

    def sole_consumer(name: str, producer_idx: int, op, probe):
        """The single consuming op index, or None with the refusal
        recorded. A PT751 fetch-refusal goes to ``probe``: the caller
        commits it to ``diags`` only when the failure killed a would-be
        chain — when the probe merely fails to EXTEND an already-valid
        chain, the fetched value is the chain's surviving output, which
        the fused op itself writes, so nothing is hidden. PT752
        multi-consumer refusals stay unconditional (they name the real
        reason a downstream epilogue op did not fold in)."""
        if name in fetch:
            probe.append(Diagnostic(
                "PT751",
                f"'{name}' is fetched mid-chain — fusing would hide the "
                f"value the caller asked for", gb.idx, producer_idx,
                op.type, _site(op)))
            return None
        v = var(name)
        if v is None or v.persistable or v.is_data:
            return None
        vl = live.get(name)
        uses = list(getattr(vl, "uses", ())) if vl is not None else []
        if len(uses) != 1:
            refusal("PT752",
                    f"'{name}' has {len(uses)} consumers — an epilogue "
                    f"intermediate must feed exactly the next chain op",
                    producer_idx, op)
            return None
        j = uses[0]
        if j <= producer_idx or j >= len(gb.ops):
            return None
        if not _sole_reads(gb.ops[j], name):
            refusal("PT752",
                    f"op {j} reads '{name}' through more than one slot",
                    producer_idx, op)
            return None
        return j

    for i, base in enumerate(gb.ops):
        if i in claimed or base.type not in _BASE_TYPES:
            continue
        if base.type == "matmul":
            xv, yv = var(base.input("X")[0]), var(base.input("Y")[0])
            if xv is None or yv is None or xv.shape is None \
                    or yv.shape is None or len(xv.shape) != 2 \
                    or len(yv.shape) != 2:
                continue  # batched matmul: not the 2-D GEMM view
        t = base.output("Out")[0]
        out_v = var(t)
        if out_v is None or out_v.shape is None:
            continue
        out_ndim = len(out_v.shape)
        n_dim = out_v.shape[-1]

        stage = _S_BASE
        chain_ops = [i]
        parts: List[str] = []
        inputs = {"X": base.input("X")[0], "Y": base.input("Y")[0]}
        # write-hazard bookkeeping: external inputs remember where the
        # chain first READS them (the fused op moves that read to the
        # chain's last position), intermediates remember their
        # (def, read) window — a non-chain op writing into either window
        # would make the fused rewrite read a different value
        read_at = {inputs["X"]: i, inputs["Y"]: i}
        hazard_windows: List[tuple] = []
        attrs: Dict[str, object] = {
            "base_type": base.type,
            "x_num_col_dims": base.attrs.get("x_num_col_dims", 1),
            "y_num_col_dims": base.attrs.get("y_num_col_dims", 1),
            "transpose_X": base.attrs.get("transpose_X", False),
            "transpose_Y": base.attrs.get("transpose_Y", False),
            "alpha": base.attrs.get("alpha", 1.0),
            "activation": "none", "gelu_approximate": False,
            "bias_axis": -1, "residual_axis": -1,
            "layer_norm": False, "epsilon": 1e-5,
            "begin_norm_axis": out_ndim - 1,
        }
        dead_outputs: List[str] = []
        cur = t
        cur_op = base
        cur_idx = i

        probe: List[Diagnostic] = []
        while True:
            probe.clear()
            j = sole_consumer(cur, cur_idx, cur_op, probe)
            if j is None or j in claimed:
                break
            op = gb.ops[j]
            if op.type == "elementwise_add" and stage < _S_RES \
                    and op.input("X") and op.input("X")[0] == cur:
                other = op.input("Y")[0]
                ov = var(other) or (gb._var_recursive(other)
                                    if gb.has_var_recursive(other) else None)
                oshape = getattr(ov, "shape", None)
                axis = op.attrs.get("axis", -1)
                if (stage == _S_BASE and oshape is not None
                        and len(oshape) == 1 and oshape[0] == n_dim
                        and axis in (-1, out_ndim - 1)):
                    inputs["Bias"] = other
                    read_at.setdefault(other, j)
                    attrs["bias_axis"] = axis
                    parts.append("bias")
                    stage = _S_BIAS
                elif (oshape is not None
                        and tuple(oshape) == tuple(out_v.shape)):
                    inputs["Residual"] = other
                    read_at.setdefault(other, j)
                    attrs["residual_axis"] = axis
                    parts.append("residual")
                    stage = _S_RES
                else:
                    break
            elif op.type in _ACT_TYPES and stage < _S_ACT:
                attrs["activation"] = op.type
                if op.type == "gelu":
                    attrs["gelu_approximate"] = bool(
                        op.attrs.get("approximate", False))
                parts.append(op.type)
                stage = _S_ACT
            elif op.type == "layer_norm" \
                    and op.attrs.get("begin_norm_axis", 1) == out_ndim - 1:
                mean, varn = op.output("Mean")[0], op.output("Variance")[0]
                side = [n for n in (mean, varn) if n != EMPTY]
                blocked = False
                for n in side:
                    sv = var(n)
                    vl = live.get(n)
                    if (n in fetch or (sv is not None and sv.persistable)
                            or (vl is not None and getattr(vl, "uses", ()))):
                        refusal("PT752",
                                f"layer_norm side output '{n}' is consumed "
                                f"— only dead Mean/Variance can fold away",
                                j, op)
                        blocked = True
                if blocked:
                    break
                for s_slot, a_slot in (("Scale", "LnScale"),
                                       ("Bias", "LnBias")):
                    names = op.input(s_slot)
                    if names and names[0] != EMPTY:
                        inputs[a_slot] = names[0]
                        read_at.setdefault(names[0], j)
                attrs["layer_norm"] = True
                attrs["epsilon"] = op.attrs.get("epsilon", 1e-5)
                dead_outputs.extend(side)
                parts.append("layer_norm")
                hazard_windows.append((cur, cur_idx, j))
                chain_ops.append(j)
                cur = op.output("Y")[0]
                break   # terminal epilogue stage
            else:
                break
            hazard_windows.append((cur, cur_idx, j))
            chain_ops.append(j)
            cur = op.output("Out")[0]
            cur_op = op
            cur_idx = j

        if len(chain_ops) < 2:
            # the fetch-probe's failure is what killed the chain — now it
            # is a genuine refusal, not a probe past the surviving output
            diags.extend(probe)
            continue

        # an op BETWEEN the chain's ops that is not a chain member and
        # rewrites (in-place) a var the chain reads: the fused op sits at
        # the chain's LAST position, so its input reads would cross the
        # redefinition — and an intermediate clobbered between its def and
        # its read means the original chain never computed what the fused
        # op recomputes. Either way the rewrite would be numerically wrong:
        # refuse (never a wrong program).
        last = chain_ops[-1]
        member = set(chain_ops)
        windows = hazard_windows + [(nm, ridx, last)
                                    for nm, ridx in read_at.items()]
        clobber = None
        for kdx in range(i + 1, last):
            if kdx in member:
                continue
            writes = set(gb.ops[kdx].output_arg_names)
            hit = [nm for nm, lo, hi in windows
                   if nm in writes and lo < kdx and kdx <= hi]
            if hit:
                clobber = (kdx, hit[0])
                break
        if clobber is not None:
            kdx, nm = clobber
            refusal("PT756",
                    f"'{nm}' is rewritten by op {kdx} "
                    f"('{gb.ops[kdx].type}') between the chain's ops — "
                    f"the fused op at the chain's last position would "
                    f"read the redefined value", i, base)
            continue
        chains.append(FusedChain(
            op_indices=chain_ops, out_name=cur, attrs=attrs, inputs=inputs,
            dead_outputs=dead_outputs, epilogue="+".join(parts)))
        claimed.update(chain_ops)
    return chains


# ---------------------------------------------------------------------------
# the fidelity witness
# ---------------------------------------------------------------------------

def _witness_inputs(block, names: Sequence[str], batch: int = 8):
    """Deterministic concrete inputs per external chain input: seeded by a
    stable hash of the var name, shaped from the recorded metadata with -1
    dims resolved to a small sentinel."""
    from ..core.types import np_dtype
    import zlib

    env = {}
    for name in names:
        v = block._var_recursive(name)
        shape = _static_shape(v, batch)
        if shape is None:
            raise ValueError(f"witness: '{name}' has no recorded shape")
        rng = np.random.RandomState(zlib.crc32(name.encode()) & 0x7FFFFFFF)
        dt = np_dtype(v.dtype)
        vals = (rng.standard_normal(shape) * 0.5).astype(np.float32)
        env[name] = vals.astype(dt)
    return env


def _witness_signature(block, chain: FusedChain) -> tuple:
    metas = []
    for slot in sorted(chain.inputs):
        v = block._var_recursive(chain.inputs[slot])
        metas.append((slot, _static_shape(v), str(v.dtype)))
    return (tuple(sorted((k, repr(v)) for k, v in chain.attrs.items())),
            tuple(metas))


def _chain_gemm_dims(block, chain: FusedChain,
                     batch: int = 8) -> Tuple[int, int, int]:
    """(m, n, k) of the chain's strictly-2-D GEMM view, with -1 dims
    resolved to ``batch`` (the executor plumbs the real feed rows; the
    small sentinel is only the direct-call default)."""
    xv = block._var_recursive(chain.inputs["X"])
    yv = block._var_recursive(chain.inputs["Y"])
    x_shape = _static_shape(xv, batch)
    xnc = chain.attrs["x_num_col_dims"] if chain.attrs["base_type"] == \
        "mul" else 1
    if chain.attrs["base_type"] == "matmul" and chain.attrs["transpose_X"]:
        x_shape = x_shape[::-1]
    y_shape = _static_shape(yv, batch)
    if chain.attrs["base_type"] == "matmul" and chain.attrs["transpose_Y"]:
        y_shape = y_shape[::-1]
    m = int(np.prod(x_shape[:xnc]))
    k = int(np.prod(x_shape[xnc:]))
    if chain.attrs["base_type"] == "mul":
        ync = chain.attrs["y_num_col_dims"]
        n = int(np.prod(y_shape[ync:]))
    else:
        n = int(y_shape[1])
    return m, n, k


def _run_witness(program: Program, fused_program: Program,
                 chain: FusedChain, fused_op, batch: int = 8,
                 gemm_blocks=None) -> Optional[str]:
    """Execute original chain vs fused op over seeded inputs through the
    real lowering rules. Returns None on success, else the failure reason.
    Never raises — any exception is a refusal reason. ``gemm_blocks`` is
    the autotuned block config the executor will thread into the real
    compile's LowerCtx: the witness must execute the configuration that
    actually runs, not the defaults."""
    import jax.numpy as jnp

    from ..lowering import LowerCtx, lower_op

    gb = program.global_block
    try:
        ext = sorted(set(chain.inputs.values()))
        base_env = _witness_inputs(gb, ext, batch=batch)
        env_a = {k: jnp.asarray(v) for k, v in base_env.items()}
        ctx_a = LowerCtx(base_key=None, program=program)
        for oi in chain.op_indices:
            lower_op(gb.ops[oi], env_a, ctx_a)
        want = np.asarray(env_a[chain.out_name])

        env_b = {k: jnp.asarray(v) for k, v in base_env.items()}
        ctx_b = LowerCtx(base_key=None, program=fused_program,
                         gemm_blocks=gemm_blocks)
        lower_op(fused_op, env_b, ctx_b)
        got = np.asarray(env_b[chain.out_name])
    except Exception as e:
        return f"witness execution failed: {type(e).__name__}: {e}"

    if want.shape != got.shape or want.dtype != got.dtype:
        return (f"witness meta mismatch: unfused {want.dtype}{want.shape} "
                f"vs fused {got.dtype}{got.shape}")

    from ..ops.fused_gemm import fused_gemm_route, resolve_gemm_blocks

    m, n, k = _chain_gemm_dims(gb, chain, batch=batch)
    try:
        # the same flag > tuned > default resolution ctx_b's lowering
        # just used
        route, _ = fused_gemm_route(
            m, n, k, layer_norm=bool(chain.attrs["layer_norm"]),
            blocks=resolve_gemm_blocks(ctx_b),
            alpha=float(chain.attrs.get("alpha", 1.0)))
    except ValueError as e:       # use_fused_gemm=always on a bad tiling
        return str(e)
    wf = want.astype(np.float32)
    gf = got.astype(np.float32)
    if route == "primitive":
        if not np.array_equal(wf, gf):
            bad = np.abs(wf - gf)
            return (f"dense-route witness must be bit-exact; max abs diff "
                    f"{bad.max():.3e} over {int((bad > 0).sum())} element(s)")
        return None
    # tolerance keyed on the chain's COMPUTE dtype: under AMP the chain
    # multiplies in the policy's compute dtype (and promotes back to f32
    # at the epilogue params), so want.dtype alone would overstate the
    # precision the kernel is held to
    comp = str(want.dtype)
    policy = getattr(program, "_amp_policy", None)
    if policy is not None and chain.attrs["base_type"] in policy.white:
        comp = str(policy.compute_dtype)
    rtol, atol = WITNESS_TOLERANCES.get(comp,
                                        WITNESS_TOLERANCES["float32"])
    if not np.allclose(wf, gf, rtol=rtol, atol=atol):
        err = np.abs(wf - gf).max()
        return (f"kernel-route witness outside declared tolerance "
                f"(rtol={rtol}, atol={atol}): max abs diff {err:.3e}")
    return None


# ---------------------------------------------------------------------------
# the transform
# ---------------------------------------------------------------------------

def fuse_epilogues(program: Program, feed_names: Sequence[str] = (),
                   fetch_names: Sequence[str] = (),
                   live: Optional[Dict[str, object]] = None,
                   diags: Optional[List[Diagnostic]] = None,
                   batch: int = 8, gemm_blocks=None
                   ) -> FusionDecision:
    """Match + rewrite + witness. Returns a refused decision (the original
    program untouched) on any gate failure — never a wrong program.
    ``batch`` resolves -1 dims for the witness and the PT755 tiling
    report (the executor plumbs the real feed rows); ``gemm_blocks`` is
    the autotuned block config this compile will actually run with."""
    from ..framework import Operator

    diags = diags if diags is not None else []
    gb = program.global_block

    for oi, op in enumerate(gb.ops):
        role = op.attrs.get("__op_role__", OpRole.Forward)
        if role != OpRole.Forward:
            diags.append(Diagnostic(
                "PT753",
                f"op {oi} ('{op.type}') has role '{role}' — epilogue "
                f"fusion only proves forward-only rewrites",
                gb.idx, oi, op.type, _site(op)))
            return FusionDecision(False, program,
                                  "backward-carrying program")

    if live is None:
        from .liveness import block_liveness

        feeds = {v.name for v in gb.vars.values() if v.is_data}
        feeds.update(feed_names or ())
        live = block_liveness(gb, sorted(feeds),
                              [getattr(f, "name", f)
                               for f in (fetch_names or ())])

    refusals_before = len(diags)
    chains = find_fusable_chains(program, live, fetch_names, diags)
    n_refused = len(diags) - refusals_before
    if not chains:
        return FusionDecision(False, program, "no fusable chains",
                              n_refused=n_refused)

    # -- rewrite on a clone (fresh _serial: caches never alias) ----------
    p = program.clone()
    new_gb = p.global_block
    # the fused op replaces the LAST chain op, not the first: a residual
    # operand may be produced between the matmul and the add, and placing
    # the fused op at the matmul's slot would read it before its def
    by_last = {c.op_indices[-1]: c for c in chains}
    removed = {oi for c in chains for oi in c.op_indices}
    new_ops = []
    fused_ops = []   # (chain, new Operator)
    for oi, op in enumerate(new_gb.ops):
        if oi not in removed:
            new_ops.append(op)
            continue
        c = by_last.get(oi)
        if c is None:
            continue   # an interior chain member: dropped
        base = new_gb.ops[c.op_indices[0]]
        fop = Operator(new_gb, "fused_gemm_epilogue",
                       inputs={k: [v] for k, v in c.inputs.items()},
                       outputs={"Out": [c.out_name]},
                       attrs=dict(c.attrs))
        fop.attrs["__uid__"] = p._next_uid()
        fop.attrs["__op_role__"] = OpRole.Forward
        if base.attrs.get("op_callstack"):
            fop.attrs["op_callstack"] = base.attrs["op_callstack"]
        new_ops.append(fop)
        fused_ops.append((c, fop))
    new_gb.ops = new_ops
    # sweep vars only the fused-away chain touched: the intermediates
    # (single-consumer by proof) and dead layer_norm side outputs
    still_used = set()
    for op in new_gb.ops:
        still_used.update(n for n in op.input_arg_names if n != EMPTY)
        still_used.update(n for n in op.output_arg_names if n != EMPTY)
    for c in chains:
        inter = []
        for oi in c.op_indices:
            inter.extend(n for n in program.global_block.ops[oi]
                         .output_arg_names if n != EMPTY)
        for name in inter + c.dead_outputs:
            v = new_gb.vars.get(name)
            if (v is not None and name not in still_used
                    and not v.persistable and not v.is_data):
                del new_gb.vars[name]
    p._bump_version()
    for _, fop in fused_ops:
        fop.infer_shape()

    # -- fidelity witness (memoized per chain signature) -----------------
    seen: Dict[tuple, Optional[str]] = {}
    for c, fop in fused_ops:
        sig = _witness_signature(program.global_block, c)
        if sig not in seen:
            seen[sig] = _run_witness(program, p, c, fop, batch=batch,
                                     gemm_blocks=gemm_blocks)
        fail = seen[sig]
        if fail is not None:
            base_idx = c.op_indices[0]
            base = program.global_block.ops[base_idx]
            diags.append(Diagnostic(
                "PT754",
                f"chain at op {base_idx} ({c.epilogue}): {fail}",
                gb.idx, base_idx, base.type, _site(base)))
            return FusionDecision(
                False, program,
                f"fidelity witness failed for chain at op {base_idx}: "
                f"{fail}", n_refused=n_refused + 1)

    from types import SimpleNamespace

    from ..ops.fused_gemm import resolve_gemm_blocks
    from ..kernels.fused_gemm import classify_gemm

    blocks = resolve_gemm_blocks(SimpleNamespace(gemm_blocks=gemm_blocks))
    for c, fop in fused_ops:
        base_idx = c.op_indices[0]
        base = program.global_block.ops[base_idx]
        diags.append(Diagnostic(
            "PT750",
            f"fused {len(c.op_indices)}-op chain ({c.epilogue}) into "
            f"fused_gemm_epilogue writing '{c.out_name}'",
            gb.idx, base_idx, base.type, _site(base)))
        m, n, k = _chain_gemm_dims(gb, c, batch=batch)
        alpha = float(c.attrs.get("alpha", 1.0))
        if alpha != 1.0:
            # mirror the op lowering's route gate: an alpha-scaled matmul
            # never takes the kernel, whatever the tiling says
            kind, reason = ("unsupported",
                            f"alpha={alpha} != 1 runs the dense replay")
        else:
            kind, reason = classify_gemm(
                m, n, k, layer_norm=bool(c.attrs["layer_norm"]),
                block_m=blocks[0], block_n=blocks[1], block_k=blocks[2])
        if kind != "supported":
            diags.append(Diagnostic(
                "PT755",
                f"chain at op {base_idx} (m={m}, n={n}, k={k}): {reason}",
                gb.idx, base_idx, base.type, _site(base)))

    return FusionDecision(
        True, p,
        f"fused {len(fused_ops)} chain(s)",
        n_fused=len(fused_ops), n_refused=n_refused,
        chains=[{"ops": list(c.op_indices), "epilogue": c.epilogue,
                 "out": c.out_name} for c, _ in fused_ops])


def epilogue_fusion_pass(program, ctx) -> FusionDecision:
    """The registered transform entry (builtin_passes): consumes the cached
    liveness analysis; reports PT750–PT755 on the context; the manager
    swaps in ``decision.program`` when applied."""
    live_info = ctx.analysis("liveness")
    diags: List[Diagnostic] = []
    decision = fuse_epilogues(program,
                              feed_names=list(ctx.feed_names),
                              fetch_names=list(ctx.fetch_names),
                              live=live_info["live"], diags=diags,
                              batch=int(ctx.batch_size or 8),
                              gemm_blocks=ctx.options.get("gemm_blocks"))
    for d in diags:
        ctx.report(d)
    return decision
