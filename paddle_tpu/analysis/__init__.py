"""paddle_tpu.analysis — static program verification and registry auditing.

Public surface:

* ``verify_program(program, fetch_names=())`` — run the multi-pass verifier,
  return a list of ``Diagnostic``.
* ``check_program(...)`` — same, but raise ``ProgramVerificationError`` when
  error-severity findings exist (the FLAGS_check_program executor hook).
* ``audit_registry()`` / ``format_audit`` — per-op capability coverage.
* ``liveness`` — dataflow liveness & effect analysis: proven-safe buffer
  donation (``safe_donation_set``), peak-memory planning (``memory_plan``,
  surfaced as ``Program.memory_plan()``), PT5xx diagnostics.
* ``remat`` — Pass 6, automatic rematerialisation: memory_plan-scored
  checkpoint selection + program rebuild (``auto_recompute_program``),
  wired to the executor via ``FLAGS_auto_recompute`` (docs/PERF_NOTES.md).
* ``pass_manager`` — the uniform IR pass framework (ROADMAP item 5):
  ``Pass``/``PassRegistry``/``@register_pass`` with declared dependencies
  and invalidations, ``PassContext`` analysis caching,
  ``PassManager.run_pipeline`` with pre/post verification and per-pass
  monitor timings. All six passes above are registered on it; the three
  new static-analysis families (``static_checks``: PT700s dtype/shape
  consistency, PT710s donation-race, PT720s dead-code + opt-in DCE) run
  through it too.
* ``epilogue_fusion`` — Pass 7, GEMM-epilogue fusion (CODA): mul/matmul →
  bias/activation/residual/layer_norm chains rewritten into the
  ``fused_gemm_epilogue`` op under a per-chain numerical fidelity witness,
  wired to the executor via ``FLAGS_epilogue_fusion``
  (docs/PERF_NOTES.md "Epilogue fusion").
* ``CODES`` — the diagnostic-code table (see docs/ANALYSIS.md).
"""
from .diagnostics import (CODES, Diagnostic, ProgramVerificationError,
                          Severity, format_diagnostics)
from .registry_audit import audit_registry, coverage_summary, format_audit
from .verifier import DEFAULT_PASSES, check_program, verify_program
from . import liveness
from .liveness import (MemoryPlan, block_liveness, classify_op_effects,
                       donation_report, memory_plan, safe_donation_set)
from . import remat
from .remat import (RematCandidate, RematDecision, auto_recompute_program,
                    remat_candidates)
from . import pass_manager
from .pass_manager import (ALL_ANALYSIS_PASSES, VERIFY_PASSES, FunctionPass,
                           Pass, PassContext, PassManager, PassRegistry,
                           PassVerificationError, PipelineResult,
                           clear_analysis_caches, default_pass_manager,
                           get_pass_registry, register_pass,
                           run_transform_pipeline, run_verify_pipeline)
from . import static_checks
from .static_checks import (DceDecision, DeadCodeReport, dce_program)
from . import cost_model
from .cost_model import (CommsReport, CostReport, comms_compute_ratio,
                         estimate_comms, estimate_cost)
from . import sharding_check
from .sharding_check import (CollectiveEvent, ShardingAnalysis,
                             propagate_sharding)
from . import epilogue_fusion
from .epilogue_fusion import (FusedChain, FusionDecision, fuse_epilogues)
from . import numerics
from .numerics import (Interval, NumericsReport, analyze_numerics,
                       check_numerics, static_intervals)

__all__ = [
    "CODES", "Diagnostic", "ProgramVerificationError", "Severity",
    "format_diagnostics", "audit_registry", "coverage_summary",
    "format_audit", "DEFAULT_PASSES", "check_program", "verify_program",
    "liveness", "MemoryPlan", "block_liveness", "classify_op_effects",
    "donation_report", "memory_plan", "safe_donation_set",
    "remat", "RematCandidate", "RematDecision", "auto_recompute_program",
    "remat_candidates",
    "pass_manager", "Pass", "FunctionPass", "PassRegistry", "PassContext",
    "PassManager", "PassVerificationError", "PipelineResult",
    "register_pass", "get_pass_registry", "default_pass_manager",
    "run_verify_pipeline", "run_transform_pipeline", "clear_analysis_caches",
    "ALL_ANALYSIS_PASSES", "VERIFY_PASSES",
    "static_checks", "DceDecision", "DeadCodeReport", "dce_program",
    "cost_model", "CostReport", "estimate_cost", "CommsReport",
    "estimate_comms", "comms_compute_ratio",
    "sharding_check", "CollectiveEvent", "ShardingAnalysis",
    "propagate_sharding",
    "epilogue_fusion", "FusedChain", "FusionDecision", "fuse_epilogues",
    "numerics", "Interval", "NumericsReport", "analyze_numerics",
    "check_numerics", "static_intervals",
]
