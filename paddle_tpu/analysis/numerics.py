"""Numerics static analysis — value-interval and precision-flow
propagation over the Program IR (the PT900 family, docs/ANALYSIS.md).

The int8 serving path (ROADMAP item 4) starts from a question no runtime
test answers: which GEMM/conv sites are *provably* safe to lower to int8,
are the slim QAT annotations (contrib/slim/quantization) well-formed, and
where does the bf16/AMP path silently lose precision? This pass answers it
statically, the way ``dtype_shape_check`` answers the shape question: walk
every op in program order over the recorded ``infer_shape`` metadata,
propagating a conservative **value interval** ``[lo, hi]`` per var
(abs-max / min-max; ``TOP`` = (-inf, inf) wherever no transfer rule
applies — soundness over precision) plus the dtype-precision flow the var
metadata already records.

Transfer rules by op family (the authoring guide is in docs/ANALYSIS.md):

* **contraction growth** — conv2d/depthwise_conv2d/mul/matmul: |out| <=
  |x|max * |y|max * K where K is the contraction width read off the
  recorded shapes (unknown/dynamic K => TOP);
* **domain hazards** — log/sqrt/rsqrt/reciprocal/elementwise_div on an
  interval statically proven to include 0 or negatives emit PT905 (a
  guard — clip, +eps, abs — narrows the interval and clears the finding
  by construction);
* **accumulation** — reduce_*/sum/mean/layer_norm scale bounds by the
  reduction width and emit PT903 when a float16/bfloat16 input
  accumulates into a float16/bfloat16 output with no upcast;
* **range-bounded activations** — relu/sigmoid/tanh/softmax/clip/... give
  the tight bounds the runtime witness (monitor/numwitness.py) cross-checks
  observed values against, tolerance-free: every bound here must be TRUE,
  never heuristic;
* **fake-quant/dequant** — the contrib/slim rewrite contract: PT900 when a
  fake-quant output is consumed off the GEMM path (or never), PT901 when
  moving-average scale state cannot survive training steps.

Whole-program checks on top of the walk: PT902 (cast whose proven interval
exceeds the target dtype's finite range), PT904 (AMP loss-scale coverage:
a grad reaching an optimizer update without passing through
``check_finite_and_unscale`` while scaling is active) and the info-level
PT906 quantizability report — one finding per forward GEMM/conv site,
carrying contraction width, quant-annotation state and static/calibrated
abs-max. PT906 is the exact work-list the int8 epilogue-lowering PR
consumes, and is asserted (tests/test_numerics.py) to be a superset of
``epilogue_fusion``'s fusable chain bases.

Calibration: ``ctx.options["numerics_calibration"] = {var: absmax}`` (the
witness's observed abs-max, fed back by tools/lint_numerics.py --witness)
seeds feed/param intervals. Calibrated intervals are *observed*, not
proven — they are tracked separately (``NumericsReport.calibrated``) and
excluded from the witness containment contract.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..framework import OpRole
from .diagnostics import Diagnostic
from .verifier import EMPTY, _site

__all__ = [
    "Interval", "TOP", "NumericsReport", "check_numerics",
    "analyze_numerics", "static_intervals", "DTYPE_FINITE_MAX",
    "LOW_PRECISION_DTYPES", "QUANT_SITE_TYPES", "FAKE_QUANT_TYPES",
    "QUANT_CONSUMER_TYPES",
]

_INF = math.inf

# finite-range table for PT902 (overflowing cast); names follow the IR's
# string dtypes
DTYPE_FINITE_MAX = {
    "float16": 65504.0,
    "bfloat16": 3.3895313892515355e38,
    "float32": 3.4028234663852886e38,
    "float64": 1.7976931348623157e308,
    "int8": 127.0,
    "uint8": 255.0,
    "int16": 32767.0,
    "int32": 2147483647.0,
    "int64": 9.223372036854775e18,
}

LOW_PRECISION_DTYPES = frozenset({"float16", "bfloat16"})

# the GEMM/conv families the QAT pass annotates and the int8 PR lowers —
# kept in sync with contrib/slim's _DEFAULT_QUANTIZABLE and (for mul/
# matmul) epilogue_fusion._BASE_TYPES, asserted in tests/test_numerics.py
QUANT_SITE_TYPES = ("conv2d", "depthwise_conv2d", "mul", "matmul")

# legal consumers of a fake-quant output under the int8 rewrite contract:
# the GEMM/conv site itself, the fused form of that site, or the site's
# grad replay (training programs read the quantized activation from the
# backward ops)
QUANT_CONSUMER_TYPES = frozenset(QUANT_SITE_TYPES) | {"fused_gemm_epilogue"}

FAKE_QUANT_TYPES = frozenset({
    "fake_quantize_dequantize_abs_max",
    "fake_quantize_dequantize_moving_average_abs_max",
})

# reduce-family ops whose accumulation order/precision PT903 polices
_REDUCE_TYPES = frozenset({
    "reduce_sum", "reduce_mean", "sum", "mean", "layer_norm",
    "softmax", "softmax_with_cross_entropy", "squared_l2_norm",
})


@dataclasses.dataclass(frozen=True)
class Interval:
    """Conservative value bound: every element of the var lies in
    ``[lo, hi]`` (TRUE bound, never heuristic — the runtime witness
    asserts tolerance-free containment against it)."""

    lo: float = -_INF
    hi: float = _INF

    @property
    def is_top(self) -> bool:
        return self.lo == -_INF and self.hi == _INF

    @property
    def known(self) -> bool:
        """At least one side carries derived information."""
        return not self.is_top

    @property
    def absmax(self) -> float:
        return max(abs(self.lo), abs(self.hi))

    def contains_zero(self) -> bool:
        return self.lo <= 0.0 <= self.hi

    def hull(self, other: "Interval") -> "Interval":
        return Interval(min(self.lo, other.lo), max(self.hi, other.hi))

    def scaled(self, f: float) -> "Interval":
        a, b = _mul_bound(self.lo, f), _mul_bound(self.hi, f)
        return Interval(min(a, b), max(a, b))

    def shifted(self, b: float) -> "Interval":
        return Interval(self.lo + b, self.hi + b)

    def to_tuple(self) -> Tuple[float, float]:
        return (self.lo, self.hi)


TOP = Interval()
_UNIT = Interval(0.0, 1.0)          # sigmoid / softmax / dropout-mask
_SYM_UNIT = Interval(-1.0, 1.0)     # tanh / softsign / erf / sin / cos
_NON_NEG = Interval(0.0, _INF)      # losses, variances, abs-max scales


def _sym(m: float) -> Interval:
    return Interval(-abs(m), abs(m))


def _pt(v: float) -> Interval:
    return Interval(float(v), float(v))


def _mul_bound(a: float, b: float) -> float:
    """IEEE-safe product for bound arithmetic: 0 * inf is 0 here (an
    exactly-zero value stays zero no matter the other operand's bound)."""
    if a == 0.0 or b == 0.0:
        return 0.0
    return a * b


# Rounding slack for transfer rules that model runtime FLOAT ARITHMETIC
# (scale, elementwise_*, exp, GEMM, reductions, ...): bounds here are
# computed in float64 while the runtime computes AND STORES float32 — a
# fill_constant(1e-4) materializes as the float32 9.9999997e-05, outside
# the exact python-float interval. Widening each derived bound by 8
# float32 ulps per arithmetic op strictly dominates the <= 0.5 ulp the
# runtime can add per op, so containment holds inductively down any
# chain — and the WITNESS cross-check stays tolerance-free, because the
# slack is part of the proven bound, not of the comparison. Structural
# rules (relu/clip/min/max/concat/fixed activation ranges) stay exact:
# they model no rounding. Accumulations (GEMM/reduce_sum) additionally
# scale slack by the contraction width K — fp32 accumulation error grows
# ~K * 2^-24, which a fixed factor cannot cover.
_REL_SLACK = 2.0 ** -20
_ABS_SLACK = 2.0 ** -126      # smallest fp32 normal: subnormal rounding

# extra relative widening when a cast stores into a narrower float
_CAST_REL = {"float16": 2.0 ** -10, "bfloat16": 2.0 ** -7,
             "float32": 2.0 ** -23}


def _slop(iv: Interval, width: float = 1.0) -> Interval:
    rel = _REL_SLACK + float(width) * 2.0 ** -23
    lo = iv.lo if iv.lo == -_INF else iv.lo - abs(iv.lo) * rel - _ABS_SLACK
    hi = iv.hi if iv.hi == _INF else iv.hi + abs(iv.hi) * rel + _ABS_SLACK
    return Interval(lo, hi)


def _iv_add(a: Interval, b: Interval) -> Interval:
    return Interval(a.lo + b.lo, a.hi + b.hi)


def _iv_sub(a: Interval, b: Interval) -> Interval:
    return Interval(a.lo - b.hi, a.hi - b.lo)


def _iv_mul(a: Interval, b: Interval) -> Interval:
    ps = [_mul_bound(x, y) for x in (a.lo, a.hi) for y in (b.lo, b.hi)]
    return Interval(min(ps), max(ps))


def _safe_exp(v: float) -> float:
    if v == -_INF:
        return 0.0
    try:
        return math.exp(v)
    except OverflowError:
        return _INF


def _abs_iv(a: Interval) -> Interval:
    if a.contains_zero():
        return Interval(0.0, a.absmax)
    return Interval(min(abs(a.lo), abs(a.hi)), a.absmax)


@dataclasses.dataclass
class NumericsReport:
    """Everything the walk derived: the analysis product cached under
    ``ctx.analysis("numerics_check")`` and serialized into the CI
    artifact."""

    diagnostics: List[Diagnostic] = dataclasses.field(default_factory=list)
    intervals: Dict[str, Interval] = dataclasses.field(default_factory=dict)
    quant_sites: List[dict] = dataclasses.field(default_factory=list)
    calibrated: Set[str] = dataclasses.field(default_factory=set)
    is_training: bool = False
    loss_scaling_active: bool = False

    def bounded_intervals(self, proven_only: bool = True
                          ) -> Dict[str, Tuple[float, float]]:
        """Vars with at least one finite bound — the witness containment
        surface. ``proven_only`` drops everything downstream of a
        calibration seed (observed, not proven)."""
        out = {}
        for name, iv in self.intervals.items():
            if not iv.known:
                continue
            if proven_only and name in self.calibrated:
                continue
            out[name] = iv.to_tuple()
        return out

    def to_dict(self) -> dict:
        by_code: Dict[str, int] = {}
        for d in self.diagnostics:
            by_code[d.code] = by_code.get(d.code, 0) + 1
        return {
            "is_training": self.is_training,
            "loss_scaling_active": self.loss_scaling_active,
            "findings_by_code": by_code,
            "bounded_intervals": {
                n: [lo, hi] for n, (lo, hi)
                in sorted(self.bounded_intervals(proven_only=False).items())},
            "calibrated_vars": sorted(self.calibrated),
            "quant_sites": list(self.quant_sites),
        }


def _find_var(block, name: str):
    b = block
    while b is not None:
        v = b.vars.get(name)
        if v is not None:
            return v
        b = b.parent_block
    return None


def _var_dtype(block, name: str) -> str:
    v = _find_var(block, name)
    return str(getattr(v, "dtype", "") or "") if v is not None else ""


def _var_shape(block, name: str):
    v = _find_var(block, name)
    return getattr(v, "shape", None) if v is not None else None


def _static_width(shape, axes=None) -> Optional[int]:
    """Product of the (reduced) dims, None when any is dynamic."""
    if shape is None:
        return None
    dims = list(shape)
    if axes is not None:
        try:
            dims = [dims[a if a >= 0 else a + len(dims)] for a in axes]
        except (IndexError, TypeError):
            return None
    w = 1
    for d in dims:
        d = int(d)
        if d < 0:
            return None
        w *= d
    return w


def _role(op):
    return op.attrs.get("__op_role__", OpRole.Forward)


def _diag(diags, code, msg, block, op_idx, op):
    diags.append(Diagnostic(code, msg, block_idx=block.idx, op_idx=op_idx,
                            op_type=op.type, site=_site(op)))


# ---------------------------------------------------------------------------
# per-op transfer rules
# ---------------------------------------------------------------------------

def _contraction_width(block, op) -> Optional[int]:
    """K of a GEMM/conv site from the recorded shapes (None = dynamic)."""
    t = op.type
    if t in ("conv2d", "depthwise_conv2d"):
        f = op.input("Filter")
        shape = _var_shape(block, f[0]) if f else None
        if shape is None or len(shape) != 4:
            return None
        return _static_width(shape[1:])              # ic * kh * kw
    if t == "mul":
        y = op.input("Y")
        shape = _var_shape(block, y[0]) if y else None
        if shape is None or len(shape) < 2:
            return None
        ncd = int(op.attrs.get("y_num_col_dims", 1))
        return _static_width(shape[:ncd])
    if t == "matmul":
        xn = op.input("X")
        shape = _var_shape(block, xn[0]) if xn else None
        if shape is None or len(shape) < 1:
            return None
        axis = -2 if op.attrs.get("transpose_X", False) else -1
        try:
            k = int(shape[axis])
        except (IndexError, TypeError):
            return None
        return k if k >= 0 else None
    return None


def _transfer(block, op, env: Dict[str, Interval],
              diags: List[Diagnostic], op_idx: int) -> Dict[str, Interval]:
    """Output intervals of one op; hazard diagnostics (PT902/PT903/PT905)
    are emitted as a side effect. Anything not covered maps to TOP."""

    def iv(slot: str, idx: int = 0) -> Interval:
        names = op.input(slot)
        if len(names) <= idx or names[idx] == EMPTY:
            return TOP
        return env.get(names[idx], TOP)

    def one(val: Interval, slot: str = "Out") -> Dict[str, Interval]:
        names = op.output(slot)
        return {names[0]: val} if names else {}

    t = op.type
    a = op.attrs

    # -- constants ---------------------------------------------------------
    if t in ("fill_constant", "fill_constant_batch_size_like"):
        return one(_slop(_pt(float(a.get("value", 0.0)))))
    if t in ("fill_zeros_like", "zeros_like"):
        return one(_pt(0.0))
    if t == "one_hot":
        return one(_UNIT)

    # -- range-bounded activations ----------------------------------------
    if t == "relu":
        v = iv("X")
        return one(Interval(max(0.0, v.lo), max(0.0, v.hi)))
    if t == "relu6":
        v = iv("X")
        thr = float(a.get("threshold", 6.0))
        return one(Interval(min(max(0.0, v.lo), thr),
                            min(max(0.0, v.hi), thr)))
    if t in ("sigmoid", "hard_sigmoid", "softmax", "log_softmax"):
        if t == "log_softmax":
            return one(Interval(-_INF, 0.0))
        return one(_UNIT)
    if t in ("tanh", "softsign", "erf", "sin", "cos", "stanh"):
        return one(_SYM_UNIT)
    if t == "sign":
        return one(_SYM_UNIT)
    if t == "gelu":
        v = iv("X")
        return one(_slop(Interval(0.0 if v.lo >= 0 else -0.2,
                                  max(v.hi, 0.0))))
    if t == "leaky_relu":
        v = iv("X")
        alpha = float(a.get("alpha", 0.02))
        cands = [v.lo, v.hi, _mul_bound(v.lo, alpha), _mul_bound(v.hi, alpha)]
        return one(_slop(Interval(min(min(cands), 0.0),
                                  max(max(cands), 0.0))))
    if t == "clip":
        v = iv("X")
        lo, hi = float(a.get("min", -1.0)), float(a.get("max", 1.0))
        return one(Interval(min(max(v.lo, lo), hi), max(min(v.hi, hi), lo)))
    if t == "abs":
        return one(_abs_iv(iv("X")))
    if t == "square":
        m = _abs_iv(iv("X"))
        return one(_slop(Interval(_mul_bound(m.lo, m.lo),
                                  _mul_bound(m.hi, m.hi))))
    if t == "exp":
        v = iv("X")
        return one(_slop(Interval(_safe_exp(v.lo), _safe_exp(v.hi))))

    # -- domain hazards (PT905) -------------------------------------------
    if t in ("log", "log2", "log10"):
        v = iv("X")
        if v.known and v.lo <= 0.0:
            _diag(diags, "PT905",
                  f"'{t}' on interval [{v.lo:g}, {v.hi:g}] — the operand "
                  f"can be <= 0, producing -inf/nan (guard with clip or "
                  f"+eps to narrow the interval)", block, op_idx, op)
        if v.lo > 0.0:
            return one(_slop(Interval(math.log(v.lo), math.log(v.hi)
                                      if v.hi < _INF else _INF)))
        return one(TOP)
    if t == "sqrt":
        v = iv("X")
        if v.known and v.lo < 0.0:
            _diag(diags, "PT905",
                  f"'sqrt' on interval [{v.lo:g}, {v.hi:g}] — the operand "
                  f"can be negative, producing nan", block, op_idx, op)
        return one(_slop(Interval(
            math.sqrt(max(v.lo, 0.0)) if v.lo > 0 else 0.0,
            math.sqrt(v.hi) if 0 <= v.hi < _INF else _INF)))
    if t == "rsqrt":
        v = iv("X")
        if v.known and v.lo <= 0.0:
            _diag(diags, "PT905",
                  f"'rsqrt' on interval [{v.lo:g}, {v.hi:g}] — the operand "
                  f"can be <= 0, producing inf/nan", block, op_idx, op)
        if v.lo > 0.0:
            return one(_slop(Interval(
                1.0 / math.sqrt(v.hi) if v.hi < _INF else 0.0,
                1.0 / math.sqrt(v.lo))))
        return one(_NON_NEG if v.lo >= 0.0 else TOP)
    if t in ("reciprocal", "elementwise_div"):
        den = iv("Y") if t == "elementwise_div" else iv("X")
        num = iv("X") if t == "elementwise_div" else _pt(1.0)
        if den.known and den.contains_zero():
            _diag(diags, "PT905",
                  f"'{t}' denominator interval [{den.lo:g}, {den.hi:g}] "
                  f"contains 0 — division can produce inf/nan (guard the "
                  f"denominator with clip/abs/+eps)", block, op_idx, op)
        if den.lo > 0.0 or den.hi < 0.0:
            inv = Interval(min(1.0 / den.lo, 1.0 / den.hi),
                           max(1.0 / den.lo, 1.0 / den.hi)) \
                if den.absmax < _INF and den.lo != 0 and den.hi != 0 \
                else TOP
            if t == "reciprocal":
                return one(_slop(inv))
            return one(_slop(_iv_mul(num, inv)))
        return one(TOP)

    # -- linear / elementwise ---------------------------------------------
    if t == "scale":
        v = iv("X")
        s, b = float(a.get("scale", 1.0)), float(a.get("bias", 0.0))
        if a.get("bias_after_scale", True):
            return one(_slop(v.scaled(s).shifted(b)))
        return one(_slop(v.shifted(b).scaled(s)))
    if t == "elementwise_add":
        return one(_slop(_iv_add(iv("X"), iv("Y"))))
    if t == "elementwise_sub":
        return one(_slop(_iv_sub(iv("X"), iv("Y"))))
    if t == "elementwise_mul":
        return one(_slop(_iv_mul(iv("X"), iv("Y"))))
    if t == "elementwise_max":
        vx, vy = iv("X"), iv("Y")
        return one(Interval(max(vx.lo, vy.lo), max(vx.hi, vy.hi)))
    if t == "elementwise_min":
        vx, vy = iv("X"), iv("Y")
        return one(Interval(min(vx.lo, vy.lo), min(vx.hi, vy.hi)))
    if t == "sum":
        _check_low_precision_accum(block, op, diags, op_idx, width=None)
        acc = _pt(0.0)
        for n in op.input("X"):
            acc = _iv_add(acc, env.get(n, TOP))
        return one(_slop(acc, width=len(op.input("X"))))

    # -- reductions (PT903) ------------------------------------------------
    if t in ("mean", "reduce_mean", "reduce_max", "reduce_min", "pool2d"):
        slot = "X"
        width = _static_width(_var_shape(block, op.input(slot)[0])) \
            if op.input(slot) else None
        if t in ("mean", "reduce_mean"):
            _check_low_precision_accum(block, op, diags, op_idx, width)
        # a mean/avg-pool stays inside its input's hull in the reals, but
        # accumulates in float — width-scaled slack; max/min-pool is exact
        return one(_slop(iv(slot), width=width or 1))
    if t == "reduce_sum":
        names = op.input("X")
        shape = _var_shape(block, names[0]) if names else None
        axes = None if a.get("reduce_all") else a.get("dim", [0])
        width = _static_width(shape, axes)
        _check_low_precision_accum(block, op, diags, op_idx, width)
        v = iv("X")
        if width is None:
            if v.lo == 0.0 and v.hi == 0.0:
                return one(_pt(0.0))
            return one(TOP)
        return one(_slop(Interval(_mul_bound(min(v.lo, 0.0), width),
                                  _mul_bound(max(v.hi, 0.0), width)),
                         width=width))
    if t == "squared_l2_norm":
        _check_low_precision_accum(block, op, diags, op_idx, None)
        return one(_NON_NEG)
    if t == "layer_norm":
        width = _static_width(_var_shape(block, op.input("X")[0])) \
            if op.input("X") else None
        _check_low_precision_accum(block, op, diags, op_idx, width,
                                   out_slot="Y")
        res = one(TOP, "Y")
        if op.output("Mean"):
            res[op.output("Mean")[0]] = iv("X")
        if op.output("Variance"):
            res[op.output("Variance")[0]] = _NON_NEG
        return res

    # -- casts (PT902) -----------------------------------------------------
    if t == "cast":
        v = iv("X")
        dst = str(a.get("out_dtype", "float32"))
        fmax = DTYPE_FINITE_MAX.get(dst)
        if fmax is not None and v.known and v.absmax > fmax:
            _diag(diags, "PT902",
                  f"cast to {dst}: statically-proven interval "
                  f"[{v.lo:g}, {v.hi:g}] exceeds the dtype's finite range "
                  f"(±{fmax:g}) — overflow to inf (float) or wraparound "
                  f"(int)", block, op_idx, op)
            return one(TOP)
        if dst.startswith("int") or dst.startswith("uint"):
            return one(Interval(math.floor(v.lo) if v.lo > -_INF else -_INF,
                                math.ceil(v.hi) if v.hi < _INF else _INF))
        # storing into a narrower float rounds: widen by the target's ulp
        rel = _CAST_REL.get(dst, 0.0)
        if rel and v.known:
            v = Interval(v.lo - abs(v.lo) * rel - _ABS_SLACK,
                         v.hi + abs(v.hi) * rel + _ABS_SLACK)
        return one(v)

    # -- GEMM / conv magnitude growth -------------------------------------
    if t in QUANT_SITE_TYPES:
        slots = ("Input", "Filter") if t.endswith("conv2d") else ("X", "Y")
        va, vb = iv(slots[0]), iv(slots[1])
        k = _contraction_width(block, op)
        if k is not None and va.absmax < _INF and vb.absmax < _INF:
            m = _mul_bound(_mul_bound(va.absmax, vb.absmax), float(k))
            return {n: _slop(_sym(m), width=k) for n in op.output("Out") or
                    op.output("Output")}
        return {}

    # -- losses / metrics --------------------------------------------------
    if t == "softmax_with_cross_entropy":
        res = {}
        if op.output("Softmax"):
            res[op.output("Softmax")[0]] = _UNIT
        if op.output("Loss"):
            res[op.output("Loss")[0]] = _NON_NEG
        return res
    if t == "cross_entropy":
        return one(_NON_NEG, "Y") if op.output("Y") else one(_NON_NEG)
    if t == "accuracy":
        res = {}
        for slot in ("Accuracy", "Correct", "Total"):
            if op.output(slot):
                res[op.output(slot)[0]] = _NON_NEG if slot != "Accuracy" \
                    else _UNIT
        return res
    if t == "square_error_cost":
        return one(_NON_NEG)

    # -- quantization ------------------------------------------------------
    if t == "fake_quantize_dequantize_abs_max":
        v = iv("X")
        res = {}
        if op.output("Out"):
            res[op.output("Out")[0]] = _slop(_sym(v.absmax)) \
                if v.absmax < _INF else TOP
        if op.output("OutScale"):
            res[op.output("OutScale")[0]] = _slop(Interval(
                0.0, v.absmax)) if v.absmax < _INF else _NON_NEG
        return res
    if t == "fake_quantize_dequantize_moving_average_abs_max":
        res = {}
        if op.output("Out"):
            res[op.output("Out")[0]] = TOP   # bounded by runtime state
        if op.output("OutScale"):
            res[op.output("OutScale")[0]] = _NON_NEG
        return res

    # -- structure-preserving ops -----------------------------------------
    if t in ("reshape", "reshape2", "squeeze", "squeeze2", "unsqueeze",
             "unsqueeze2", "flatten", "flatten2", "transpose", "transpose2",
             "assign", "share_data", "cast_identity", "pad", "pad2d"):
        v = iv("X")
        if t.startswith("pad"):
            v = v.hull(_pt(float(a.get("pad_value", 0.0))))
        res = one(v)
        # XShape echoes stay TOP (never materialized)
        return res
    if t == "concat":
        acc = None
        for n in op.input("X"):
            cur = env.get(n, TOP)
            acc = cur if acc is None else acc.hull(cur)
        return one(acc if acc is not None else TOP)
    if t == "split":
        v = iv("X")
        return {n: v for n in op.output("Out")}
    if t == "dropout":
        v = iv("X")
        p = float(a.get("dropout_prob", 0.5))
        f = 1.0 / (1.0 - p) if p < 1.0 else 1.0
        scaled = _slop(v.scaled(f).hull(v).hull(_pt(0.0)))
        res = one(scaled)
        if op.output("Mask"):
            res[op.output("Mask")[0]] = Interval(0.0, max(f, 1.0))
        return res
    if t in ("lookup_table", "lookup_table_v2", "embedding", "gather"):
        w = iv("W") if op.input("W") else iv("X")
        return one(w)

    return {}


def _check_low_precision_accum(block, op, diags, op_idx,
                               width: Optional[int],
                               out_slot: str = "Out") -> None:
    """PT903: a reduce-family op whose input AND output are float16/bf16 —
    the accumulation happens in the storage precision with no upcast."""
    in_names = [n for ns in op.inputs.values() for n in ns if n != EMPTY]
    out_names = op.output(out_slot) or op.output_arg_names
    if not in_names or not out_names:
        return
    in_dt = _var_dtype(block, in_names[0])
    out_dt = _var_dtype(block, out_names[0])
    if in_dt in LOW_PRECISION_DTYPES and out_dt in LOW_PRECISION_DTYPES:
        w = f"width {width}" if width else "dynamic width"
        _diag(diags, "PT903",
              f"'{op.type}' accumulates a {in_dt} input into a {out_dt} "
              f"output ({w}) with no upcast — each partial sum rounds to "
              f"{out_dt}; cast to float32 around the reduction",
              block, op_idx, op)


# ---------------------------------------------------------------------------
# whole-program checks
# ---------------------------------------------------------------------------

def _consumers(block) -> Dict[str, List[Tuple[int, object]]]:
    by_name: Dict[str, List[Tuple[int, object]]] = {}
    for i, op in enumerate(block.ops):
        for n in op.input_arg_names:
            if n != EMPTY:
                by_name.setdefault(n, []).append((i, op))
    return by_name


def _check_quant_contract(block, consumers, fetch_names, is_training,
                          diags) -> None:
    """PT900 (pairing) + PT901 (moving-average scale state)."""
    fetched = set(fetch_names)
    for i, op in enumerate(block.ops):
        if op.type not in FAKE_QUANT_TYPES:
            continue
        out_names = op.output("Out")
        if not out_names:
            continue
        q = out_names[0]
        readers = [(j, c) for j, c in consumers.get(q, ()) if c is not op]
        if not readers and q not in fetched:
            _diag(diags, "PT900",
                  f"fake-quant output '{q}' is never consumed and not "
                  f"fetched — the quantized value (and its scale) is dead",
                  block, i, op)
        for _j, c in readers:
            if c.type in QUANT_CONSUMER_TYPES or c.type.endswith("_grad") \
                    or c.type in FAKE_QUANT_TYPES:
                continue
            _diag(diags, "PT900",
                  f"fake-quant output '{q}' is consumed by '{c.type}' — "
                  f"the int8 rewrite contract only holds for GEMM/conv "
                  f"consumers ({', '.join(sorted(QUANT_CONSUMER_TYPES))}); "
                  f"an off-path consumer would read dequantized values the "
                  f"int8 lowering cannot reproduce", block, i, op)
        if op.type == "fake_quantize_dequantize_moving_average_abs_max" \
                and is_training:
            scales = op.output("OutScale")
            in_scales = op.input("InScale")
            if scales:
                s = scales[0]
                v = _find_var(block, s)
                if v is not None and not getattr(v, "persistable", False):
                    _diag(diags, "PT901",
                          f"moving-average scale '{s}' is not persistable "
                          f"in a training program — the running scale "
                          f"resets every step and the QAT calibration "
                          f"never converges", block, i, op)
                if in_scales and in_scales[0] != EMPTY \
                        and in_scales[0] != s:
                    _diag(diags, "PT901",
                          f"moving-average scale state is not updated in "
                          f"place: InScale '{in_scales[0]}' != OutScale "
                          f"'{s}' — the updated scale is never read back, "
                          f"so the moving average never advances",
                          block, i, op)


def _check_amp_coverage(block, diags) -> bool:
    """PT904: loss scaling active but a grad skips unscale. Returns
    whether scaling is active (for the report)."""
    unscaled: Set[str] = set()
    for op in block.ops:
        if op.type == "check_finite_and_unscale":
            unscaled.update(n for n in op.input("X") if n != EMPTY)
            unscaled.update(n for n in op.output("Out") if n != EMPTY)
    if not unscaled:
        return False
    for i, op in enumerate(block.ops):
        if _role(op) != OpRole.Optimize:
            continue
        for g in op.input("Grad"):
            if g != EMPTY and g not in unscaled:
                _diag(diags, "PT904",
                      f"gradient '{g}' reaches '{op.type}' without "
                      f"passing through check_finite_and_unscale while "
                      f"loss scaling is active — the update applies a "
                      f"scaled gradient (wrong by the loss-scale factor)",
                      block, i, op)
    return True


def _quant_report(block, env, calibration, diags,
                  sites: List[dict]) -> None:
    """PT906: one info finding + work-list entry per forward GEMM/conv
    site (the int8 PR's input)."""
    produced_by: Dict[str, object] = {}
    for op in block.ops:
        for n in op.output_arg_names:
            if n != EMPTY:
                produced_by[n] = op
    for i, op in enumerate(block.ops):
        if op.type not in QUANT_SITE_TYPES or _role(op) != OpRole.Forward:
            continue
        slots = ("Input", "Filter") if op.type.endswith("conv2d") \
            else ("X", "Y")
        in_names = [op.input(s)[0] for s in slots if op.input(s)]
        quant_annotated = bool(in_names) and all(
            getattr(produced_by.get(n), "type", "") in FAKE_QUANT_TYPES
            for n in in_names)
        out_names = op.output("Out") or op.output("Output")
        out_name = out_names[0] if out_names else ""
        k = _contraction_width(block, op)
        static_absmax = None
        iv = env.get(out_name, TOP)
        if iv.absmax < _INF:
            static_absmax = iv.absmax
        calib = {n: calibration[n] for n in in_names + [out_name]
                 if n in calibration}
        sites.append({
            "block": block.idx, "op_idx": i, "op_type": op.type,
            "out": out_name, "inputs": dict(zip(slots, in_names)),
            "contraction_width": k, "quant_annotated": quant_annotated,
            "static_absmax": static_absmax,
            "calibrated_absmax": calib or None,
        })
        _diag(diags, "PT906",
              f"quantizable {op.type} site -> '{out_name}' "
              f"(K={k if k is not None else '?'}, "
              f"quant-annotated={'yes' if quant_annotated else 'no'}"
              + (f", observed |x|max={max(calib.values()):g}" if calib
                 else "") + ") — int8 epilogue lowering candidate",
              block, i, op)


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def analyze_numerics(program, fetch_names: Sequence[str] = (),
                     calibration: Optional[Dict[str, float]] = None
                     ) -> NumericsReport:
    """The full walk, free of any PassContext (the witness cross-check and
    the tests call this directly; the registered pass wraps it)."""
    calibration = dict(calibration or {})
    rep = NumericsReport()
    rep.is_training = any(
        _role(op) in (OpRole.Backward, OpRole.Optimize)
        for blk in program.blocks for op in blk.ops)
    env: Dict[str, Interval] = rep.intervals

    # calibration seeds (observed abs-max — tracked, never "proven")
    for name, v in calibration.items():
        if isinstance(v, (tuple, list)) and len(v) == 2:
            env[name] = Interval(float(v[0]), float(v[1]))
        else:
            env[name] = _sym(float(v))
        rep.calibrated.add(name)

    for blk in program.blocks:
        consumers = _consumers(blk)
        for i, op in enumerate(blk.ops):
            try:
                outs = _transfer(blk, op, env, rep.diagnostics, i)
            except Exception:
                outs = {}
            for n in op.output_arg_names:
                if n == EMPTY:
                    continue
                new = outs.get(n, TOP)
                # taint: any output derived from a calibrated input is
                # itself calibrated (observed, not proven)
                if new.known and any(
                        m in rep.calibrated for m in op.input_arg_names
                        if m != EMPTY):
                    rep.calibrated.add(n)
                env[n] = new
        _check_quant_contract(blk, consumers, fetch_names,
                              rep.is_training, rep.diagnostics)
        if _check_amp_coverage(blk, rep.diagnostics):
            rep.loss_scaling_active = True
        _quant_report(blk, env, calibration, rep.diagnostics,
                      rep.quant_sites)
    return rep


def check_numerics(program, ctx) -> NumericsReport:
    """The registered ``numerics_check`` analysis pass: reports the PT900
    family on the context and caches the :class:`NumericsReport`.
    Options: ``numerics_calibration`` — {var: observed absmax} (or
    ``(min, max)``), fed back from the runtime witness."""
    rep = analyze_numerics(
        program, fetch_names=ctx.fetch_names,
        calibration=ctx.options.get("numerics_calibration"))
    for d in rep.diagnostics:
        ctx.report(d)
    return rep


def static_intervals(program, fetch_names: Sequence[str] = ()
                     ) -> Dict[str, Tuple[float, float]]:
    """Proven (calibration-free) bounded intervals by var name — the
    witness containment contract surface (tools/lint_numerics.py
    --witness asserts every observed value lies inside, tolerance-free)."""
    return analyze_numerics(program,
                            fetch_names=fetch_names).bounded_intervals()
