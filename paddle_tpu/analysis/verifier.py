"""Multi-pass static program verifier (the build-time role of the reference's
op_registry.h schema checks + InferShape enforcement, run as an IR pass the
way TVM gates its lowering pipeline with verification passes).

Passes over a ``Program``:

1. **schema**       — every op's slots and attrs checked against its OpDef
                      (PT10x / PT107).
2. **dataflow**     — def-before-use per block with parent-block recursion,
                      dead writes, dangling outputs, uninitialized reads
                      (PT20x).
3. **lowerability** — ops that cannot lower: no lower rule, grad ops of
                      non-differentiable forwards, RNG ops under the
                      deterministic flag (PT30x).
4. **shape_replay** — re-runs infer_shape/auto_infer_shape over each block
                      and flags drift against the recorded var metadata
                      (PT40x). Catches post-append mutations that skipped
                      ``Operator.set_attr``.
5. **liveness**     — dataflow liveness + effect classification (see
                      ``analysis/liveness.py``): donation-unsafe fetches,
                      write-after-fetch hazards, dead ops/vars, persistables
                      rebound inside sub-blocks (PT50x).

Only error-severity findings gate execution (see ``check_program``); warnings
and infos are surfaced by ``tools/lint_program.py`` and the test suite.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

from ..core import registry
from .diagnostics import (Diagnostic, ProgramVerificationError, Severity,
                          format_diagnostics)

__all__ = ["verify_program", "check_program", "DEFAULT_PASSES"]

DEFAULT_PASSES = ("schema", "dataflow", "lowerability", "shape_replay",
                  "liveness")

EMPTY = "@EMPTY@"  # lowering.EMPTY_VAR_NAME (no import: keep analysis light)

# attrs stamped by the framework itself, never part of an op schema
_FRAMEWORK_ATTRS = frozenset({"op_callstack", "op_namescope", "op_device"})


def _is_internal_attr(name: str) -> bool:
    return name.startswith("__") or name in _FRAMEWORK_ATTRS


def _site(op) -> str:
    return op.attrs.get("op_callstack", "") or ""


def _is_auto_grad(op) -> bool:
    return (op.type.endswith("_grad") and not registry.has_op(op.type)
            and registry.has_op(op.attrs.get("__fwd_type__", op.type[:-5])))


def _fwd_type(op) -> str:
    return op.attrs.get("__fwd_type__", op.type[:-5])


# ---------------------------------------------------------------------------
# pass 1: schema conformance
# ---------------------------------------------------------------------------

def _check_schema(program, diags: List[Diagnostic]) -> None:
    for blk in program.blocks:
        for oi, op in enumerate(blk.ops):
            if op.type in ("feed", "fetch"):
                continue
            if not registry.has_op(op.type):
                if op.type.endswith("_grad"):
                    _check_grad_op_schema(blk, oi, op, diags)
                else:
                    diags.append(Diagnostic(
                        "PT100", f"op '{op.type}' is not registered",
                        blk.idx, oi, op.type, _site(op)))
                continue
            opdef = registry.get_op_def(op.type)
            declared_in = {s.name: s for s in opdef.inputs}
            declared_out = {s.name: s for s in opdef.outputs}
            for sname, spec in declared_in.items():
                names = [n for n in op.inputs.get(sname, ()) if n != EMPTY]
                if not spec.optional and not names:
                    diags.append(Diagnostic(
                        "PT101",
                        f"op '{op.type}': required input slot '{sname}' "
                        f"absent or empty", blk.idx, oi, op.type, _site(op)))
                if not spec.duplicable and len(names) > 1:
                    diags.append(Diagnostic(
                        "PT107",
                        f"op '{op.type}': input slot '{sname}' is not "
                        f"duplicable but holds {len(names)} vars",
                        blk.idx, oi, op.type, _site(op)))
            for sname in op.inputs:
                if sname not in declared_in:
                    diags.append(Diagnostic(
                        "PT102",
                        f"op '{op.type}': input slot '{sname}' is not in "
                        f"the schema (declares {sorted(declared_in)})",
                        blk.idx, oi, op.type, _site(op)))
            for sname, spec in declared_out.items():
                names = [n for n in op.outputs.get(sname, ()) if n != EMPTY]
                if not spec.optional and not names:
                    diags.append(Diagnostic(
                        "PT103",
                        f"op '{op.type}': required output slot '{sname}' "
                        f"absent or empty", blk.idx, oi, op.type, _site(op)))
                if not spec.duplicable and len(names) > 1:
                    diags.append(Diagnostic(
                        "PT107",
                        f"op '{op.type}': output slot '{sname}' is not "
                        f"duplicable but holds {len(names)} vars",
                        blk.idx, oi, op.type, _site(op)))
            for sname in op.outputs:
                if sname not in declared_out:
                    diags.append(Diagnostic(
                        "PT104",
                        f"op '{op.type}': output slot '{sname}' is not in "
                        f"the schema (declares {sorted(declared_out)})",
                        blk.idx, oi, op.type, _site(op)))
            for aname, aspec in opdef.attrs.items():
                if aspec.required and aname not in op.attrs:
                    diags.append(Diagnostic(
                        "PT105",
                        f"op '{op.type}': required attr '{aname}' missing",
                        blk.idx, oi, op.type, _site(op)))
            for aname in op.attrs:
                if aname not in opdef.attrs and not _is_internal_attr(aname):
                    diags.append(Diagnostic(
                        "PT106",
                        f"op '{op.type}': attr '{aname}' is not in the "
                        f"schema", blk.idx, oi, op.type, _site(op)))


def _check_grad_op_schema(blk, oi, op, diags: List[Diagnostic]) -> None:
    """Auto '<fwd>_grad' ops (backward.py _make_grad_op layout): inputs are
    forward slots, '__out__<slot>' echoes and '<slot>@GRAD' cotangents;
    outputs are '<slot>@GRAD'. Anything else is a malformed grad desc."""
    fwd = _fwd_type(op)
    if not registry.has_op(fwd):
        diags.append(Diagnostic(
            "PT100",
            f"grad op '{op.type}': forward op '{fwd}' is not registered",
            blk.idx, oi, op.type, _site(op)))
        return
    fwd_def = registry.get_op_def(fwd)
    fwd_in = {s.name for s in fwd_def.inputs}
    fwd_out = {s.name for s in fwd_def.outputs}
    for sname in op.inputs:
        base = sname[:-5] if sname.endswith("@GRAD") else None
        ok = (sname in fwd_in
              or (sname.startswith("__out__") and sname[7:] in fwd_out)
              or (base is not None and base in fwd_out))
        if not ok:
            diags.append(Diagnostic(
                "PT102",
                f"grad op '{op.type}': input slot '{sname}' matches no "
                f"forward slot of '{fwd}'", blk.idx, oi, op.type, _site(op)))
    for sname in op.outputs:
        if not (sname.endswith("@GRAD") and sname[:-5] in fwd_in):
            diags.append(Diagnostic(
                "PT104",
                f"grad op '{op.type}': output slot '{sname}' is not the "
                f"@GRAD of a forward input of '{fwd}'",
                blk.idx, oi, op.type, _site(op)))


# ---------------------------------------------------------------------------
# pass 2: dataflow
# ---------------------------------------------------------------------------

def _raw_attr_var_names(op) -> Set[str]:
    """Raw (sub-block) ops name vars through attrs (step_input_names etc.);
    count those as reads so they don't show up dead."""
    names: Set[str] = set()
    for v in op.attrs.values():
        if isinstance(v, str):
            names.add(v)
        elif isinstance(v, (list, tuple)):
            names.update(n for n in v if isinstance(n, str))
    return names


def _block_reads(program, bidx: int, memo: Dict[int, Set[str]]) -> Set[str]:
    """All var names read by block ``bidx``'s ops, including nested
    sub-blocks (parent-block recursion for the raw control-flow ops)."""
    if bidx in memo:
        return memo[bidx]
    memo[bidx] = set()  # cycle guard
    reads: Set[str] = set()
    blk = program.blocks[bidx]
    for op in blk.ops:
        reads.update(n for n in op.input_arg_names if n != EMPTY)
        sub = op.attrs.get("sub_block")
        if isinstance(sub, int) and 0 <= sub < len(program.blocks):
            reads.update(_block_reads(program, sub, memo))
            reads.update(_raw_attr_var_names(op))
    memo[bidx] = reads
    return reads


def _persistable_names(program) -> Set[str]:
    return {v.name for blk in program.blocks for v in blk.vars.values()
            if v.persistable}


def _check_dataflow(program, diags: List[Diagnostic],
                    fetch_names: Sequence[str]) -> None:
    read_memo: Dict[int, Set[str]] = {}
    persistable = _persistable_names(program)
    produced_by_block: Dict[int, Set[str]] = {}
    for blk in program.blocks:
        produced_by_block[blk.idx] = {
            n for op in blk.ops for n in op.output_arg_names if n != EMPTY}

    global_reads: Set[str] = set()
    for blk in program.blocks:
        global_reads.update(_block_reads(program, blk.idx, read_memo))

    for blk in program.blocks:
        # names available before the block runs: feeds, persistables, and —
        # for sub-blocks — everything the ancestor context can supply (the
        # raw op seeds the env; ordering across blocks is runtime's job)
        avail: Set[str] = set(persistable)
        avail.update(v.name for v in blk.vars.values() if v.is_data)
        anc = blk.parent_block
        block_local_produced = produced_by_block[blk.idx]
        while anc is not None:
            avail.update(anc.vars.keys())
            avail.update(produced_by_block[anc.idx])
            anc = anc.parent_block
        if blk.parent_idx >= 0:
            # sub-block vars never produced locally are seeded by the owning
            # raw op's lowering (while/recurrent step slices)
            avail.update(n for n in blk.vars
                         if n not in block_local_produced)

        first_producer: Dict[str, int] = {}
        for oi, op in enumerate(blk.ops):
            for n in op.output_arg_names:
                if n != EMPTY:
                    first_producer.setdefault(n, oi)

        produced: Set[str] = set()
        last_write: Dict[str, int] = {}
        read_since_write: Set[str] = set()
        for oi, op in enumerate(blk.ops):
            if op.type in ("feed", "fetch"):
                continue
            op_reads = {n for n in op.input_arg_names if n != EMPTY}
            sub = op.attrs.get("sub_block")
            if isinstance(sub, int) and 0 <= sub < len(program.blocks):
                op_reads.update(_block_reads(program, sub, read_memo))
                op_reads.update(_raw_attr_var_names(op))
            for n in op_reads:
                read_since_write.add(n)
                if n in produced or n in avail:
                    continue
                if n in first_producer and first_producer[n] > oi:
                    diags.append(Diagnostic(
                        "PT200",
                        f"op '{op.type}' reads '{n}' which is only produced "
                        f"later (op {first_producer[n]}) in block {blk.idx}",
                        blk.idx, oi, op.type, _site(op)))
                else:
                    diags.append(Diagnostic(
                        "PT201",
                        f"op '{op.type}' reads '{n}' which no op produces "
                        f"and no feed/persistable supplies (runtime will "
                        f"require it pre-set in the scope)",
                        blk.idx, oi, op.type, _site(op)))
                # report each name once per block
                avail.add(n)
            for n in op.output_arg_names:
                if n == EMPTY:
                    continue
                if (n in last_write and n not in read_since_write
                        and n not in persistable):
                    diags.append(Diagnostic(
                        "PT202",
                        f"op '{op.type}' overwrites '{n}' whose previous "
                        f"write (op {last_write[n]}) was never read",
                        blk.idx, oi, op.type, _site(op)))
                last_write[n] = oi
                read_since_write.discard(n)
                produced.add(n)

        # dangling outputs: produced here, read nowhere, not fetched
        for oi, op in enumerate(blk.ops):
            if op.type in ("feed", "fetch"):
                continue
            for n in op.output_arg_names:
                if (n != EMPTY and n not in global_reads
                        and n not in fetch_names and n not in persistable):
                    diags.append(Diagnostic(
                        "PT203",
                        f"op '{op.type}' output '{n}' is never read, not "
                        f"fetched and not persistable",
                        blk.idx, oi, op.type, _site(op)))


# ---------------------------------------------------------------------------
# pass 3: lowerability
# ---------------------------------------------------------------------------

def _check_lowerability(program, diags: List[Diagnostic]) -> None:
    from ..flags import flag

    deterministic = bool(flag("cudnn_deterministic"))
    for blk in program.blocks:
        for oi, op in enumerate(blk.ops):
            if op.type in ("feed", "fetch"):
                continue
            if _is_auto_grad(op):
                fwd_def = registry.get_op_def(_fwd_type(op))
                if fwd_def.grad is None and fwd_def.grad_lower is None:
                    diags.append(Diagnostic(
                        "PT301",
                        f"grad op '{op.type}': forward '{fwd_def.type}' "
                        f"declares grad=None (non-differentiable); the "
                        f"generic vjp lowering may be meaningless",
                        blk.idx, oi, op.type, _site(op)))
                continue
            if not registry.has_op(op.type):
                continue  # PT100 already reported by the schema pass
            opdef = registry.get_op_def(op.type)
            if opdef.lower is None:
                diags.append(Diagnostic(
                    "PT300",
                    f"op '{op.type}' has no lower rule — it cannot execute",
                    blk.idx, oi, op.type, _site(op)))
            if opdef.needs_rng and deterministic:
                diags.append(Diagnostic(
                    "PT302",
                    f"op '{op.type}' draws randomness but "
                    f"FLAGS_cudnn_deterministic is set",
                    blk.idx, oi, op.type, _site(op)))


# ---------------------------------------------------------------------------
# pass 4: shape/dtype replay
# ---------------------------------------------------------------------------

def _check_shape_replay(program, diags: List[Diagnostic]) -> None:
    """Re-run each registered op's infer_shape in block order and compare
    against the recorded var metadata, then restore the snapshot. Drift
    means the program was mutated after append without re-inference (e.g.
    direct ``op.attrs[...] =`` writes)."""
    snapshot = {}
    for blk in program.blocks:
        for v in blk.vars.values():
            snapshot[(blk.idx, v.name)] = (v.shape, v.dtype)
    try:
        for blk in program.blocks:
            for oi, op in enumerate(blk.ops):
                if op.type in ("feed", "fetch") or not registry.has_op(
                        op.type):
                    continue
                before = {}
                for n in op.output_arg_names:
                    if n != EMPTY and blk.has_var(n):
                        v = blk.var(n)
                        before[n] = (v.shape, v.dtype)
                try:
                    op.infer_shape()
                except Exception:
                    continue  # dynamic/unsupported at build time
                for n, (old_shape, old_dtype) in before.items():
                    v = blk.var(n)
                    if (old_shape is not None and v.shape is not None
                            and tuple(old_shape) != tuple(v.shape)):
                        diags.append(Diagnostic(
                            "PT400",
                            f"op '{op.type}' output '{n}': recorded shape "
                            f"{tuple(old_shape)} but infer_shape replays "
                            f"{tuple(v.shape)}",
                            blk.idx, oi, op.type, _site(op)))
                    if old_dtype is not None and old_dtype != v.dtype:
                        diags.append(Diagnostic(
                            "PT401",
                            f"op '{op.type}' output '{n}': recorded dtype "
                            f"{old_dtype} but infer_shape replays {v.dtype}",
                            blk.idx, oi, op.type, _site(op)))
    finally:
        for blk in program.blocks:
            for v in blk.vars.values():
                old = snapshot.get((blk.idx, v.name))
                if old is not None:
                    v.shape, v.dtype = old


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def _check_liveness_pass(program, diags: List[Diagnostic],
                         fetch_names: Sequence[str]) -> None:
    # lazy import: liveness.py imports helpers from this module
    from .liveness import check_liveness

    check_liveness(program, diags, fetch_names)


_PASS_FNS = {
    "schema": lambda p, d, f: _check_schema(p, d),
    "dataflow": _check_dataflow,
    "lowerability": lambda p, d, f: _check_lowerability(p, d),
    "shape_replay": lambda p, d, f: _check_shape_replay(p, d),
    "liveness": _check_liveness_pass,
}


def verify_program(program, fetch_names: Sequence[str] = (),
                   passes: Sequence[str] = DEFAULT_PASSES
                   ) -> List[Diagnostic]:
    """Run the static verifier; returns all findings (never raises).

    ``fetch_names`` suppresses PT203 for vars the caller will fetch.

    Since the pass-manager refactor this routes through
    ``PassManager.run_pipeline`` over the default ``PassRegistry`` — any
    registered analysis pass name (including the PT700s/710s/720s families
    and custom ``@register_pass`` passes) is accepted, each run lands
    ``pass_runs_total``/``pass_duration_seconds`` on the monitor registry,
    and passes sharing a dependency (liveness) compute it once. Raises
    ``KeyError`` on an unknown pass name.
    """
    from .pass_manager import default_pass_manager

    result = default_pass_manager().run_pipeline(
        program, passes, fetch_names=fetch_names, verify="none")
    return list(result.diagnostics)


def check_program(program, fetch_names: Sequence[str] = (),
                  passes: Sequence[str] = DEFAULT_PASSES) -> List[Diagnostic]:
    """verify_program + raise ProgramVerificationError on error findings.

    The executor's FLAGS_check_program pre-run hook calls this once per
    program version; warnings and infos pass through silently (inspect the
    return value or run tools/lint_program.py to see them).
    """
    diags = verify_program(program, fetch_names, passes)
    if any(d.severity == Severity.ERROR for d in diags):
        raise ProgramVerificationError(diags)
    return diags
