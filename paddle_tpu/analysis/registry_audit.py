"""Op-registry conformance/coverage audit (pass 5 of the analysis
subsystem): dumps, per registered op, which capabilities it implements —
explicit infer_shape, lower rule, grad story, rng/raw flags — and whether
any test file mentions it. Registry gaps become a visible table instead of
latent runtime surprises (the role op_function_generator + the op-bench
coverage dashboards play in the reference CI).
"""
from __future__ import annotations

import os
import re
from typing import Dict, List, Optional

from ..core import registry

__all__ = ["audit_registry", "format_audit", "coverage_summary"]


def _grad_mode(opdef) -> str:
    if opdef.grad_lower is not None:
        return "custom-lower"
    if opdef.grad is None:
        return "none"
    if callable(opdef.grad):
        return "custom-maker"
    return "auto-vjp"


def _tested_ops(test_dir: str) -> Dict[str, bool]:
    """One scan of tests/*.py; an op counts as tested if its name appears as
    a word anywhere (direct append_op use or through its layer wrapper of
    the same name)."""
    blob = []
    for fn in sorted(os.listdir(test_dir)):
        if fn.endswith(".py"):
            with open(os.path.join(test_dir, fn), "r",
                      encoding="utf-8", errors="replace") as f:
                blob.append(f.read())
    text = "\n".join(blob)
    words = set(re.findall(r"[A-Za-z_][A-Za-z_0-9]*", text))
    return {op: (op in words) for op in registry.all_ops()}


def audit_registry(test_dir: Optional[str] = None) -> List[dict]:
    """One row per registered op, sorted by name."""
    tested = _tested_ops(test_dir) if test_dir else None
    rows = []
    for name in registry.all_ops():
        opdef = registry.get_op_def(name)
        rows.append({
            "op": name,
            "infer_shape": ("explicit" if opdef.infer_shape is not None
                            else "auto" if opdef.lower is not None
                            else "none"),
            "lower": opdef.lower is not None,
            "grad": _grad_mode(opdef),
            "needs_rng": opdef.needs_rng,
            "raw": opdef.raw,
            "tested": None if tested is None else tested[name],
        })
    return rows


def coverage_summary(rows: List[dict]) -> dict:
    n = len(rows)
    return {
        "ops": n,
        "with_lower": sum(r["lower"] for r in rows),
        "explicit_infer_shape": sum(r["infer_shape"] == "explicit"
                                    for r in rows),
        "differentiable": sum(r["grad"] != "none" for r in rows),
        "tested": (sum(bool(r["tested"]) for r in rows)
                   if rows and rows[0]["tested"] is not None else None),
    }


def format_audit(rows: List[dict]) -> str:
    cols = ["op", "infer_shape", "lower", "grad", "needs_rng", "raw",
            "tested"]
    if rows and rows[0]["tested"] is None:
        cols = cols[:-1]

    def cell(v):
        if v is True:
            return "yes"
        if v is False:
            return "-"
        return str(v)

    widths = {c: max(len(c), max((len(cell(r[c])) for r in rows),
                                 default=0)) for c in cols}
    lines = ["  ".join(c.ljust(widths[c]) for c in cols),
             "  ".join("-" * widths[c] for c in cols)]
    for r in rows:
        lines.append("  ".join(cell(r[c]).ljust(widths[c]) for c in cols))
    s = coverage_summary(rows)
    lines.append("")
    tail = (f"{s['ops']} ops | lower: {s['with_lower']} | explicit "
            f"infer_shape: {s['explicit_infer_shape']} | differentiable: "
            f"{s['differentiable']}")
    if s["tested"] is not None:
        tail += f" | referenced by tests: {s['tested']}"
    lines.append(tail)
    return "\n".join(lines)
