"""New static-analysis passes over the shared PassContext (docs/ANALYSIS.md).

Three diagnostic families the pass manager makes cheap — donation_race
leans on the liveness pass' cached def/use + donation analysis instead of
re-deriving it; dead_code is a standalone mark-and-sweep over the effect
classifier:

* ``check_dtype_shape``  (PT700–PT704) — whole-program dtype/shape replay:
  re-runs ``infer_shape`` across op boundaries WITHOUT restoring metadata
  between ops, so a producer whose replayed output disagrees with the
  recorded metadata is reported at the consumer that observes the drift
  (the shape_replay pass, PT40x, checks each op in isolation; this pass
  checks the op-to-op contract).
* ``check_donation_race`` (PT710–PT713) — the static face of the PR 2/PR 4
  donation-hazard class: variables the old heuristic would donate but a
  later op still reads, unordered double writes, fetches that view a
  donated buffer, and in-place writes to feed vars.
* ``check_dead_code``     (PT720–PT722) — transitive dead-op closure (the
  chain extension of PT502), unused outputs of live ops, unreachable
  sub-blocks; plus ``dce_program``, the opt-in transform that removes the
  proven-dead set, gated by a fidelity witness (refuse, never a wrong
  program — the remat pattern).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..core import registry
from .diagnostics import Diagnostic, Severity
from .verifier import EMPTY, _block_reads, _raw_attr_var_names, _site
from .liveness import classify_op_effects

__all__ = [
    "check_dtype_shape", "check_donation_race", "check_dead_code",
    "DeadCodeReport", "DceDecision", "dce_program", "VIEW_OP_TYPES",
]

# identity-like ops whose XLA lowering may alias the output buffer to the
# input (no data movement) — the PT712 alias-into-fetch surface
VIEW_OP_TYPES = frozenset({
    "assign", "reshape", "reshape2", "squeeze", "squeeze2", "unsqueeze",
    "unsqueeze2", "flatten", "flatten2", "share_data",
})


def _feeds_of(program, ctx) -> Set[str]:
    feeds = {v.name for v in program.global_block.vars.values() if v.is_data}
    feeds.update(ctx.feed_names)
    return feeds


# ---------------------------------------------------------------------------
# PT700s — whole-program dtype/shape consistency
# ---------------------------------------------------------------------------

def check_dtype_shape(program, ctx) -> List[Diagnostic]:
    """Replay ``infer_shape`` over every block in program order WITHOUT
    restoring var metadata between ops, so inferred shapes/dtypes propagate
    across op boundaries the way they will at lowering time. Mismatches are
    reported at the producer with the first consumer named (both with
    ``op_callstack`` build sites). All metadata is restored afterwards."""
    diags: List[Diagnostic] = []
    snapshot = {}
    for blk in program.blocks:
        for v in blk.vars.values():
            snapshot[(blk.idx, v.name)] = (v.shape, v.dtype)
    try:
        for blk in program.blocks:
            _replay_block(program, blk, diags)
    finally:
        for blk in program.blocks:
            for v in blk.vars.values():
                old = snapshot.get((blk.idx, v.name))
                if old is not None:
                    v.shape, v.dtype = old
    for d in diags:
        ctx.report(d)
    return diags


def _replay_block(program, blk, diags: List[Diagnostic]) -> None:
    # var -> list of (op_idx, op) reading it, for consumer attribution
    read_at: Dict[str, List[Tuple[int, object]]] = {}
    for oi, op in enumerate(blk.ops):
        for n in op.input_arg_names:
            if n != EMPTY:
                read_at.setdefault(n, []).append((oi, op))

    def first_consumer_after(name: str, oi: int):
        for ci, cop in read_at.get(name, ()):
            if ci > oi:
                return ci, cop
        return None, None

    # var -> (producer_idx, inferred shape, inferred dtype) for PT703
    produced_meta: Dict[str, Tuple[int, object, object]] = {}
    reported: Set[Tuple[str, str, int]] = set()   # (code, var, op idx)

    for oi, op in enumerate(blk.ops):
        if op.type in ("feed", "fetch") or not registry.has_op(op.type):
            continue
        # PT704 — consumer reads a var with no recorded shape: propagation
        # is undecidable past this boundary (dynamic/raw-op outputs)
        for n in op.input_arg_names:
            if n == EMPTY or not blk.has_var(n):
                continue
            v = blk.var(n)
            if v.shape is None and not v.is_data \
                    and ("PT704", n, oi) not in reported:
                reported.add(("PT704", n, oi))
                diags.append(Diagnostic(
                    "PT704",
                    f"op '{op.type}' reads '{n}' whose shape is unknown — "
                    f"dtype/shape propagation is blind past this boundary",
                    blk.idx, oi, op.type, _site(op)))
        before = {}
        for n in op.output_arg_names:
            if n != EMPTY and blk.has_var(n):
                v = blk.var(n)
                before[n] = (v.shape, v.dtype)
        try:
            op.infer_shape()
        except Exception as e:
            diags.append(Diagnostic(
                "PT700",
                f"op '{op.type}': infer_shape fails under whole-program "
                f"replay ({type(e).__name__}: {e}) — an upstream producer "
                f"hands it metadata it cannot consume",
                blk.idx, oi, op.type, _site(op)))
            continue
        for n, (old_shape, old_dtype) in before.items():
            v = blk.var(n)
            prev = produced_meta.get(n)
            if prev is not None:
                pi, pshape, pdtype = prev
                if (pdtype != v.dtype
                        or (pshape is not None and v.shape is not None
                            and tuple(pshape) != tuple(v.shape))):
                    diags.append(Diagnostic(
                        "PT703",
                        f"'{n}' is written by op {pi} as "
                        f"{_meta(pshape, pdtype)} and rebound by op {oi} "
                        f"('{op.type}') as {_meta(v.shape, v.dtype)} — "
                        f"consumers see whichever write ran last",
                        blk.idx, oi, op.type, _site(op)))
            produced_meta[n] = (oi, v.shape, v.dtype)
            ci, cop = first_consumer_after(n, oi)
            if cop is None:
                continue
            if (old_shape is not None and v.shape is not None
                    and tuple(old_shape) != tuple(v.shape)):
                diags.append(Diagnostic(
                    "PT701",
                    f"op '{op.type}' replays '{n}' as shape "
                    f"{tuple(v.shape)} but the recorded shape its consumer "
                    f"op {ci} ('{cop.type}'{_consumer_site(cop)}) was built "
                    f"against is {tuple(old_shape)}",
                    blk.idx, oi, op.type, _site(op)))
            if old_dtype is not None and old_dtype != v.dtype:
                diags.append(Diagnostic(
                    "PT702",
                    f"op '{op.type}' replays '{n}' as dtype {v.dtype} but "
                    f"the recorded dtype its consumer op {ci} "
                    f"('{cop.type}'{_consumer_site(cop)}) was built "
                    f"against is {old_dtype}",
                    blk.idx, oi, op.type, _site(op)))


def _meta(shape, dtype) -> str:
    s = tuple(shape) if shape is not None else "?"
    return f"{dtype}{s}"


def _consumer_site(op) -> str:
    site = _site(op)
    return f" at {site}" if site else ""


# ---------------------------------------------------------------------------
# PT710s — donation/alias race detector
# ---------------------------------------------------------------------------

def check_donation_race(program, ctx) -> List[Diagnostic]:
    """Turn the PR 2/PR 4 donation-hazard class into static diagnostics.
    Uses the liveness pass' cached def/use chains and donation analysis
    (``ctx.analysis("liveness")``) — the executor refuses the unsafe
    donations at runtime; this pass explains them at build time."""
    diags: List[Diagnostic] = []
    live_info = ctx.analysis("liveness")
    gb = program.global_block
    live = live_info["live"]
    cands = live_info["cands"]
    unsafe = live_info["unsafe"]
    safe = cands - set(unsafe)
    fetch = set(ctx.fetch_names)

    # PT710 — donated on one path, still read later: the old heuristic's
    # set minus the proven set, for the read-after-write reason (the
    # fetched flavour is PT500's)
    for n in sorted(unsafe):
        if n in fetch:
            continue  # PT500 covers the fetched flavour
        vl = live[n]
        ld, lu = vl.last_def, vl.last_use
        op = gb.ops[lu] if lu is not None and lu < len(gb.ops) else None
        diags.append(Diagnostic(
            "PT710",
            f"'{n}' would be donated by the state_in∩state_out heuristic "
            f"but op {lu} still reads it after its last write (op {ld}) — "
            f"the donated buffer would already be consumed; the liveness "
            f"proof keeps it un-donated (a host copy per step)",
            gb.idx, lu, op.type if op else None, _site(op) if op else ""))

    # PT711 — unordered double writes, per block: two writes of one var
    # with no read of the var between them and no direct data dependency
    # (the later op reads nothing the earlier one produced). List order is
    # the only thing sequencing them.
    for blk in program.blocks:
        _check_unordered_writes(blk, diags)

    # PT712 — a fetched var that is a view of a donated var, taken BEFORE
    # the donated var's last in-place update: the fetch may alias the
    # consumed buffer (XLA may lower view ops with no copy).
    for oi, op in enumerate(gb.ops):
        if op.type not in VIEW_OP_TYPES:
            continue
        srcs = [n for n in op.input_arg_names if n != EMPTY and n in safe]
        outs = [n for n in op.output_arg_names if n != EMPTY and n in fetch]
        for src in srcs:
            vl = live.get(src)
            if vl is None or vl.last_def is None or oi >= vl.last_def:
                continue  # view taken after the final write: consistent
            for out in outs:
                diags.append(Diagnostic(
                    "PT712",
                    f"fetch '{out}' is a '{op.type}' view of donated "
                    f"'{src}' taken at op {oi}, before '{src}'s last "
                    f"in-place write (op {vl.last_def}) — the fetched "
                    f"value may alias a consumed buffer",
                    gb.idx, oi, op.type, _site(op)))

    # PT713 — in-place write to a feed var: the user's host array and the
    # scope copy diverge silently (feeds are device_put per step).
    feeds = _feeds_of(program, ctx)
    for blk in program.blocks:
        for oi, op in enumerate(blk.ops):
            if op.type in ("feed", "fetch"):
                continue
            for n in op.output_arg_names:
                if n != EMPTY and n in feeds:
                    diags.append(Diagnostic(
                        "PT713",
                        f"op '{op.type}' writes feed var '{n}' — the fed "
                        f"host buffer and the in-step value diverge; feed "
                        f"a copy or write a fresh var instead",
                        blk.idx, oi, op.type, _site(op)))

    for d in diags:
        ctx.report(d)
    return diags


def _check_unordered_writes(blk, diags: List[Diagnostic]) -> None:
    writes_at: Dict[str, List[int]] = {}
    reads_at: Dict[str, List[int]] = {}
    for oi, op in enumerate(blk.ops):
        if op.type in ("feed", "fetch"):
            continue
        for n in op.output_arg_names:
            if n != EMPTY:
                writes_at.setdefault(n, []).append(oi)
        for n in op.input_arg_names:
            if n != EMPTY:
                reads_at.setdefault(n, []).append(oi)
    for n, ws in writes_at.items():
        for a, b in zip(ws, ws[1:]):
            opb = blk.ops[b]
            b_reads = set(opb.input_arg_names)
            if n in b_reads:
                continue  # read-modify-write: ordered by the value chain
            if any(a < r < b for r in reads_at.get(n, ())):
                continue  # an intervening read orders the pair
            opa = blk.ops[a]
            a_outs = {x for x in opa.output_arg_names if x != EMPTY}
            if b_reads & a_outs:
                continue  # direct dependency on another of a's outputs
            diags.append(Diagnostic(
                "PT711",
                f"ops {a} ('{opa.type}') and {b} ('{opb.type}') both "
                f"write '{n}' with no read or data dependency between "
                f"them — only list order sequences the writes, and the "
                f"earlier value is unobservable",
                blk.idx, b, opb.type, _site(opb)))


# ---------------------------------------------------------------------------
# PT720s — dead/unreachable code lint + the opt-in DCE transform
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class DeadCodeReport:
    """The dead_code analysis result cached on the PassContext (also what
    the DCE transform consumes)."""

    # (block_idx, op_idx) of every transitively dead, eliminable op
    dead_ops: List[Tuple[int, int]]
    # (block_idx, op_idx, var name) unused outputs of live ops
    unused_outputs: List[Tuple[int, int, str]]
    # block idx of sub-blocks no op references
    unreachable_blocks: List[int]
    # every var name some live op still reads (for the DCE var sweep)
    needed_names: Set[str]

    def to_dict(self) -> dict:
        return {"dead_ops": [list(t) for t in self.dead_ops],
                "unused_outputs": [list(t) for t in self.unused_outputs],
                "unreachable_blocks": list(self.unreachable_blocks)}


def _dead_code_analysis(program, fetch_names: Sequence[str]
                        ) -> DeadCodeReport:
    """Backward mark-and-sweep over the whole program: roots are fetches,
    persistable writes, and non-eliminable ops (side effects, collectives,
    control flow); liveness propagates from an op to the producers of
    every name it (or its sub-blocks) reads. Ops never reached are
    transitively dead — including chains PT502 misses, where A's only
    reader is the dead op B."""
    fetch = set(fetch_names or ())
    persistable = {v.name for blk in program.blocks
                   for v in blk.vars.values() if v.persistable}
    memo: Dict[int, Set[str]] = {}

    ops = []  # (blk, oi, op, reads, writes, eliminable)
    producers: Dict[str, List[int]] = {}
    referenced_blocks: Set[int] = {0}
    for blk in program.blocks:
        for oi, op in enumerate(blk.ops):
            reads = {n for n in op.input_arg_names if n != EMPTY}
            sub = op.attrs.get("sub_block")
            if isinstance(sub, int) and 0 <= sub < len(program.blocks):
                referenced_blocks.add(sub)
                reads.update(_block_reads(program, sub, memo))
                reads.update(_raw_attr_var_names(op))
            writes = {n for n in op.output_arg_names if n != EMPTY}
            eff = classify_op_effects(op)
            idx = len(ops)
            ops.append((blk, oi, op, reads, writes, eff.eliminable))
            for n in writes:
                producers.setdefault(n, []).append(idx)

    # ops inside a sub-block live or die with the owning op's reachability;
    # the sweep below only ever removes GLOBAL-block ops, so sub-block ops
    # are rooted unless their whole block is unreachable
    live_ops: Set[int] = set()
    worklist: List[int] = []
    for idx, (blk, oi, op, reads, writes, eliminable) in enumerate(ops):
        rooted = (not eliminable
                  or op.type in ("feed", "fetch")
                  or blk.idx != 0
                  or any(n in fetch or n in persistable for n in writes))
        if rooted:
            live_ops.add(idx)
            worklist.append(idx)
    while worklist:
        idx = worklist.pop()
        for n in ops[idx][3]:           # reads of the live op
            for p in producers.get(n, ()):
                if p not in live_ops:
                    live_ops.add(p)
                    worklist.append(p)

    needed: Set[str] = set(fetch) | set(persistable)
    for idx in live_ops:
        needed.update(ops[idx][3])

    dead: List[Tuple[int, int]] = []
    unused: List[Tuple[int, int, str]] = []
    for idx, (blk, oi, op, reads, writes, eliminable) in enumerate(ops):
        if idx not in live_ops:
            dead.append((blk.idx, oi))
        elif blk.idx == 0 and op.type not in ("feed", "fetch"):
            for n in sorted(writes):
                if n not in needed:
                    unused.append((blk.idx, oi, n))

    unreachable = [blk.idx for blk in program.blocks
                   if blk.idx not in referenced_blocks]
    return DeadCodeReport(dead_ops=dead, unused_outputs=unused,
                          unreachable_blocks=unreachable,
                          needed_names=needed)


def check_dead_code(program, ctx) -> DeadCodeReport:
    """The PT720–PT722 lint pass; returns the ``DeadCodeReport`` the DCE
    transform reuses from the context cache."""
    report = _dead_code_analysis(program, ctx.fetch_names)
    for bidx, oi in report.dead_ops:
        op = program.blocks[bidx].ops[oi]
        outs = sorted(n for n in op.output_arg_names if n != EMPTY)
        ctx.report(Diagnostic(
            "PT720",
            f"transitively dead op: '{op.type}' ({', '.join(outs)}) "
            f"reaches no fetch, persistable or effect — every consumer "
            f"chain is itself dead",
            bidx, oi, op.type, _site(op)))
    for bidx, oi, n in report.unused_outputs:
        op = program.blocks[bidx].ops[oi]
        ctx.report(Diagnostic(
            "PT721",
            f"unused output: '{n}' of live op '{op.type}' is never read, "
            f"fetched or persistable",
            bidx, oi, op.type, _site(op)))
    for bidx in report.unreachable_blocks:
        ctx.report(Diagnostic(
            "PT722",
            f"sub-block {bidx} is unreachable: no op references it via a "
            f"sub_block attr",
            bidx, None, None, ""))
    return report


@dataclasses.dataclass
class DceDecision:
    """Outcome of the opt-in DCE transform (``applied=False`` => the
    original program is returned untouched, with the reason)."""

    applied: bool
    program: object
    reason: str
    removed_ops: int = 0
    removed_vars: int = 0

    def to_dict(self) -> dict:
        return {"applied": self.applied, "reason": self.reason,
                "removed_ops": self.removed_ops,
                "removed_vars": self.removed_vars}


def dce_program(program, fetch_names: Sequence[str] = (),
                report: Optional[DeadCodeReport] = None) -> DceDecision:
    """Remove the transitively dead op set from a CLONE of ``program``,
    gated by a fidelity witness (the remat pattern — refuse, never a wrong
    program): after removal the dead-code analysis is re-run on the result
    and must find zero dead ops and the identical needed-name set, and no
    live op may have lost a producer. Any witness failure refuses."""
    if report is None:
        report = _dead_code_analysis(program, fetch_names)
    if not report.dead_ops:
        return DceDecision(False, program, "no dead ops found")
    if any(bidx != 0 for bidx, _ in report.dead_ops):
        # sub-block surgery would need owner-op attr rewrites; refuse
        return DceDecision(False, program,
                           "dead ops inside sub-blocks — DCE only proves "
                           "global-block removals safe")

    p = program.clone()
    gb = p.global_block
    dead_idx = {oi for bidx, oi in report.dead_ops if bidx == 0}
    removed = [op for oi, op in enumerate(gb.ops) if oi in dead_idx]
    gb.ops = [op for oi, op in enumerate(gb.ops) if oi not in dead_idx]

    # drop vars only the removed ops touched (declared activations)
    still_used: Set[str] = set(report.needed_names)
    for op in gb.ops:
        still_used.update(n for n in op.input_arg_names if n != EMPTY)
        still_used.update(n for n in op.output_arg_names if n != EMPTY)
    removable = []
    for op in removed:
        for n in op.output_arg_names:
            if (n != EMPTY and n in gb.vars and n not in still_used
                    and not gb.vars[n].persistable
                    and not gb.vars[n].is_data):
                removable.append(n)
    for n in removable:
        del gb.vars[n]
    p._bump_version()

    # fidelity witness: the transformed program must be provably clean
    check = _dead_code_analysis(p, fetch_names)
    if check.dead_ops:
        return DceDecision(False, program,
                           "witness failed: removal exposed further dead "
                           "ops — refusing (run the lint, fix the build)")
    if check.needed_names - still_used:
        return DceDecision(False, program,
                           "witness failed: the transformed program needs "
                           "names the original analysis did not — refusing")
    missing = [n for n in check.needed_names
               if n not in gb.vars and not any(
                   n in blk.vars for blk in p.blocks)]
    if missing:
        return DceDecision(False, program,
                           f"witness failed: needed vars vanished "
                           f"({missing[:3]}) — refusing")
    return DceDecision(True, p,
                       f"removed {len(removed)} dead op(s), "
                       f"{len(removable)} var(s)",
                       removed_ops=len(removed),
                       removed_vars=len(removable))
