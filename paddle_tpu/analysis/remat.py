"""Pass 6 — automatic rematerialisation (auto gradient checkpointing).

The manual path already exists end to end: ``RecomputeOptimizer`` lets the
user name checkpoint activations before backward construction and
``ops/recompute.py`` collapses each forward segment into ONE
``recompute_segment`` op lowered under ``jax.checkpoint`` (bit-identical
training, proven by tests/test_recompute.py). What the user had to bring was
the checkpoint set — and by executor time the program is already a complete
forward+backward+optimize artifact, too late for the manual API.

This pass closes that gap, in the spirit of search-based tensor-program
tuning (Chen et al., "Learning to Optimize Tensor Programs") applied at the
*program* level: the candidate space is enumerated from the program itself,
each candidate configuration is *scored statically* with the PR-2 liveness
planner (``Program.memory_plan``), and the cheapest configuration that fits
the budget wins. No hardware in the loop — the cost model is the linear-scan
live-byte plan, which models remat faithfully because segment internals are
demoted into sub-blocks (dead between forward and backward) and the grad op
inherits the ``sub_block`` attr, so the planner charges the recompute peak
at the backward op that replays it.

Pipeline:

1. **Partition** the global block by ``__op_role__``: forward prefix,
   backward region, tail (optimize / lr_sched / trailing forward ops).
2. **Fidelity proof** — rebuild the program with NO checkpoints (strip the
   backward region, re-run ``append_backward`` on the same loss, reattach
   the tail) and require op-for-op equality with the original modulo
   volatile attrs (``__uid__``, build sites). Programs whose backward was
   not produced by the stock ``append_backward`` (custom no_grad sets,
   loss-scaled AMP, while-loop grad blocks) fail this proof and are left
   untouched — auto-remat refuses rather than risks.
3. **Candidates** — forward ops at layer boundaries (where the
   ``op_callstack`` build site changes, i.e. the seam between two builder
   calls) with exactly one float activation flowing to later forward ops;
   sized via infer_shape shapes with ``-1`` dims resolved to the feed batch.
4. **Search** — segment counts from a geometric ladder are scored by
   rebuilding (clone → strip backward → ``insert_recompute_segments`` →
   re-append backward → reattach tail) and planning peak bytes. With
   ``FLAGS_remat_budget_mb`` set, the *cheapest* fitting set wins (most
   checkpoints = least recomputation); without a budget, sqrt(N)
   segmentation (Chen et al. 2016 gradient-checkpointing spacing).

The chosen program is a fresh ``Program`` with its own ``_serial``, so
executor compile caches can never alias remat and plain variants.
Wiring: ``Executor._maybe_auto_remat`` (FLAGS_auto_recompute) on ``run`` /
``run_chained`` / ``CompiledProgram``; counters in docs/OBSERVABILITY.md;
methodology in docs/PERF_NOTES.md; diagnostics table in docs/ANALYSIS.md.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..framework import OpRole, Program
from .verifier import EMPTY

__all__ = [
    "RematCandidate", "RematDecision", "RematError",
    "forward_region", "is_trainable_program", "find_loss_name",
    "remat_candidates", "rebuild_with_checkpoints",
    "auto_recompute_program",
]

# attrs that legitimately differ between an original program and a faithful
# rebuild: fresh uid stamps and the build site of re-appended ops
_VOLATILE_ATTRS = ("__uid__", "op_callstack", "op_namescope")


class RematError(RuntimeError):
    """Auto-remat could not transform the program (the caller should fall
    back to the untransformed program; the message says why)."""


def _op_signature(op) -> tuple:
    attrs = sorted((k, repr(v)) for k, v in op.attrs.items()
                   if k not in _VOLATILE_ATTRS)
    return (op.type,
            tuple(sorted((k, tuple(v)) for k, v in op.inputs.items())),
            tuple(sorted((k, tuple(v)) for k, v in op.outputs.items())),
            tuple(attrs))


def forward_region(block) -> Optional[int]:
    """Index of the first backward-role op in ``block``, i.e. the exclusive
    end of the forward prefix; None when the block has no backward ops
    (inference / startup programs)."""
    for i, op in enumerate(block.ops):
        if op.attrs.get("__op_role__", OpRole.Forward) == OpRole.Backward:
            return i
    return None


def is_trainable_program(program: Program) -> bool:
    return forward_region(program.global_block) is not None


def find_loss_name(block, first_bwd: int) -> Optional[str]:
    """The backward target: ``append_backward`` seeds the sweep with a
    backward-role ``fill_constant`` writing ``<loss>@GRAD`` = 1.0 (the very
    first backward op). Anything else — user cotangents, several targets —
    is not a stock training program and auto-remat refuses."""
    from ..framework import GRAD_VAR_SUFFIX

    op = block.ops[first_bwd]
    if op.type != "fill_constant":
        return None
    outs = op.output_arg_names
    if len(outs) != 1 or not outs[0].endswith(GRAD_VAR_SUFFIX):
        return None
    if float(op.attrs.get("value", 0.0)) != 1.0:
        return None
    name = outs[0][:-len(GRAD_VAR_SUFFIX)]
    return name if block.has_var(name) else None


# ---------------------------------------------------------------------------
# candidate discovery
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RematCandidate:
    """One legal checkpoint position: cutting after ``op_idx`` and saving
    ``var_name`` across the fwd/bwd gap costs ``nbytes`` of residency."""

    op_idx: int
    var_name: str
    nbytes: int
    site: str  # op_callstack build site of the producing op


def _activation_bytes(v, batch_size: int) -> Optional[int]:
    from .liveness import _var_bytes

    if v is None or v.shape is None:
        return None
    nbytes, _ = _var_bytes(v, batch_size)
    return nbytes


def remat_candidates(program: Program, batch_size: int = 1,
                     boundaries_only: bool = True) -> List[RematCandidate]:
    """Checkpointable positions in the forward region of ``program``.

    A forward op qualifies when exactly one of its outputs is a float,
    non-persistable, known-shape activation read by a LATER forward op (the
    value that flows across the would-be cut). With ``boundaries_only`` the
    list is restricted to layer boundaries — ops whose successor was built
    at a different user call site (``op_callstack``), the seam between two
    layer-builder invocations. Build sites record the first frame OUTSIDE
    paddle_tpu, so models built by package code (models/bert.py) or inside
    a Python loop share one site for every op; when boundary filtering
    leaves fewer than 4 positions, all qualifying ops are returned and the
    even-spacing picker provides the layer structure instead."""
    from ..core.types import is_floating

    block = program.global_block
    first_bwd = forward_region(block)
    if first_bwd is None:
        return []
    fwd_ops = block.ops[:first_bwd]

    read_at: Dict[str, List[int]] = {}
    for i, op in enumerate(fwd_ops):
        for n in op.input_arg_names:
            if n != EMPTY:
                read_at.setdefault(n, []).append(i)

    all_cands: List[RematCandidate] = []
    boundary: List[RematCandidate] = []
    for i, op in enumerate(fwd_ops[:-1]):  # a cut at the last op is useless
        flowing: List[Tuple[str, int]] = []
        skip = False
        for n in op.output_arg_names:
            if n == EMPTY or not block.has_var(n):
                continue
            reads = read_at.get(n, [])
            if not any(r > i for r in reads):
                continue  # only backward/tail read it; not a forward seam
            v = block.var(n)
            if v.persistable or v.is_data or not is_floating(v.dtype):
                skip = True  # a persistable flowing forward: odd op, skip
                break
            nb = _activation_bytes(v, batch_size)
            if nb is None:
                skip = True
                break
            flowing.append((n, nb))
        if skip or len(flowing) != 1:
            continue
        name, nb = flowing[0]
        cand = RematCandidate(op_idx=i, var_name=name, nbytes=nb,
                              site=op.attrs.get("op_callstack", ""))
        all_cands.append(cand)
        if fwd_ops[i + 1].attrs.get("op_callstack", "") != cand.site:
            boundary.append(cand)
    if boundaries_only and len(boundary) >= 4:
        return boundary
    return all_cands


# ---------------------------------------------------------------------------
# program rebuild: strip backward -> segment forward -> regenerate backward
# ---------------------------------------------------------------------------

def rebuild_with_checkpoints(program: Program, loss_name: str,
                             checkpoints: Sequence[str],
                             extra_live: Sequence[str] = ()
                             ) -> Tuple[Program, int]:
    """Clone ``program``; drop its backward-role ops; collapse the forward
    region into ``recompute_segment`` ops at ``checkpoints`` (no-op when
    empty); regenerate the backward with ``append_backward``; reattach the
    non-backward tail (optimize / lr_sched / trailing forward ops) in their
    original order. Returns ``(rebuilt_program, n_segments)``.

    The rebuilt program is a fresh ``Program`` (own ``_serial``), so
    executor caches never alias it with the source program. ``extra_live``
    names (fetches, tail reads) are kept as segment outputs so transparent
    remat never breaks a fetch the way the manual API is allowed to."""
    from ..backward import append_backward
    from ..ops.recompute import insert_recompute_segments

    p = program.clone()
    blk = p.global_block
    first_bwd = forward_region(blk)
    if first_bwd is None:
        raise RematError("program has no backward ops — nothing to remat")
    tail = [op for op in blk.ops[first_bwd:]
            if op.attrs.get("__op_role__", OpRole.Forward) != OpRole.Backward]
    blk.ops = list(blk.ops[:first_bwd])
    if not blk.has_var(loss_name):
        raise RematError(f"loss var '{loss_name}' not in the global block")
    loss = blk.var(loss_name)

    tail_reads = {n for op in tail for n in op.input_arg_names if n != EMPTY}
    n_segments = 0
    if checkpoints:
        n_segments = insert_recompute_segments(
            loss, list(checkpoints),
            extra_live=sorted(tail_reads | set(extra_live)))
    append_backward(loss)
    blk.ops.extend(tail)
    p._bump_version()
    return p, n_segments


def _programs_equivalent(a: Program, b: Program) -> bool:
    ao, bo = a.global_block.ops, b.global_block.ops
    if len(ao) != len(bo):
        return False
    return all(_op_signature(x) == _op_signature(y) for x, y in zip(ao, bo))


# ---------------------------------------------------------------------------
# the chooser
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class RematDecision:
    """Outcome of one auto-remat attempt (also the monitor/bench payload)."""

    applied: bool
    program: Program                  # transformed, or the original
    reason: str
    n_segments: int = 0
    n_candidates: int = 0
    checkpoints: Tuple[str, ...] = ()
    peak_before: int = 0
    peak_after: int = 0
    budget_bytes: Optional[int] = None
    batch_size: int = 1
    trials: List[dict] = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "applied": self.applied, "reason": self.reason,
            "segments": self.n_segments, "candidates": self.n_candidates,
            "checkpoints": list(self.checkpoints),
            "predicted_peak_bytes_plain": self.peak_before,
            "predicted_peak_bytes_remat": self.peak_after,
            "budget_bytes": self.budget_bytes,
            "batch_size": self.batch_size,
            "trials": list(self.trials),
        }


def _pick_evenly(cands: List[RematCandidate],
                 k: int) -> List[RematCandidate]:
    """k checkpoints spread evenly over the candidate sequence (classic
    every-sqrt(N)th-layer spacing generalised to arbitrary k)."""
    n = len(cands)
    if k >= n:
        return list(cands)
    idxs = sorted({int(round((j + 1) * n / (k + 1.0))) - 1
                   for j in range(k)})
    return [cands[max(0, min(n - 1, i))] for i in idxs]


def _k_ladder(n: int, max_trials: int) -> List[int]:
    """Segment-count ladder, densest first: n, n/2, n/4, ..., plus the
    sqrt(N) default, deduped, capped at ``max_trials`` entries."""
    ks: List[int] = []
    k = n
    while k >= 1 and len(ks) < max_trials - 1:
        if k not in ks:
            ks.append(k)
        k //= 2
    s = max(1, int(round(math.sqrt(n))))
    if s not in ks:
        ks.append(s)
    return sorted(set(ks), reverse=True)[:max_trials]


def auto_recompute_program(program: Program,
                           feed_names: Sequence[str] = (),
                           fetch_names: Sequence[str] = (),
                           batch_size: int = 1,
                           budget_mb: int = 0,
                           max_trials: int = 6) -> RematDecision:
    """The auto-remat chooser: candidate discovery, static scoring via
    ``memory_plan``, budget fit, rebuild. Never raises on an untransformable
    program — it returns ``applied=False`` with the reason, and the caller
    runs the original (``RematError`` is internal)."""
    feed_names = list(feed_names)
    fetch_names = [getattr(f, "name", f) for f in (fetch_names or ())]
    batch_size = max(int(batch_size), 1)

    def refuse(reason: str, **kw) -> RematDecision:
        return RematDecision(applied=False, program=program, reason=reason,
                             batch_size=batch_size, **kw)

    if int(getattr(program, "_pipeline_microbatches", 1)) > 1:
        return refuse("pipeline program: the microbatch scan already "
                      "bounds activation residency")
    block = program.global_block
    first_bwd = forward_region(block)
    if first_bwd is None:
        return refuse("no backward ops (inference/startup program)")
    if any(op.type == "recompute_segment" for op in block.ops):
        return refuse("program already carries recompute segments "
                      "(manual RecomputeOptimizer)")
    loss_name = find_loss_name(block, first_bwd)
    if loss_name is None:
        return refuse("backward seed not recognised (custom cotangents or "
                      "non-stock backward) — cannot rebuild faithfully")

    try:
        plain, _ = rebuild_with_checkpoints(program, loss_name, ())
    except Exception as e:  # registry gaps, exotic ops
        return refuse(f"backward regeneration failed: {e}")
    if not _programs_equivalent(program, plain):
        return refuse("backward regeneration does not reproduce the "
                      "original program (custom no_grad/parameter_list, "
                      "loss scaling, or sub-block grads) — refusing")

    cands = remat_candidates(program, batch_size=batch_size)
    if not cands:
        return refuse("no checkpointable layer boundaries found")

    plan0 = program.memory_plan(feed_names=feed_names,
                                fetch_names=fetch_names,
                                batch_size=batch_size)
    peak0 = plan0.peak_bytes
    budget_bytes = int(budget_mb) * (1 << 20) if budget_mb else None
    if budget_bytes is not None and peak0 <= budget_bytes:
        # cheapest fitting set is NO checkpoints: the plain program already
        # fits; inserting segments would buy recompute cost for nothing
        return refuse(f"plain predicted peak {peak0 >> 20} MiB already "
                      f"fits the {budget_mb} MiB budget",
                      n_candidates=len(cands), peak_before=peak0,
                      budget_bytes=budget_bytes)

    def score(k: int) -> Tuple[Program, int, int, List[str]]:
        picks = [c.var_name for c in _pick_evenly(cands, k)]
        prog_k, nseg = rebuild_with_checkpoints(
            program, loss_name, picks, extra_live=fetch_names)
        plan = prog_k.memory_plan(feed_names=feed_names,
                                  fetch_names=fetch_names,
                                  batch_size=batch_size)
        return prog_k, nseg, plan.peak_bytes, picks

    trials: List[dict] = []
    best = None  # (program, nseg, peak, picks, k)
    if budget_bytes is None:
        k = max(1, int(round(math.sqrt(len(cands)))))
        prog_k, nseg, peak, picks = score(k)
        trials.append({"k": k, "segments": nseg, "peak_bytes": peak,
                       "fits": None})
        if nseg and peak < peak0:
            best = (prog_k, nseg, peak, picks, k)
    else:
        # cheapest first (max checkpoints = least recompute): the first
        # fitting rung wins; remember the min-peak rung as the fallback
        fallback = None
        for k in _k_ladder(len(cands), max_trials):
            prog_k, nseg, peak, picks = score(k)
            fits = peak <= budget_bytes
            trials.append({"k": k, "segments": nseg, "peak_bytes": peak,
                           "fits": fits})
            if nseg == 0:
                continue
            if fits:
                best = (prog_k, nseg, peak, picks, k)
                break
            if fallback is None or peak < fallback[2]:
                fallback = (prog_k, nseg, peak, picks, k)
        if best is None and fallback is not None \
                and fallback[2] < peak0:
            best = fallback

    if best is None:
        return refuse("no checkpoint set improved the predicted peak",
                      n_candidates=len(cands), peak_before=peak0,
                      budget_bytes=budget_bytes, trials=trials)

    prog_k, nseg, peak, picks, k = best
    return RematDecision(
        applied=True, program=prog_k,
        reason=(f"k={k} checkpoints over {len(cands)} boundaries"
                + (f", fits {budget_mb} MiB budget" if budget_bytes
                   and peak <= budget_bytes else
                   (", best effort over budget" if budget_bytes else
                    ", sqrt(N) default"))),
        n_segments=nseg, n_candidates=len(cands),
        checkpoints=tuple(picks), peak_before=peak0, peak_after=peak,
        budget_bytes=budget_bytes, batch_size=batch_size, trials=trials)
