"""incubate namespace (reference python/paddle/fluid/incubate)."""
