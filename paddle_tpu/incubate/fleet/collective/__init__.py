"""Fleet collective mode: the distributed-training front door.

Reference: python/paddle/fluid/incubate/fleet/collective/__init__.py
(:41 CollectiveOpBasedFleet, :94 DistributedStrategy, :142
CollectiveOptimizer) — there the distributed_optimizer rewrites the program
through the collective transpiler, inserting c_allreduce_sum on every grad
(transpiler/collective.py:178 GradAllReduce).

TPU-native: no transpilation. ``fleet.distributed_optimizer(opt).minimize``
builds the normal single-device program; ``fleet.main_program`` returns it
wrapped in a CompiledProgram over the device mesh, where GSPMD places the
gradient collectives. Multi-process ranks bootstrap through
``fleet.init`` -> ``distributed.init_parallel_env`` (the gen_nccl_id
replacement). Sharded embeddings (is_sparse/is_distributed tables) ride the
same path — their tables row-shard over the mesh instead of living on
parameter servers.
"""
from __future__ import annotations

import os
from typing import Optional

from ....parallel.compiled_program import (BuildStrategy, CompiledProgram,
                                           ReduceStrategy)
from ..base.role_maker import PaddleCloudRoleMaker, RoleMakerBase

__all__ = ["fleet", "Fleet", "DistributedStrategy", "CollectiveOptimizer",
           "LocalSGDSync"]


class DistributedStrategy:
    """Reference collective/__init__.py:94 — knobs that still mean something
    under XLA, plus accepted-for-parity fields."""

    def __init__(self):
        self.use_local_sgd = False
        self.use_dgc = False                  # no ICI analogue; parity only
        self.nccl_comm_num = 1                # parity; XLA owns comm lanes
        self.forward_recompute = False
        self.recompute_checkpoints = []
        self.use_amp = False
        self.amp_loss_scaling = 2 ** 15
        # ZeRO-1: shard optimizer state over data-parallel ranks
        self.use_sharding = False


class Fleet:
    def __init__(self):
        self._role_maker: Optional[RoleMakerBase] = None
        self._strategy = DistributedStrategy()
        self._origin_program = None
        self._compiled = None
        self._startup = None
        self._inited = False

    # -- lifecycle (reference fleet_base.py:29 Fleet.init) ----------------
    def init(self, role_maker: Optional[RoleMakerBase] = None):
        self._role_maker = role_maker or PaddleCloudRoleMaker()
        self._role_maker.generate_role()
        if self._role_maker.worker_num() > 1:
            from .... import distributed as dist

            dist.init_parallel_env()
        self._inited = True
        return self

    def _require_init(self):
        if not self._inited:
            raise RuntimeError("call fleet.init(role) before using fleet")

    # -- cluster views ----------------------------------------------------
    def is_first_worker(self) -> bool:
        self._require_init()
        return self._role_maker.is_first_worker()

    def worker_index(self) -> int:
        self._require_init()
        return self._role_maker.worker_index()

    def worker_num(self) -> int:
        self._require_init()
        return self._role_maker.worker_num()

    def is_worker(self) -> bool:
        self._require_init()
        return self._role_maker.is_worker()

    def worker_endpoints(self):
        self._require_init()
        return self._role_maker.get_trainer_endpoints()

    # -- the optimizer wrapper -------------------------------------------
    def distributed_optimizer(self, optimizer,
                              strategy: Optional[DistributedStrategy] = None):
        self._require_init()
        if strategy is not None:
            self._strategy = strategy
        return CollectiveOptimizer(self, optimizer, self._strategy)

    # -- programs to run (reference fleet.main_program property) ----------
    @property
    def main_program(self):
        if self._compiled is None:
            raise RuntimeError("minimize() a distributed_optimizer first")
        return self._compiled

    @property
    def startup_program(self):
        from ....framework import default_startup_program

        return self._startup or default_startup_program()

    def save_persistables(self, executor, dirname, main_program=None):
        from .... import io

        prog = main_program or self._origin_program
        return io.save_persistables(executor, dirname, prog)


class CollectiveOptimizer:
    """reference collective/__init__.py:142 — wraps a normal optimizer;
    minimize() additionally prepares the mesh-compiled program."""

    def __init__(self, fleet_: Fleet, optimizer, strategy: DistributedStrategy):
        self._fleet = fleet_
        self._inner = optimizer
        self._strategy = strategy

    def backward(self, loss, **kw):
        return self._inner.backward(loss, **kw)

    def apply_gradients(self, params_grads):
        return self._inner.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        opt = self._inner
        if self._strategy.use_amp:
            from ....contrib import mixed_precision as mp

            opt = mp.decorate(opt,
                              init_loss_scaling=self._strategy.amp_loss_scaling)
        if self._strategy.forward_recompute:
            from ....optimizer import RecomputeOptimizer

            opt = RecomputeOptimizer(opt)
            opt._set_checkpoints(list(self._strategy.recompute_checkpoints))
        result = opt.minimize(loss, startup_program=startup_program,
                              parameter_list=parameter_list,
                              no_grad_set=no_grad_set)

        bs = BuildStrategy()
        if self._strategy.use_sharding:
            bs.reduce_strategy = ReduceStrategy.Reduce
        program = loss.block.program
        self._fleet._origin_program = program
        self._fleet._startup = startup_program
        self._fleet._compiled = CompiledProgram(program).with_data_parallel(
            loss_name=loss.name, build_strategy=bs)
        return result


class LocalSGDSync:
    """LocalSGD (reference transpiler/collective.py:269 LocalSGD): each
    rank trains INDEPENDENTLY (no per-step gradient allreduce) and every
    ``k`` steps the persistable parameters are averaged across processes —
    trading per-step ICI/DCN traffic for slightly stale weights.

    Usage: run the PLAIN (non-data-parallel) program per rank and call
    ``sync.step(scope)`` after each exe.run; every k-th call averages.
    """

    def __init__(self, program, k_steps: int = 1):
        self._names = [p.name for p in program.all_parameters()]
        self._k = max(1, int(k_steps))
        self._count = 0

    def step(self, scope) -> bool:
        """Returns True when a sync happened on this call."""
        self._count += 1
        if self._count % self._k:
            return False
        from ....distributed import allgather_mean_tree

        tree = {}
        for n in self._names:
            v = scope.find_var(n)
            if v is None:
                raise RuntimeError(
                    f"LocalSGDSync: parameter '{n}' not initialized in "
                    f"scope — run the startup program first")
            tree[n] = v
        for n, v in allgather_mean_tree(tree).items():
            scope.set_var(n, v)
        return True


fleet = Fleet()
