from . import fs  # noqa: F401
