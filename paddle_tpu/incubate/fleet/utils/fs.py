"""Filesystem shim: local + HDFS (reference paddle/fluid/framework/io/fs.h
localfs_*/hdfs_* via piped shell commands, and
python/paddle/fluid/incubate/fleet/utils/hdfs.py HDFSClient).

LocalFS is a plain implementation; HDFSClient shells out to the ``hadoop``
binary exactly like the reference and raises a clear error when no Hadoop
is installed (this environment has none), so fleet data tooling written
against the reference API ports unchanged.
"""
from __future__ import annotations

import os
import shutil
import subprocess
from typing import List, Optional

__all__ = ["LocalFS", "HDFSClient"]


class LocalFS:
    """reference io/fs.h localfs_* verbs."""

    def ls_dir(self, path: str) -> List[str]:
        return sorted(os.listdir(path)) if os.path.isdir(path) else []

    def is_exist(self, path: str) -> bool:
        return os.path.exists(path)

    def is_dir(self, path: str) -> bool:
        return os.path.isdir(path)

    def is_file(self, path: str) -> bool:
        return os.path.isfile(path)

    def mkdirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def delete(self, path: str) -> None:
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def mv(self, src: str, dst: str) -> None:
        shutil.move(src, dst)

    def upload(self, local: str, remote: str) -> None:
        shutil.copy(local, remote)

    def download(self, remote: str, local: str) -> None:
        shutil.copy(remote, local)

    def touch(self, path: str) -> None:
        open(path, "a").close()

    def cat(self, path: str) -> str:
        with open(path) as f:
            return f.read()


class HDFSClient:
    """reference incubate/fleet/utils/hdfs.py HDFSClient: every verb shells
    out to ``hadoop fs`` (the reference pipes the same commands through
    io/shell.h)."""

    def __init__(self, hadoop_home: Optional[str] = None, configs=None):
        self._hadoop = os.path.join(hadoop_home, "bin", "hadoop") \
            if hadoop_home else "hadoop"
        self._pre = []
        for k, v in (configs or {}).items():
            self._pre += ["-D", f"{k}={v}"]

    def _run(self, *args) -> str:
        cmd = [self._hadoop, "fs"] + self._pre + list(args)
        try:
            r = subprocess.run(cmd, capture_output=True, text=True)
        except FileNotFoundError:
            raise RuntimeError(
                f"hadoop binary not found ({self._hadoop}) — HDFSClient "
                f"needs a Hadoop installation (reference hdfs.py has the "
                f"same requirement); use LocalFS for local paths")
        if r.returncode != 0:
            raise RuntimeError(f"hadoop fs {' '.join(args)} failed: "
                               f"{r.stderr.strip()[:500]}")
        return r.stdout

    def ls_dir(self, path: str) -> List[str]:
        out = self._run("-ls", path)
        return [line.split()[-1] for line in out.splitlines()
                if line.startswith(("-", "d"))]

    def is_exist(self, path: str) -> bool:
        try:
            self._run("-test", "-e", path)
            return True
        except RuntimeError:
            return False

    def mkdirs(self, path: str) -> None:
        self._run("-mkdir", "-p", path)

    def delete(self, path: str) -> None:
        self._run("-rm", "-r", "-f", path)

    def upload(self, local: str, remote: str) -> None:
        self._run("-put", "-f", local, remote)

    def download(self, remote: str, local: str) -> None:
        self._run("-get", remote, local)
