from . import role_maker  # noqa: F401
