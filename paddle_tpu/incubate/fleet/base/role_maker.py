"""Role makers: who am I in the cluster?

Reference: python/paddle/fluid/incubate/fleet/base/role_maker.py
(:328 PaddleCloudRoleMaker reading the PADDLE_* env, :428 UserDefinedRoleMaker,
:111 MPIRoleMaker). The MPI variant has no TPU analogue — cluster membership
comes from the launcher env / jax.distributed, so PaddleCloud + UserDefined
cover the surface.
"""
from __future__ import annotations

import os
from typing import List, Optional

__all__ = ["Role", "RoleMakerBase", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker"]


class Role:
    WORKER = 1
    SERVER = 2  # accepted for API parity; there are no parameter servers


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER
        self._current_id = 0
        self._worker_endpoints: List[str] = []

    def generate_role(self):
        pass

    def is_worker(self) -> bool:
        return self._role == Role.WORKER

    def is_server(self) -> bool:
        return self._role == Role.SERVER

    def is_first_worker(self) -> bool:
        return self.is_worker() and self._current_id == 0

    def worker_index(self) -> int:
        return self._current_id

    def worker_num(self) -> int:
        return max(1, len(self._worker_endpoints)) if self._worker_endpoints \
            else 1

    def get_trainer_endpoints(self) -> List[str]:
        return list(self._worker_endpoints)


class PaddleCloudRoleMaker(RoleMakerBase):
    """Reads the launcher's PADDLE_* env contract (the same vars
    paddle_tpu.distributed.launch sets; reference role_maker.py:328)."""

    def __init__(self, is_collective: bool = True):
        super().__init__()
        self._is_collective = is_collective
        self._generated = False

    def generate_role(self):
        if self._generated:
            return
        self._current_id = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        eps = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
        self._worker_endpoints = [e for e in eps.split(",") if e]
        self._nranks = int(os.getenv("PADDLE_TRAINERS_NUM",
                                     str(max(1, len(self._worker_endpoints)))))
        self._role = Role.WORKER
        self._generated = True

    def worker_num(self) -> int:
        self.generate_role()
        return self._nranks


class UserDefinedRoleMaker(RoleMakerBase):
    """reference role_maker.py:428 — explicit role wiring for tests."""

    def __init__(self, current_id: int = 0, role: int = Role.WORKER,
                 worker_num: int = 1,
                 server_endpoints: Optional[List[str]] = None,
                 worker_endpoints: Optional[List[str]] = None):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._num = worker_num
        self._worker_endpoints = list(worker_endpoints or [])

    def worker_num(self) -> int:
        return self._num
