"""Fleet distributed-training API (reference incubate/fleet/)."""
from . import base  # noqa: F401
