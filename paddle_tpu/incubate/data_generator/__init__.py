"""DataGenerator — the MultiSlot training-data writer (reference
python/paddle/fluid/incubate/data_generator/__init__.py:21).

Role: users subclass it to turn raw text lines into the space-separated
``<ids_num> id1 id2 ...`` MultiSlot format that DatasetFactory /
``native/datafeed.cpp`` ingest, either streaming (stdin -> stdout, the MR
pipeline pattern) or from memory. Semantics mirror the reference: a float
feasign upgrades the slot's recorded type, batch mode buffers
``batch_size`` samples through ``generate_batch``.
"""
from __future__ import annotations

import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    """Base class (reference data_generator/__init__.py:21)."""

    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 1
        self._line_limit = None

    def _set_line_limit(self, line_limit):
        if not isinstance(line_limit, int) or line_limit < 1:
            raise ValueError("line_limit must be a positive int")
        self._line_limit = line_limit

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    def run_from_memory(self):
        """Generate data from memory: process samples yielded by
        ``generate_sample(None)``, batched through ``generate_batch``,
        write MultiSlot lines to stdout (reference :67)."""
        batch_samples = []
        line_iter = self.generate_sample(None)
        for user_parsed_line in line_iter():
            if user_parsed_line is None:
                continue
            batch_samples.append(user_parsed_line)
            if len(batch_samples) == self.batch_size_:
                batch_iter = self.generate_batch(batch_samples)
                for sample in batch_iter():
                    sys.stdout.write(self._gen_str(sample))
                batch_samples = []
        if batch_samples:
            batch_iter = self.generate_batch(batch_samples)
            for sample in batch_iter():
                sys.stdout.write(self._gen_str(sample))

    def run_from_stdin(self):
        """Process each stdin line through ``generate_sample`` (reference
        :101) — the Hadoop-streaming-style entry point."""
        batch_samples = []
        processed = 0
        for line in sys.stdin:
            if self._line_limit and processed >= self._line_limit:
                break
            processed += 1
            line_iter = self.generate_sample(line)
            for user_parsed_line in line_iter():
                if user_parsed_line is None:
                    continue
                batch_samples.append(user_parsed_line)
                if len(batch_samples) == self.batch_size_:
                    batch_iter = self.generate_batch(batch_samples)
                    for sample in batch_iter():
                        sys.stdout.write(self._gen_str(sample))
                    batch_samples = []
        if batch_samples:
            batch_iter = self.generate_batch(batch_samples)
            for sample in batch_iter():
                sys.stdout.write(self._gen_str(sample))

    def _gen_str(self, line):
        raise NotImplementedError(
            "use MultiSlotDataGenerator or MultiSlotStringDataGenerator")

    def generate_sample(self, line):
        raise NotImplementedError(
            "rewrite generate_sample to return a zero-arg generator "
            "yielding [(name, [feasign, ...]), ...]")

    def generate_batch(self, samples):
        def local_iter():
            for sample in samples:
                yield sample

        return local_iter


class MultiSlotDataGenerator(DataGenerator):
    """Writes ``<num> id...`` per slot; tracks per-slot types the way the
    reference does — a float feasign upgrades the slot from uint64 to
    float (reference :282 _gen_str)."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of process() must be in list or tuple type; "
                "example: [('words', [1926, 8, 17]), ('label', [1])]")
        out = []
        if self._proto_info is None:
            self._proto_info = []
            first = True
        else:
            first = False
            if len(line) != len(self._proto_info):
                raise ValueError(
                    f"the complete field set of two samples must be the "
                    f"same: got {len(line)} slots, expected "
                    f"{len(self._proto_info)}")
        for i, item in enumerate(line):
            name, elements = item
            if not isinstance(name, str):
                raise ValueError(f"name {type(name)} must be str")
            if not isinstance(elements, list):
                raise ValueError(f"elements {type(elements)} must be list")
            if not elements:
                raise ValueError(
                    f"slot {name!r} is empty — pad it in process()")
            if first:
                self._proto_info.append((name, "uint64"))
            else:
                if name != self._proto_info[i][0]:
                    raise ValueError(
                        f"the field name of two samples must match: "
                        f"{name} != {self._proto_info[i][0]}")
            out.append(str(len(elements)))
            for elem in elements:
                if isinstance(elem, bool):
                    # bool IS an int subclass — str() would emit the
                    # literal 'True' and corrupt the MultiSlot line
                    elem = int(elem)
                elif isinstance(elem, float):
                    self._proto_info[i] = (name, "float")
                elif not isinstance(elem, int):
                    raise ValueError(
                        f"the type of element {type(elem)} must be "
                        f"int or float")
                out.append(str(elem))
        return " ".join(out) + "\n"


class MultiSlotStringDataGenerator(DataGenerator):
    """String feasigns passthrough (later-reference variant): elements are
    written verbatim, no type tracking."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of process() must be in list or tuple type")
        out = []
        for name, elements in line:
            out.append(str(len(elements)))
            out.extend(str(e) for e in elements)
        return " ".join(out) + "\n"
