"""Dygraph LR schedulers (reference
python/paddle/fluid/dygraph/learning_rate_scheduler.py): the decay object
is passed as an optimizer's learning_rate; each evaluation returns the
current rate then advances step_num by step_size (the reference __call__
contract). Formulas mirror the static layers.learning_rate_scheduler
versions so the two modes cannot diverge."""
from __future__ import annotations

import math

__all__ = ["LearningRateDecay", "PiecewiseDecay", "NaturalExpDecay",
           "ExponentialDecay", "InverseTimeDecay", "PolynomialDecay",
           "CosineDecay", "NoamDecay"]


class LearningRateDecay:
    def __init__(self, begin=0, step=1, dtype="float32"):
        self.step_num = begin
        self.step_size = step
        self.dtype = dtype

    def __call__(self):
        lr = float(self.step())
        self.step_num += self.step_size
        return lr

    def step(self):
        raise NotImplementedError

    def create_lr_var(self, lr):  # reference API parity: eager mode floats
        return float(lr)


class PiecewiseDecay(LearningRateDecay):
    def __init__(self, boundaries, values, begin, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.boundaries = list(boundaries)
        self.values = list(values)

    def step(self):
        for i, b in enumerate(self.boundaries):
            if self.step_num < b:
                return self.values[i]
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def step(self):
        div = self.step_num / self.decay_steps
        if self.staircase:
            div = math.floor(div)
        return self.learning_rate * math.exp(-self.decay_rate * div)


class ExponentialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def step(self):
        div = self.step_num / self.decay_steps
        if self.staircase:
            div = math.floor(div)
        return self.learning_rate * (self.decay_rate ** div)


class InverseTimeDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def step(self):
        div = self.step_num / self.decay_steps
        if self.staircase:
            div = math.floor(div)
        return self.learning_rate / (1 + self.decay_rate * div)


class PolynomialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, end_learning_rate=0.0001,
                 power=1.0, cycle=False, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.end_learning_rate = end_learning_rate
        self.power = power
        self.cycle = cycle

    def step(self):
        tmp_step = self.step_num
        decay_steps = self.decay_steps
        if self.cycle:
            div = math.ceil(self.step_num / float(self.decay_steps))
            div = max(div, 1.0)
            decay_steps = self.decay_steps * div
        else:
            tmp_step = min(tmp_step, self.decay_steps)
        frac = (1 - tmp_step / decay_steps) ** self.power
        return ((self.learning_rate - self.end_learning_rate) * frac
                + self.end_learning_rate)


class CosineDecay(LearningRateDecay):
    def __init__(self, learning_rate, step_each_epoch, epochs, begin=0,
                 step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.step_each_epoch = step_each_epoch
        self.epochs = epochs

    def step(self):
        cur_epoch = math.floor(self.step_num / self.step_each_epoch)
        return self.learning_rate * 0.5 * (
            math.cos(cur_epoch * math.pi / self.epochs) + 1)


class NoamDecay(LearningRateDecay):
    def __init__(self, d_model, warmup_steps, begin=1, step=1,
                 dtype="float32"):
        super().__init__(begin, step, dtype)
        self.d_model = d_model
        self.warmup_steps = warmup_steps

    def step(self):
        a = self.step_num ** -0.5
        b = (self.warmup_steps ** -1.5) * self.step_num
        return (self.d_model ** -0.5) * min(a, b)
