"""Dygraph layer zoo (reference dygraph/nn.py:35-2762: Conv2D, FC,
BatchNorm, Embedding, LayerNorm, ...). Thin parameterized wrappers over the
eager op namespace; all math lives in the shared op registry."""
from __future__ import annotations

import numpy as np

from . import ops
from .base import VarBase
from .layers import Layer

__all__ = ["FC", "Linear", "Conv2D", "BatchNorm", "Embedding", "LayerNorm",
           "Pool2D", "Dropout", "GRUUnit", "NCE", "PRelu",
           "BilinearTensorProduct", "Conv2DTranspose", "GroupNorm",
           "SpectralNorm", "TreeConv", "RowConv", "SequenceConv"]


class FC(Layer):
    """reference dygraph/nn.py FC (input_dim explicit, as the later Linear)."""

    def __init__(self, input_dim, size, act=None, dtype="float32",
                 name_scope=None):
        super().__init__(name_scope or "fc", dtype)
        self.weight = self.create_parameter([int(input_dim), int(size)])
        self.bias = self.create_parameter([int(size)], is_bias=True)
        self._act = act

    def forward(self, x):
        out = ops.elementwise_add(ops.mul(x, self.weight), self.bias)
        return getattr(ops, self._act)(out) if self._act else out


Linear = FC


class Conv2D(Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, groups=1, act=None, use_bias=True,
                 dtype="float32", name_scope=None):
        super().__init__(name_scope or "conv2d", dtype)
        k = filter_size if isinstance(filter_size, (list, tuple)) \
            else (filter_size, filter_size)
        fan_in = num_channels * k[0] * k[1]
        fan_out = num_filters * k[0] * k[1]
        limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
        from .layers import _param_rng

        w = _param_rng().uniform(
            -limit, limit,
            (num_filters, num_channels // groups, k[0], k[1])
        ).astype(dtype)
        self.weight = self.create_parameter(w.shape, dtype, init=w)
        self.bias = self.create_parameter([num_filters], is_bias=True) \
            if use_bias else None
        self._attrs = {"strides": [stride] * 2 if np.isscalar(stride)
                       else list(stride),
                       "paddings": [padding] * 2 if np.isscalar(padding)
                       else list(padding),
                       "groups": groups}
        self._act = act

    def forward(self, x):
        out = ops.conv2d(x, self.weight, None, **self._attrs)
        if self.bias is not None:
            out = ops.elementwise_add(out, self.bias, axis=1)
        return getattr(ops, self._act)(out) if self._act else out


class BatchNorm(Layer):
    """Eager batch_norm: running stats are parameters updated in place from
    the op's MeanOut/VarianceOut outputs (the reference aliases them)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 dtype="float32", name_scope=None):
        super().__init__(name_scope or "batch_norm", dtype)
        self.weight = self.create_parameter([num_channels], init=1.0)
        self.bias = self.create_parameter([num_channels], is_bias=True)
        self._mean = self.create_parameter([num_channels], init=0.0,
                                           stop_gradient=True)
        self._variance = self.create_parameter([num_channels], init=1.0,
                                               stop_gradient=True)
        self._momentum = momentum
        self._epsilon = epsilon
        self._act = act

    def forward(self, x):
        y, mean_out, var_out, _, _ = ops.batch_norm(
            x, self.weight, self.bias, self._mean, self._variance,
            momentum=self._momentum, epsilon=self._epsilon,
            is_test=not self.training)
        if self.training:
            self._mean.set_value(mean_out.value)
            self._variance.set_value(var_out.value)
        return getattr(ops, self._act)(y) if self._act else y


class Embedding(Layer):
    def __init__(self, size, is_sparse=False, padding_idx=None,
                 dtype="float32", name_scope=None):
        super().__init__(name_scope or "embedding", dtype)
        self.weight = self.create_parameter(list(size))
        self._padding_idx = -1 if padding_idx is None else padding_idx

    def forward(self, ids):
        return ops.lookup_table(self.weight, ids,
                                padding_idx=self._padding_idx)


class LayerNorm(Layer):
    def __init__(self, normalized_shape, scale=True, shift=True,
                 epsilon=1e-5, act=None, dtype="float32", name_scope=None):
        super().__init__(name_scope or "layer_norm", dtype)
        if np.isscalar(normalized_shape):
            normalized_shape = [normalized_shape]
        n = int(np.prod(normalized_shape))
        self.weight = self.create_parameter([n], init=1.0) if scale else None
        self.bias = self.create_parameter([n], is_bias=True) if shift else None
        self._epsilon = epsilon
        self._act = act

    def forward(self, x):
        y, _, _ = ops.layer_norm(x, self.weight, self.bias,
                                 epsilon=self._epsilon,
                                 begin_norm_axis=len(x.shape) - 1)
        return getattr(ops, self._act)(y) if self._act else y


class Pool2D(Layer):
    def __init__(self, pool_size=2, pool_type="max", pool_stride=2,
                 pool_padding=0, global_pooling=False, name_scope=None):
        super().__init__(name_scope or "pool2d")
        self._attrs = {
            "pooling_type": pool_type,
            "ksize": [pool_size] * 2 if np.isscalar(pool_size)
            else list(pool_size),
            "strides": [pool_stride] * 2 if np.isscalar(pool_stride)
            else list(pool_stride),
            "paddings": [pool_padding] * 2 if np.isscalar(pool_padding)
            else list(pool_padding),
            "global_pooling": global_pooling,
        }

    def forward(self, x):
        return ops.pool2d(x, **self._attrs)


class Dropout(Layer):
    def __init__(self, p=0.5, name_scope=None):
        super().__init__(name_scope or "dropout")
        self._p = p

    def forward(self, x):
        r = ops.dropout(x, dropout_prob=self._p, is_test=not self.training)
        return r[0] if isinstance(r, tuple) else r  # drop the Mask output


class GRUUnit(Layer):
    """One GRU step (reference dygraph/nn.py:1509): gate input [B, 3H] is
    pre-projected; returns (gate, reset_hidden_prev, hidden)."""

    def __init__(self, size, activation="tanh", gate_activation="sigmoid",
                 origin_mode=False, dtype="float32", name_scope=None):
        super().__init__(name_scope or "gru_unit", dtype)
        h = size // 3
        self.weight = self.create_parameter([h, 3 * h])
        self.bias = self.create_parameter([1, 3 * h], is_bias=True)
        self._attrs = {"activation": activation,
                       "gate_activation": gate_activation,
                       "origin_mode": origin_mode}

    def forward(self, input, hidden_prev):
        return ops.gru_unit(input, hidden_prev, self.weight, self.bias,
                            **self._attrs)


class NCE(Layer):
    """Noise-contrastive estimation head (reference dygraph/nn.py:1684)."""

    def __init__(self, num_total_classes, dim, num_neg_samples=10,
                 sampler="uniform", seed=0, dtype="float32",
                 name_scope=None):
        super().__init__(name_scope or "nce", dtype)
        self.weight = self.create_parameter([num_total_classes, dim])
        self.bias = self.create_parameter([num_total_classes], is_bias=True)
        self._attrs = {
            "num_total_classes": int(num_total_classes),
            "num_neg_samples": int(num_neg_samples),
            "sampler": {"uniform": 0, "log_uniform": 1}[sampler],
            "seed": seed}

    def forward(self, input, label, sample_weight=None):
        cost, _, _ = ops.nce(input, label, self.weight, self.bias,
                             sample_weight, **self._attrs)
        return cost


class PRelu(Layer):
    """reference dygraph/nn.py PRelu: mode all/channel/element."""

    def __init__(self, mode="all", channel=None, input_shape=None,
                 dtype="float32", name_scope=None):
        super().__init__(name_scope or "prelu", dtype)
        if mode == "all":
            shape = [1]
        elif mode == "channel":
            shape = [int(channel)]
        elif mode == "element":
            shape = [int(np.prod(input_shape))]
        else:
            raise ValueError(f"prelu mode {mode!r}")
        self.weight = self.create_parameter(shape, init=0.25)
        self._mode = mode

    def forward(self, x):
        return ops.prelu(x, self.weight, mode=self._mode)


class BilinearTensorProduct(Layer):
    """out_k = x W_k y^T + b (reference dygraph/nn.py BilinearTensorProduct)."""

    def __init__(self, input1_dim, input2_dim, output_dim, dtype="float32",
                 name_scope=None):
        super().__init__(name_scope or "bilinear_tensor_product", dtype)
        self.weight = self.create_parameter(
            [output_dim, input1_dim, input2_dim])
        self.bias = self.create_parameter([1, output_dim], is_bias=True)

    def forward(self, x, y):
        return ops.bilinear_tensor_product(x, y, self.weight, self.bias)


class Conv2DTranspose(Layer):
    """reference dygraph/nn.py:2135."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, groups=1, act=None, use_bias=True,
                 dtype="float32", name_scope=None):
        super().__init__(name_scope or "conv2d_transpose", dtype)
        k = filter_size if isinstance(filter_size, (list, tuple)) \
            else (filter_size, filter_size)
        self.weight = self.create_parameter(
            [num_channels, num_filters // groups, k[0], k[1]])
        self.bias = self.create_parameter([num_filters], is_bias=True) \
            if use_bias else None
        self._attrs = {"strides": [stride] * 2 if np.isscalar(stride)
                       else list(stride),
                       "paddings": [padding] * 2 if np.isscalar(padding)
                       else list(padding),
                       "groups": groups}
        self._act = act

    def forward(self, x):
        out = ops.conv2d_transpose(x, self.weight, **self._attrs)
        if self.bias is not None:
            out = ops.elementwise_add(out, self.bias, axis=1)
        return getattr(ops, self._act)(out) if self._act else out


class GroupNorm(Layer):
    """reference dygraph/nn.py:2563."""

    def __init__(self, channels, groups, epsilon=1e-5, act=None,
                 dtype="float32", name_scope=None):
        super().__init__(name_scope or "group_norm", dtype)
        self.weight = self.create_parameter([channels], init=1.0)
        self.bias = self.create_parameter([channels], is_bias=True)
        self._attrs = {"groups": int(groups), "epsilon": epsilon}
        self._act = act

    def forward(self, x):
        y, _, _ = ops.group_norm(x, self.weight, self.bias, **self._attrs)
        return getattr(ops, self._act)(y) if self._act else y


class SpectralNorm(Layer):
    """reference dygraph/nn.py:2662: weight / sigma_max via power
    iteration. The U/V buffers persist on the layer; since the op is pure
    (see ops/misc.py spectral_norm), each call runs ``power_iters``
    iterations from the stored buffers."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32", name_scope=None):
        super().__init__(name_scope or "spectral_norm", dtype)
        h = int(weight_shape[dim])
        w = int(np.prod(weight_shape)) // h
        from .layers import _param_rng

        self._u = self.create_parameter(
            [h], init=_param_rng().randn(h).astype(dtype),
            stop_gradient=True)
        self._v = self.create_parameter(
            [w], init=_param_rng().randn(w).astype(dtype),
            stop_gradient=True)
        self._attrs = {"dim": int(dim), "power_iters": int(power_iters),
                       "eps": eps}

    def forward(self, weight):
        return ops.spectral_norm(weight, self._u, self._v, **self._attrs)


class TreeConv(Layer):
    """reference dygraph/nn.py:2762: tree-based convolution (TBCNN)."""

    def __init__(self, feature_size, output_size, num_filters=1, max_depth=2,
                 act="tanh", use_bias=False, dtype="float32",
                 name_scope=None):
        super().__init__(name_scope or "tree_conv", dtype)
        self.weight = self.create_parameter(
            [feature_size, 3, output_size, num_filters])
        self.bias = self.create_parameter([1, 1, output_size, num_filters],
                                          is_bias=True) if use_bias else None
        self._attrs = {"max_depth": int(max_depth)}
        self._act = act

    def forward(self, nodes_vector, edge_set):
        out = ops.tree_conv(nodes_vector, edge_set, self.weight,
                            **self._attrs)
        if self.bias is not None:
            out = ops.elementwise_add(out, self.bias)
        return getattr(ops, self._act)(out) if self._act else out


class RowConv(Layer):
    """Lookahead row convolution (reference dygraph/nn.py RowConv)."""

    def __init__(self, future_context_size, dim, act=None, dtype="float32",
                 name_scope=None):
        super().__init__(name_scope or "row_conv", dtype)
        self.weight = self.create_parameter(
            [future_context_size + 1, dim])
        self._act = act

    def forward(self, x):
        out = ops.row_conv(x, self.weight)
        return getattr(ops, self._act)(out) if self._act else out


class SequenceConv(Layer):
    """Context-window conv over padded sequences (reference dygraph/nn.py
    SequenceConv). The padded+lengths encoding needs explicit lengths."""

    def __init__(self, dim, num_filters, filter_size=3, filter_stride=1,
                 act=None, dtype="float32", name_scope=None):
        super().__init__(name_scope or "sequence_conv", dtype)
        self.weight = self.create_parameter(
            [filter_size * dim, num_filters])
        self._attrs = {"contextLength": int(filter_size),
                       "contextStart": -((filter_size - 1) // 2),
                       "contextStride": int(filter_stride)}
        self._act = act

    def forward(self, x, seq_len):
        out = ops.sequence_conv(x, self.weight, seq_len, **self._attrs)
        return getattr(ops, self._act)(out) if self._act else out
