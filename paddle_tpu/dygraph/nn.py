"""Dygraph layer zoo (reference dygraph/nn.py:35-2762: Conv2D, FC,
BatchNorm, Embedding, LayerNorm, ...). Thin parameterized wrappers over the
eager op namespace; all math lives in the shared op registry."""
from __future__ import annotations

import numpy as np

from . import ops
from .base import VarBase
from .layers import Layer

__all__ = ["FC", "Linear", "Conv2D", "BatchNorm", "Embedding", "LayerNorm",
           "Pool2D", "Dropout"]


class FC(Layer):
    """reference dygraph/nn.py FC (input_dim explicit, as the later Linear)."""

    def __init__(self, input_dim, size, act=None, dtype="float32",
                 name_scope=None):
        super().__init__(name_scope or "fc", dtype)
        self.weight = self.create_parameter([int(input_dim), int(size)])
        self.bias = self.create_parameter([int(size)], is_bias=True)
        self._act = act

    def forward(self, x):
        out = ops.elementwise_add(ops.mul(x, self.weight), self.bias)
        return getattr(ops, self._act)(out) if self._act else out


Linear = FC


class Conv2D(Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, groups=1, act=None, use_bias=True,
                 dtype="float32", name_scope=None):
        super().__init__(name_scope or "conv2d", dtype)
        k = filter_size if isinstance(filter_size, (list, tuple)) \
            else (filter_size, filter_size)
        fan_in = num_channels * k[0] * k[1]
        fan_out = num_filters * k[0] * k[1]
        limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
        from .layers import _param_rng

        w = _param_rng().uniform(
            -limit, limit,
            (num_filters, num_channels // groups, k[0], k[1])
        ).astype(dtype)
        self.weight = self.create_parameter(w.shape, dtype, init=w)
        self.bias = self.create_parameter([num_filters], is_bias=True) \
            if use_bias else None
        self._attrs = {"strides": [stride] * 2 if np.isscalar(stride)
                       else list(stride),
                       "paddings": [padding] * 2 if np.isscalar(padding)
                       else list(padding),
                       "groups": groups}
        self._act = act

    def forward(self, x):
        out = ops.conv2d(x, self.weight, None, **self._attrs)
        if self.bias is not None:
            out = ops.elementwise_add(out, self.bias, axis=1)
        return getattr(ops, self._act)(out) if self._act else out


class BatchNorm(Layer):
    """Eager batch_norm: running stats are parameters updated in place from
    the op's MeanOut/VarianceOut outputs (the reference aliases them)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 dtype="float32", name_scope=None):
        super().__init__(name_scope or "batch_norm", dtype)
        self.weight = self.create_parameter([num_channels], init=1.0)
        self.bias = self.create_parameter([num_channels], is_bias=True)
        self._mean = self.create_parameter([num_channels], init=0.0,
                                           stop_gradient=True)
        self._variance = self.create_parameter([num_channels], init=1.0,
                                               stop_gradient=True)
        self._momentum = momentum
        self._epsilon = epsilon
        self._act = act

    def forward(self, x):
        y, mean_out, var_out, _, _ = ops.batch_norm(
            x, self.weight, self.bias, self._mean, self._variance,
            momentum=self._momentum, epsilon=self._epsilon,
            is_test=not self.training)
        if self.training:
            self._mean.set_value(mean_out.value)
            self._variance.set_value(var_out.value)
        return getattr(ops, self._act)(y) if self._act else y


class Embedding(Layer):
    def __init__(self, size, is_sparse=False, padding_idx=None,
                 dtype="float32", name_scope=None):
        super().__init__(name_scope or "embedding", dtype)
        self.weight = self.create_parameter(list(size))
        self._padding_idx = -1 if padding_idx is None else padding_idx

    def forward(self, ids):
        return ops.lookup_table(self.weight, ids,
                                padding_idx=self._padding_idx)


class LayerNorm(Layer):
    def __init__(self, normalized_shape, scale=True, shift=True,
                 epsilon=1e-5, act=None, dtype="float32", name_scope=None):
        super().__init__(name_scope or "layer_norm", dtype)
        if np.isscalar(normalized_shape):
            normalized_shape = [normalized_shape]
        n = int(np.prod(normalized_shape))
        self.weight = self.create_parameter([n], init=1.0) if scale else None
        self.bias = self.create_parameter([n], is_bias=True) if shift else None
        self._epsilon = epsilon
        self._act = act

    def forward(self, x):
        y, _, _ = ops.layer_norm(x, self.weight, self.bias,
                                 epsilon=self._epsilon,
                                 begin_norm_axis=len(x.shape) - 1)
        return getattr(ops, self._act)(y) if self._act else y


class Pool2D(Layer):
    def __init__(self, pool_size=2, pool_type="max", pool_stride=2,
                 pool_padding=0, global_pooling=False, name_scope=None):
        super().__init__(name_scope or "pool2d")
        self._attrs = {
            "pooling_type": pool_type,
            "ksize": [pool_size] * 2 if np.isscalar(pool_size)
            else list(pool_size),
            "strides": [pool_stride] * 2 if np.isscalar(pool_stride)
            else list(pool_stride),
            "paddings": [pool_padding] * 2 if np.isscalar(pool_padding)
            else list(pool_padding),
            "global_pooling": global_pooling,
        }

    def forward(self, x):
        return ops.pool2d(x, **self._attrs)


class Dropout(Layer):
    def __init__(self, p=0.5, name_scope=None):
        super().__init__(name_scope or "dropout")
        self._p = p

    def forward(self, x):
        r = ops.dropout(x, dropout_prob=self._p, is_test=not self.training)
        return r[0] if isinstance(r, tuple) else r  # drop the Mask output
