"""Eager functional namespace over the whole op registry.

Any registered (non-control-flow) op is callable as
``dygraph.ops.<type>(*inputs, **attrs)`` — inputs map positionally onto the
op's input slots (lists allowed for duplicable slots), execution happens
immediately through the same lowering rule the compiled path uses, and the
call is recorded on the tape for backward(). Returns one VarBase when the
op has a single output value, else a tuple in schema order.

This replaces the reference's per-op dygraph dispatch (every layers.* fn
checking in_dygraph_mode and calling the C++ Tracer) with one generic door:
~150 ops become eager for free, and op semantics can't diverge between the
two modes.
"""
from __future__ import annotations

from typing import Any

from ..core import registry
from .base import VarBase, current_tape

__all__ = []  # populated dynamically via __getattr__


def _as_varbase(v):
    if v is None or isinstance(v, VarBase):
        return v
    return VarBase(v, stop_gradient=True)


def _call_op(op_type: str, *args, **attrs):
    opdef = registry.get_op_def(op_type)
    ins = {}
    specs = opdef.inputs
    if len(args) > len(specs):
        raise TypeError(
            f"{op_type}() takes at most {len(specs)} positional inputs "
            f"({[s.name for s in specs]}), got {len(args)}")
    for spec, arg in zip(specs, args):
        if arg is None:
            continue
        vals = list(arg) if isinstance(arg, (list, tuple)) else [arg]
        ins[spec.name] = [_as_varbase(v) for v in vals]
    # slot values may also arrive as keyword args (e.g. Label=...)
    for spec in specs[len(args):]:
        if spec.name in attrs:
            arg = attrs.pop(spec.name)
            if arg is None:
                continue
            vals = list(arg) if isinstance(arg, (list, tuple)) else [arg]
            ins[spec.name] = [_as_varbase(v) for v in vals]
    outs = current_tape().record(op_type, ins, attrs)
    flat = []
    for spec in opdef.outputs:
        for vb in outs.get(spec.name, []):
            if vb is not None:
                flat.append(vb)
    if not flat:
        return None
    return flat[0] if len(flat) == 1 else tuple(flat)


# user-facing names for ops registered under their versioned type
# (reference layers.reshape appends a reshape2 op, etc.)
_ALIASES = {"reshape": "reshape2", "transpose": "transpose2",
            "squeeze": "squeeze2", "unsqueeze": "unsqueeze2",
            "flatten": "flatten2"}


def __getattr__(name: str):
    op_type = _ALIASES.get(name, name)
    first_only = name in _ALIASES  # strip the versioned ops' dummy XShape
    if registry.has_op(op_type):
        def fn(*args, **attrs):
            r = _call_op(op_type, *args, **attrs)
            if first_only and isinstance(r, tuple):
                return r[0]
            return r

        fn.__name__ = name
        fn.__qualname__ = f"dygraph.ops.{name}"
        return fn
    raise AttributeError(f"no registered op '{name}'")
