"""Dygraph (eager) mode — reference paddle/fluid/imperative/ +
python/paddle/fluid/dygraph/. See base.py for the tape design."""
from .base import (VarBase, guard, to_variable, enabled,  # noqa: F401
                   in_dygraph_mode, current_tape)
from .checkpoint import load_dygraph, save_dygraph  # noqa: F401
from .layers import Layer  # noqa: F401
from .layers import seed_parameters  # noqa: F401
from .nn import (FC, NCE, BatchNorm, BilinearTensorProduct,  # noqa: F401
                 Conv2D, Conv2DTranspose, Dropout, Embedding, GroupNorm,
                 GRUUnit, LayerNorm, Linear, Pool2D, PRelu, RowConv,
                 SequenceConv, SpectralNorm, TreeConv)
from . import nn  # noqa: F401
from . import ops  # noqa: F401
from .learning_rate_scheduler import (CosineDecay,  # noqa: F401
                                      ExponentialDecay, InverseTimeDecay,
                                      LearningRateDecay, NaturalExpDecay,
                                      NoamDecay, PiecewiseDecay,
                                      PolynomialDecay)
from . import learning_rate_scheduler  # noqa: F401
from .parallel import DataParallel, ParallelEnv, prepare_context  # noqa: F401
