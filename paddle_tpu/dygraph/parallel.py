"""Dygraph DataParallel (reference python/paddle/fluid/dygraph/parallel.py:
Env :54, DataParallel :84 with apply_collective_grads :201 coalescing grads
and running an allreduce op + c_sync_comm_stream).

TPU-native: the per-grad NCCL allreduce becomes one host-coordinated mean
over ``jax.experimental.multihost_utils`` (ranks bootstrap through
distributed.init_parallel_env, the gen_nccl_id replacement). Single-process
use is a transparent passthrough, so the same script runs standalone or
under the launcher — the reference's pattern."""
from __future__ import annotations

import numpy as np

from ..distributed import ParallelEnv, init_parallel_env
from .layers import Layer

__all__ = ["DataParallel", "ParallelEnv", "prepare_context"]


def prepare_context(strategy=None):
    """reference dygraph/parallel.py prepare_context: bootstrap collectives
    from the PADDLE_* env."""
    return init_parallel_env()


class DataParallel(Layer):
    def __init__(self, layers: Layer, strategy=None):
        super().__init__("data_parallel")
        self._layers = layers
        self._env = ParallelEnv()

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    # -- reference surface -------------------------------------------------
    def scale_loss(self, loss):
        """The reference divides the loss by nranks before backward so the
        summed cross-rank grads average; here apply_collective_grads takes
        the mean directly, so this is identity (kept for API parity)."""
        return loss

    def apply_collective_grads(self):
        """Average every parameter gradient across ranks in ONE pytree
        collective (the reference :201 coalesces grads before its
        allreduce for the same reason: one launch, not N round-trips)."""
        import jax

        if jax.process_count() <= 1:
            return
        from ..distributed import allgather_mean_tree

        import jax.numpy as jnp

        # keyed by POSITION over all TRAINABLE parameters() — not just the
        # with-grad subset, whose membership can differ across ranks (a
        # conditional path or unused parameter on one rank would silently
        # misalign the averages). stop_gradient params (BatchNorm running
        # stats) never take part: giving them a zero grad would flip them
        # from frozen to optimizer-updated. Ranks where a trainable param
        # has no grad contribute zeros — the correct term for unused.
        params = [p for p in self._layers.parameters()
                  if not getattr(p, "stop_gradient", False)]
        if not any(p._grad is not None for p in params):
            return
        tree = allgather_mean_tree(
            {str(i): (p._grad if p._grad is not None
                      else jnp.zeros(p.shape, p.dtype))
             for i, p in enumerate(params)})
        # write back unconditionally (standard DDP semantics): a rank whose
        # conditional path skipped this parameter must still apply the same
        # averaged grad, or its copy diverges from the other ranks'.
        for i, p in enumerate(params):
            p._grad = tree[str(i)]

    # -- delegation --------------------------------------------------------
    def parameters(self):
        return self._layers.parameters()

    def named_parameters(self, prefix=""):
        return self._layers.named_parameters(prefix)

    def state_dict(self):
        return self._layers.state_dict()

    def set_dict(self, state):
        return self._layers.set_dict(state)

    load_dict = set_dict

    def clear_gradients(self):
        self._layers.clear_gradients()
