"""Layer: the dygraph module system (reference dygraph/layers.py:31).

Parameters are eager VarBases initialized at construction (no startup
program); sublayers register via attribute assignment, parameters() walks
the tree, state_dict()/set_dict() snapshot and restore values by
hierarchical name.
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from .base import VarBase

__all__ = ["Layer"]


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        self._full_name = name_scope or type(self).__name__.lower()
        self._dtype = dtype
        self._parameters: Dict[str, VarBase] = {}
        self._sub_layers: Dict[str, "Layer"] = {}
        self.training = True

    # -- construction -----------------------------------------------------
    def create_parameter(self, shape, dtype=None, init=None,
                         is_bias: bool = False,
                         stop_gradient: bool = False) -> VarBase:
        """init: None (Xavier for weights / zeros for bias), a float
        (constant), or a numpy array."""
        dtype = np.dtype(dtype or self._dtype)
        shape = tuple(int(s) for s in shape)
        if isinstance(init, np.ndarray):
            val = init.astype(dtype)
        elif init is not None:
            val = np.full(shape, float(init), dtype)
        elif is_bias:
            val = np.zeros(shape, dtype)
        else:
            fan_in = shape[0] if shape else 1
            fan_out = shape[1] if len(shape) > 1 else 1
            limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
            val = _param_rng().uniform(-limit, limit, shape).astype(dtype)
        vb = VarBase(val, stop_gradient=stop_gradient, persistable=True)
        return vb

    def add_parameter(self, name: str, param: VarBase) -> VarBase:
        self._parameters[name] = param
        param.name = f"{self._full_name}.{name}"
        return param

    def add_sublayer(self, name: str, layer: "Layer") -> "Layer":
        self._sub_layers[name] = layer
        return layer

    def __setattr__(self, name, value):
        if isinstance(value, VarBase) and value.persistable:
            self.__dict__.setdefault("_parameters", {})[name] = value
            value.name = f"{self.__dict__.get('_full_name', '?')}.{name}"
        elif isinstance(value, Layer):
            self.__dict__.setdefault("_sub_layers", {})[name] = value
        object.__setattr__(self, name, value)

    # -- inference/training mode ------------------------------------------
    def train(self):
        self.training = True
        for l in self._sub_layers.values():
            l.train()

    def eval(self):
        self.training = False
        for l in self._sub_layers.values():
            l.eval()

    # -- traversal ---------------------------------------------------------
    def named_parameters(self, prefix="") -> Iterator[Tuple[str, VarBase]]:
        for n, p in self._parameters.items():
            yield (f"{prefix}{n}", p)
        for ln, l in self._sub_layers.items():
            yield from l.named_parameters(prefix=f"{prefix}{ln}.")

    def parameters(self) -> List[VarBase]:
        return [p for _, p in self.named_parameters()]

    def sublayers(self) -> List["Layer"]:
        out = list(self._sub_layers.values())
        for l in self._sub_layers.values():
            out.extend(l.sublayers())
        return out

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    # -- state dicts (reference dygraph/checkpoint.py save_dygraph) --------
    def state_dict(self) -> Dict[str, np.ndarray]:
        return {n: p.numpy() for n, p in self.named_parameters()}

    def set_dict(self, state: Dict[str, np.ndarray]):
        own = dict(self.named_parameters())
        missing = set(own) - set(state)
        if missing:
            raise KeyError(f"state dict missing parameters: {sorted(missing)}")
        for n, p in own.items():
            arr = np.asarray(state[n])
            if tuple(arr.shape) != p.shape:
                raise ValueError(
                    f"parameter '{n}': saved shape {arr.shape} != {p.shape}")
            p.set_value(arr)

    load_dict = set_dict

    # -- call ---------------------------------------------------------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        return self.forward(*args, **kwargs)


_rng = None


def _param_rng() -> np.random.RandomState:
    global _rng
    if _rng is None:
        _rng = np.random.RandomState(0)
    return _rng


def seed_parameters(seed: int) -> None:
    """Reset the eager parameter-init RNG (fluid.default_startup_program().
    random_seed analogue for dygraph)."""
    global _rng
    _rng = np.random.RandomState(seed)
