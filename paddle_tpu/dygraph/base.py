"""Dygraph (eager) core: VarBase, the tape, guard, to_variable.

Reference: paddle/fluid/imperative/ — `VarBase` eager tensors with grad
twins (layer.h:55), `Tracer::TraceOp` running each kernel immediately while
wiring an autograd graph (tracer.h:39), and `BasicEngine` doing a reverse
dep-counted sweep on backward (engine.h:69).

TPU-native redesign: ops execute eagerly through the SAME registry lowering
rules the compiled path uses (one source of truth for op semantics), and the
tape records (opdef, input uids, attrs, output uids). ``backward()`` replays
the tape as a pure function of the leaf values under ``jax.grad`` — JAX is
the BasicEngine, the replay is the autograd graph, and the whole backward
can be jitted. RNG ops replay bit-identically because each entry's PRNG key
is derived from its tape position.
"""
from __future__ import annotations

import contextlib
import itertools
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import registry
from ..lowering import LowerCtx

__all__ = ["VarBase", "guard", "to_variable", "enabled", "in_dygraph_mode",
           "current_tape"]

_uid = itertools.count(1)
_tape: Optional["Tape"] = None


def in_dygraph_mode() -> bool:
    return _tape is not None


enabled = in_dygraph_mode


def current_tape() -> "Tape":
    if _tape is None:
        raise RuntimeError(
            "not in dygraph mode — wrap eager code in fluid.dygraph.guard()")
    return _tape


@contextlib.contextmanager
def guard(place=None, seed: int = 0):
    """reference dygraph/base.py:89 — enables eager execution inside."""
    global _tape
    old, _tape = _tape, Tape(seed=seed)
    try:
        yield
    finally:
        _tape = old


class VarBase:
    """Eager tensor (reference imperative/layer.h:55). Wraps a jax array;
    ``_grad`` is the grad twin, filled by backward()."""

    def __init__(self, value, name: Optional[str] = None,
                 stop_gradient: bool = False, persistable: bool = False):
        self.value = jnp.asarray(value)
        self.uid = next(_uid)
        self.name = name or f"eager_tmp_{self.uid}"
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self._grad: Optional[jax.Array] = None

    # -- reference VarBase surface ---------------------------------------
    @property
    def shape(self):
        return tuple(self.value.shape)

    @property
    def dtype(self):
        return self.value.dtype

    def numpy(self) -> np.ndarray:
        return np.asarray(self.value)

    def set_value(self, v) -> None:
        self.value = jnp.asarray(v)

    def detach(self) -> "VarBase":
        return VarBase(self.value, name=self.name + ".detached",
                       stop_gradient=True)

    def backward(self, retain_graph: bool = False) -> None:
        current_tape().backward(self, retain_graph=retain_graph)

    def gradient(self) -> Optional[np.ndarray]:
        return None if self._grad is None else np.asarray(self._grad)

    def clear_gradient(self) -> None:
        self._grad = None

    def astype(self, dtype):
        from . import ops

        return ops.cast(self, in_dtype=str(self.value.dtype),
                        out_dtype=dtype)

    def __repr__(self):
        return f"VarBase(name={self.name}, shape={self.shape}, " \
               f"dtype={self.dtype})"

    # -- arithmetic (reference math_op_patch for VarBase) ----------------
    def _binary(self, other, op, reverse=False):
        from . import ops

        if not isinstance(other, VarBase):
            # keep numpy/jnp promotion semantics (a float scalar promotes an
            # int tensor; forcing self.dtype would truncate it)
            other = VarBase(jnp.asarray(other), stop_gradient=True)
        a, b = (other, self) if reverse else (self, other)
        return getattr(ops, op)(a, b)

    def __add__(self, o):
        return self._binary(o, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "elementwise_sub")

    def __rsub__(self, o):
        return self._binary(o, "elementwise_sub", reverse=True)

    def __mul__(self, o):
        return self._binary(o, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "elementwise_div")

    def __rtruediv__(self, o):
        return self._binary(o, "elementwise_div", reverse=True)

    def __matmul__(self, o):
        return self._binary(o, "matmul")

    def __rmatmul__(self, o):
        return self._binary(o, "matmul", reverse=True)

    def __neg__(self):
        from . import ops

        return ops.scale(self, scale=-1.0)


class _TapeEntry:
    __slots__ = ("opdef", "ins", "attrs", "outs", "pos")

    def __init__(self, opdef, ins, attrs, outs, pos):
        self.opdef = opdef
        self.ins = ins      # {slot: [uid or None]}
        self.attrs = attrs
        self.outs = outs    # {slot: [uid]}
        self.pos = pos


class Tape:
    def __init__(self, seed: int = 0):
        self.entries: List[_TapeEntry] = []
        self.const_values: Dict[int, Any] = {}   # leaf/const uid -> value
        self.leaves: Dict[int, VarBase] = {}     # uid -> VarBase (leaf refs)
        self.produced: set = set()
        self.base_key = jax.random.key(seed)

    # -- tracing ---------------------------------------------------------
    def record(self, op_type: str, ins: Dict[str, List[Optional[VarBase]]],
               attrs: Dict[str, Any]) -> Dict[str, List[VarBase]]:
        """Execute one op eagerly and record it (Tracer::TraceOp)."""
        opdef = registry.get_op_def(op_type)
        if opdef.raw:
            raise RuntimeError(
                f"op '{op_type}' is a graph control-flow op; in dygraph "
                f"mode use ordinary Python control flow instead")
        full_attrs = {name: spec.default for name, spec in opdef.attrs.items()}
        full_attrs.update(attrs)
        pos = len(self.entries)
        in_uids: Dict[str, List[Optional[int]]] = {}
        in_vals: Dict[str, List[Any]] = {}
        for slot, vbs in ins.items():
            uids, vals = [], []
            for vb in vbs:
                if vb is None:
                    uids.append(None)
                    vals.append(None)
                    continue
                uids.append(vb.uid)
                vals.append(vb.value)
                if vb.uid not in self.produced and \
                        vb.uid not in self.const_values:
                    self.const_values[vb.uid] = vb.value
                    self.leaves[vb.uid] = vb
            in_uids[slot] = uids
            in_vals[slot] = vals

        ctx = LowerCtx(base_key=self.base_key, uid=pos)
        outs = opdef.lower(ctx, in_vals, full_attrs) or {}
        out_vbs: Dict[str, List[VarBase]] = {}
        out_uids: Dict[str, List[int]] = {}
        for slot, vals in outs.items():
            if not isinstance(vals, (list, tuple)):
                vals = [vals]
            vbs, uids = [], []
            for v in vals:
                vb = VarBase(v) if v is not None else None
                vbs.append(vb)
                uids.append(vb.uid if vb else None)
                if vb:
                    self.produced.add(vb.uid)
            out_vbs[slot] = vbs
            out_uids[slot] = uids
        self.entries.append(
            _TapeEntry(opdef, in_uids, full_attrs, out_uids, pos))
        return out_vbs

    # -- autograd (reference BasicEngine::Execute) -----------------------
    def _replay(self, target_uid: int, leaf_uids: List[int],
                entries: Optional[List["_TapeEntry"]] = None):
        """Build the pure function leaf_values -> scalar(target)."""
        entries = self.entries if entries is None else entries
        const = self.const_values
        base_key = self.base_key

        def fn(leaf_vals: List[Any]):
            env = dict(const)
            env.update(zip(leaf_uids, leaf_vals))
            for e in entries:
                ins = {slot: [env.get(u) if u is not None else None
                              for u in uids]
                       for slot, uids in e.ins.items()}
                ctx = LowerCtx(base_key=base_key, uid=e.pos)
                outs = e.opdef.lower(ctx, ins, e.attrs) or {}
                for slot, vals in outs.items():
                    if not isinstance(vals, (list, tuple)):
                        vals = [vals]
                    for u, v in zip(e.outs.get(slot, []), vals):
                        if u is not None and v is not None:
                            env[u] = v
            return jnp.sum(env[target_uid])

        return fn

    def backward(self, loss: VarBase, retain_graph: bool = False) -> None:
        if loss.uid not in self.produced:
            raise RuntimeError(
                f"backward() target {loss.name} was not produced on this "
                f"tape (created outside dygraph ops?)")
        # backward slice: only entries reachable from the loss replay, and
        # only leaves those entries read — unrelated parameters keep
        # gradient()==None instead of silently receiving zeros (and AdamW
        # weight decay never touches them)
        needed = {loss.uid}
        live_entries = []
        for e in reversed(self.entries):
            if any(u in needed for uids in e.outs.values() for u in uids):
                live_entries.append(e)
                needed.update(u for uids in e.ins.values()
                              for u in uids if u is not None)
        live_entries.reverse()
        leaf_uids = [u for u, vb in self.leaves.items()
                     if u in needed and not vb.stop_gradient
                     and jnp.issubdtype(vb.value.dtype, jnp.inexact)]
        if not leaf_uids:
            raise RuntimeError("backward(): no differentiable leaves found")
        fn = self._replay(loss.uid, leaf_uids, live_entries)
        leaf_vals = [self.leaves[u].value for u in leaf_uids]
        grads = jax.grad(fn)(leaf_vals)
        for u, g in zip(leaf_uids, grads):
            vb = self.leaves[u]
            # accumulate like the reference GradientAccumulator
            vb._grad = g if vb._grad is None else vb._grad + g
        if not retain_graph:
            self.reset()

    def reset(self) -> None:
        """Drop everything recorded. Parameters re-register as leaves on
        the next forward; grad accumulation across steps still works
        because grads live on the VarBase objects themselves (_grad)."""
        self.entries.clear()
        self.const_values.clear()
        self.leaves.clear()
        self.produced.clear()


def to_variable(value, name=None, zero_copy=None) -> VarBase:
    """reference dygraph/base.py:151."""
    if isinstance(value, VarBase):
        return value
    arr = np.asarray(value)
    return VarBase(jnp.asarray(arr), name=name, stop_gradient=True)
