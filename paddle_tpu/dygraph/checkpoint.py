"""save_dygraph / load_dygraph (reference dygraph/checkpoint.py): state
dicts as npz archives, matching the static path's npz checkpoint format."""
from __future__ import annotations

import os
from typing import Dict, Tuple

import numpy as np

__all__ = ["save_dygraph", "load_dygraph"]


def save_dygraph(state_dict: Dict[str, np.ndarray], model_path: str) -> None:
    path = model_path if model_path.endswith(".npz") else \
        model_path + ".pdparams.npz"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez(path, **{k: np.asarray(v) for k, v in state_dict.items()})


def load_dygraph(model_path: str) -> Tuple[Dict[str, np.ndarray], None]:
    path = model_path if model_path.endswith(".npz") else \
        model_path + ".pdparams.npz"
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    with np.load(path) as z:
        state = {k: z[k] for k in z.files}
    # second element is the optimizer state slot (reference returns a pair)
    return state, None
