"""FLAGS_* config shim (reference paddle/fluid/platform/flags.cc + the
``FLAGS_*`` env contract surfaced through core.init_gflags).

Flags resolve, in order: explicit ``set_flags`` > ``FLAGS_<name>`` env var >
default. Memory/allocator knobs from the reference are accepted for script
compatibility but inert — XLA owns device memory (documented per flag).
"""
from __future__ import annotations

import os
from typing import Any, Dict

__all__ = ["get_flags", "set_flags", "flag", "xla_options"]

# name -> (type, default, meaning)
_DEFS: Dict[str, tuple] = {
    # live flags
    "check_nan_inf": (bool, False,
                      "per-op finite checks with op provenance on failure "
                      "(reference flags.cc:44; operator.cc fast_check_nan_inf)"),
    "check_program": (int, 0,
                      "static-verification level (paddle_tpu.analysis): "
                      "0 off; 1 verify each program once before first "
                      "execution (error-severity findings raise "
                      "ProgramVerificationError with the op's build site); "
                      "2 additionally re-run verify_program after every "
                      "transform pass in a PassManager pipeline — a "
                      "transform introducing new errors is refused with "
                      "PassVerificationError naming the pass. See "
                      "docs/ANALYSIS.md. Level 1 is on by default in the "
                      "test suite via tests/conftest.py"),
    "monitor": (bool, True,
                "runtime metrics collection (paddle_tpu.monitor): executor "
                "counters/histograms, step hooks, recompilation diagnostics "
                "— docs/OBSERVABILITY.md. Off disables all collection"),
    "lock_witness": (bool, False,
                     "instrument the named framework locks "
                     "(monitor.lockwitness factories): per-thread "
                     "acquisition-order edges, wait/hold histograms and "
                     "runtime lock-order cycle detection, gated against "
                     "the static PT800 lock-order graph by "
                     "tools/load_check.py --fleet-chaos. Off: the "
                     "factories return plain threading primitives"),
    "numerics_witness": (bool, False,
                         "compile per-var numeric range taps into every "
                         "step (monitor.numwitness): jitted abs-max/min/"
                         "max + nonfinite counts per float op output, "
                         "merged host-side and cross-checked against the "
                         "numerics_check pass's static intervals by "
                         "tools/lint_numerics.py --witness. Off: steps "
                         "trace without taps (no hot-path cost)"),
    "log_compiles": (bool, False,
                     "log every executor compile (INFO) and recompile "
                     "(WARNING, with the changed cache-key component and "
                     "program build site) — the jax_log_compiles analogue "
                     "for the step cache"),
    "recompile_warn_threshold": (int, 3,
                                 "warn via logging once a single program "
                                 "has recompiled this many times, even "
                                 "without FLAGS_log_compiles (0 disables)"),
    "nan_inf_policy": (str, "raise",
                       "what a tripped FLAGS_check_nan_inf step does: "
                       "raise (FloatingPointError with op provenance), "
                       "skip (drop the step, roll state back bit-exactly; "
                       "nan_inf_max_consecutive_skips trips escalate), "
                       "zero_grad (skip without escalation — the zero-"
                       "gradient approximation). docs/RESILIENCE.md"),
    "nan_inf_max_consecutive_skips": (int, 5,
                                      "under nan_inf_policy=skip, this many "
                                      "consecutive dropped steps escalate "
                                      "to FloatingPointError (0 disables "
                                      "escalation)"),
    "fault_plan": (str, "",
                   "deterministic fault-injection schedule, e.g. "
                   "'compile:2:RuntimeError,ckpt_write:1:kill' "
                   "(paddle_tpu.resilience.faults; sites: compile, "
                   "device_put, step, ckpt_write, shard_write, hang, "
                   "device_lost; actions add 'hang' — an interruptible "
                   "stall the step watchdog must break). Empty disables"),
    "elastic": (bool, True,
                "elastic preemption-tolerant training "
                "(resilience.elastic): a typed DeviceLostError in a "
                "parallel contrib.Trainer run with a checkpoint config "
                "tears down the failed CompiledProgram, re-forms the "
                "mesh on the surviving devices, restores from the last "
                "verified checkpoint and fast-forwards the data cursor. "
                "Off: the DeviceLostError propagates (die typed). "
                "docs/RESILIENCE.md"),
    "elastic_max_rescales": (int, 8,
                             "elastic rescales allowed per Trainer.train "
                             "call before escalating with PT612 — "
                             "repeated device loss is an outage, not "
                             "churn"),
    "elastic_upscale_after_steps": (int, 0,
                                    "after this many consecutive healthy "
                                    "steps at reduced capacity, probe the "
                                    "device set and rescale BACK UP when "
                                    "capacity returned (no state restore "
                                    "— the live state re-shards onto the "
                                    "bigger mesh). 0 disables (default)"),
    "step_timeout_s": (float, 0.0,
                       "step watchdog (resilience.distributed): arm a "
                       "deadline around compile/step/collective sections; "
                       "on expiry all thread stacks + the active program "
                       "serial + the last recompile diagnosis are dumped "
                       "and the section raises WatchdogTimeout instead of "
                       "hanging CI forever. 0 disables (default). "
                       "docs/RESILIENCE.md"),
    "watchdog_hard_exit": (bool, True,
                           "after a watchdog expiry, if the hung section "
                           "is still armed one extra timeout later (stuck "
                           "in uninterruptible native code), os._exit(124)"
                           " with the diagnosis already on stderr — a "
                           "diagnosed fast failure beats a CI wall-clock "
                           "kill. Off: dump + raise only"),
    "replica_check_interval": (int, 0,
                               "every N-th data-parallel step, checksum "
                               "replicated params/optimizer state across "
                               "the dp axis (jitted reduce, no host "
                               "gather) and trip ReplicaDivergenceError "
                               "naming the first diverged param when "
                               "replicas disagree. 0 disables (default). "
                               "docs/RESILIENCE.md"),
    "replica_divergence_policy": (str, "raise",
                                  "what a detected cross-replica "
                                  "divergence does: raise "
                                  "(ReplicaDivergenceError), or restore "
                                  "(roll back to the last verified "
                                  "checkpoint via the registered recovery"
                                  " walk — contrib.Trainer wires it — "
                                  "and keep training; escalates to raise "
                                  "when nothing restorable exists)"),
    "trace": (bool, False,
              "structured span tracing (paddle_tpu.trace): request/step "
              "trace-ID propagation through serving, executor, trainer, "
              "retry and the resilience failure paths, feeding the "
              "flight recorder and the Chrome/JSONL exporters. Off "
              "(default) the hot paths pay one flag read and a no-op "
              "singleton — tools/trace_check.py gates the overhead. "
              "docs/OBSERVABILITY.md"),
    "trace_buffer_size": (int, 4096,
                          "finished spans kept in the bounded trace "
                          "collector (oldest evicted); exporters and "
                          "trace_tree read from this buffer"),
    "flight_recorder_size": (int, 256,
                             "spans kept in the flight-recorder ring "
                             "dumped into the diagnosis when a "
                             "WatchdogTimeout / DeviceLostError / "
                             "replica divergence / BatchFailed fires; "
                             "0 disables the recorder (incidents then "
                             "ship without span context — the "
                             "trace_check negative control)"),
    "device_peak_tflops": (float, 197.0,
                           "accelerator peak dense TF/s used for the "
                           "cost-model MFU gauges (default: v5e bf16 "
                           "peak; set per deployment). "
                           "docs/PERF_NOTES.md"),
    "ici_gbytes_per_s": (float, 100.0,
                         "effective per-chip interconnect bandwidth "
                         "(GB/s) for the predicted comms-vs-compute "
                         "ratio (analysis.cost_model.estimate_comms); "
                         "default a conservative v5e ICI figure — set "
                         "per deployment. docs/PERF_NOTES.md"),
    "fault_seed": (int, 0,
                   "seed for probabilistic fault-plan rules and retry "
                   "jitter — the same plan+seed replays identically"),
    "fault_stall_s": (float, 5.0,
                      "duration of the 'stall' data-plane wire fault "
                      "action (resilience.faults wire_connect/"
                      "wire_response/wire_stream sites): the injected "
                      "sleep that models a stalling-but-listening peer "
                      "the router's per-replica breaker must eject"),
    "retry_max_attempts": (int, 3,
                           "attempts (first try included) for transient "
                           "failures at the compile/device_put sites; 1 "
                           "disables retry"),
    "retry_base_delay": (float, 0.05,
                         "first backoff delay in seconds (doubles per "
                         "retry, seeded jitter on top)"),
    "retry_max_delay": (float, 2.0, "backoff delay ceiling in seconds"),
    "retry_timeout": (float, 30.0,
                      "per-site wall-clock retry budget in seconds across "
                      "all attempts (0 = unlimited)"),
    # serving (paddle_tpu.serving — docs/SERVING.md). ServingConfig reads
    # these as its defaults; explicit config fields win.
    "serving_max_batch": (int, 8,
                          "serving: largest padded batch per dispatch; "
                          "shape buckets are powers of two up to this, so "
                          "one compiled executable per bucket absorbs "
                          "arbitrary traffic"),
    "serving_queue_depth": (int, 256,
                            "serving admission control: queued requests "
                            "above this are rejected with typed Overloaded "
                            "(load shedding, never a silent drop)"),
    "serving_queue_age_s": (float, 5.0,
                            "serving admission control: when the OLDEST "
                            "queued request is older than this, new "
                            "arrivals are shed as Overloaded — queue-age "
                            "pressure catches a stuck device before the "
                            "depth bound does (0 disables)"),
    "serving_deadline_s": (float, 0.0,
                           "default per-request deadline in seconds "
                           "(resilience.deadline); an expired request gets "
                           "typed DeadlineExceeded instead of a stale "
                           "response. 0 = no default; submit(deadline_s=) "
                           "overrides per request"),
    "serving_batch_window_s": (float, 0.0,
                               "how long the dispatcher waits for a "
                               "partially-filled batch to fill before "
                               "dispatching it anyway (0 = dispatch "
                               "whatever is queued — lowest latency)"),
    "serving_breaker_threshold": (int, 3,
                                  "consecutive batch failures that OPEN a "
                                  "shape bucket's circuit breaker (requests "
                                  "for that bucket are then rejected "
                                  "CircuitOpen until a half-open probe "
                                  "succeeds)"),
    "serving_breaker_cooldown_s": (float, 0.5,
                                   "base open->half-open cooldown; each "
                                   "re-open backs off through the "
                                   "resilience.retry schedule (doubling, "
                                   "capped) instead of hammering a broken "
                                   "bucket"),
    "serving_degrade_after_s": (float, 1.0,
                                "sustained overload pressure for this long "
                                "enters degraded mode: max batch halves "
                                "and sub-priority requests are shed "
                                "(docs/SERVING.md)"),
    "serving_recover_after_s": (float, 1.0,
                                "pressure-free time before degraded mode "
                                "restores the full batch ceiling"),
    "serving_degraded_min_priority": (int, 1,
                                      "in degraded mode, requests with "
                                      "priority below this are shed at "
                                      "admission with typed Overloaded"),
    "serving_bisect_depth": (int, 0,
                             "poison-request isolation (docs/SERVING.md): "
                             "when a batch fails with a state-safe error, "
                             "re-dispatch it as bisected halves up to this "
                             "depth until the culprit request is isolated "
                             "— innocents complete with correct results, "
                             "the culprit settles typed PoisonRequest and "
                             "its feed fingerprint is quarantined. 0 "
                             "disables (default): the whole batch fails "
                             "typed BatchFailed as before. Failures that "
                             "may have corrupted device state (watchdog "
                             "timeout, device loss, consumed donated "
                             "buffers) always fail the whole batch"),
    "serving_bisect_quarantine": (int, 64,
                                  "bounded count of poison feed "
                                  "fingerprints remembered per engine; a "
                                  "quarantined fingerprint is shed at "
                                  "admission (typed Overloaded, reason "
                                  "poison_quarantine) instead of failing "
                                  "another batch. Oldest evicted"),
    "serving_slo_latency_s": (str, "batch:30,standard:1.0,interactive:0.25",
                              "per-priority-class latency objective for "
                              "the SLO burn-rate tracker (serving/slo.py; "
                              "docs/SERVING.md 'SLO burn rate'): "
                              "'class:seconds' pairs, comma-separated. A "
                              "completed request slower than its class "
                              "target, or any non-completed terminal "
                              "outcome, consumes error budget"),
    "serving_slo_error_budget": (float, 0.01,
                                 "allowed bad-request fraction of the SLO "
                                 "objective; burn rate = observed bad "
                                 "fraction / this budget (1.0 = burning "
                                 "exactly at budget)"),
    "serving_slo_fast_window_s": (float, 60.0,
                                  "fast burn-rate window in seconds (the "
                                  "page-now signal of the multi-window "
                                  "burn alert)"),
    "serving_slo_slow_window_s": (float, 600.0,
                                  "slow burn-rate window in seconds (the "
                                  "sustained-burn confirmation window)"),
    # per-tenant quotas + weighted fair share (serving/engine.py;
    # docs/SERVING.md 'Fleet control loop'). ServingConfig reads these as
    # its defaults; explicit config fields win.
    "serving_tenant_fair_share": (bool, False,
                                  "per-tenant admission fairness: a tenant "
                                  "holding more than its queue quota is "
                                  "shed typed Overloaded(reason="
                                  "tenant_quota), and the dispatcher picks "
                                  "batches by weighted fair queueing "
                                  "(DWRR-equivalent stride scheduling) "
                                  "instead of strict FIFO. Off (default): "
                                  "admission and dispatch behave exactly "
                                  "as before"),
    "serving_tenant_weights": (str, "",
                               "'tenant:weight,...' fair-share weights "
                               "(e.g. 'acme:3,globex:1'); unlisted "
                               "tenants get weight 1. A tenant's queue "
                               "quota and dispatch share scale with its "
                               "weight"),
    "serving_tenant_quota_frac": (float, 0.5,
                                  "largest fraction of serving_queue_depth "
                                  "one weight-1 tenant may occupy before "
                                  "its NEW arrivals are shed typed "
                                  "Overloaded(reason=tenant_quota); a "
                                  "tenant with weight w gets w times this "
                                  "share (capped at the whole queue)"),
    # fleet autoscaler (serving/fleet/autoscaler.py; docs/SERVING.md
    # 'Fleet control loop'). AutoscalerConfig reads these as defaults.
    "serving_autoscale_min_replicas": (int, 1,
                                       "autoscaler floor: scale-in below "
                                       "this many replicas is refused "
                                       "typed at_min_replicas"),
    "serving_autoscale_max_replicas": (int, 4,
                                       "autoscaler ceiling: scale-out "
                                       "above this many replicas is "
                                       "refused typed at_max_replicas"),
    "serving_autoscale_interval_s": (float, 1.0,
                                     "autoscaler control-loop tick "
                                     "interval in seconds"),
    "serving_autoscale_cooldown_s": (float, 30.0,
                                     "minimum seconds between two scale "
                                     "actions (and from a drain start to "
                                     "the next action): decisions inside "
                                     "it are refused typed cooldown — the "
                                     "anti-flap half of the hysteresis"),
    "serving_autoscale_hot_sustain_s": (float, 5.0,
                                        "burn/pressure must be observed "
                                        "continuously for this long "
                                        "before a scale-out fires (one "
                                        "bad tick never scales)"),
    "serving_autoscale_calm_sustain_s": (float, 30.0,
                                         "the fleet must be calm (no "
                                         "burn, no pressure) continuously "
                                         "for this long before a drain-"
                                         "based scale-in fires"),
    "serving_autoscale_max_inflight_spawns": (int, 1,
                                              "spawns not yet ready the "
                                              "autoscaler may have in "
                                              "flight; further scale-outs "
                                              "are refused typed "
                                              "spawn_budget_spent"),
    "serving_autoscale_queue_high": (int, 8,
                                     "per-replica queue depth the "
                                     "autoscaler counts as pressure "
                                     "(alongside degraded mode and open "
                                     "breaker buckets)"),
    # fleet telemetry plane (serving/fleet/telemetry.py;
    # docs/OBSERVABILITY.md 'Fleet telemetry plane')
    "fleet_telemetry": (bool, False,
                        "fleet telemetry plane: when on, request-latency "
                        "observations carry trace-id exemplars into the "
                        "JSON /metrics form and FleetAggregator.start() "
                        "runs its scrape thread. Off (default) is a "
                        "hot-path no-op: no exemplar allocation, no "
                        "scrape thread"),
    "fleet_scrape_interval_s": (float, 1.0,
                                "FleetAggregator scrape interval in "
                                "seconds (per-replica GET /metrics)"),
    "auto_recompute": (bool, False,
                       "automatic rematerialisation: on Executor.run / "
                       "run_chained / CompiledProgram, training programs "
                       "are segmented at layer boundaries and gradient-"
                       "checkpointed (analysis/remat.py Pass 6), with the "
                       "checkpoint set chosen by Program.memory_plan() "
                       "scoring. Transformed programs get their own serial "
                       "so compile caches never alias remat and plain "
                       "variants. docs/PERF_NOTES.md"),
    "remat_budget_mb": (int, 0,
                        "peak-memory target for FLAGS_auto_recompute in "
                        "MiB: the cheapest checkpoint set (fewest "
                        "recomputed ops) whose PREDICTED peak fits is "
                        "chosen; 0 = no budget, sqrt(N) segmentation"),
    "epilogue_fusion": (bool, False,
                        "GEMM-epilogue fusion (analysis/epilogue_fusion.py, "
                        "registered transform pass): rewrite mul/matmul -> "
                        "bias-add -> activation -> residual -> layer_norm "
                        "chains in forward-only programs into the "
                        "fused_gemm_epilogue op, gated by a fidelity "
                        "witness (unfusable or witness-failing programs "
                        "refuse and run untransformed — never a wrong "
                        "program). Fused programs get their own serial so "
                        "compile caches never alias fused and plain "
                        "variants. docs/PERF_NOTES.md"),
    "use_fused_gemm": (str, "auto",
                       "fused_gemm_epilogue path: auto (Pallas kernel on "
                       "TPU when the tiling fits, dense replay of the "
                       "original op rules elsewhere), always (force "
                       "kernel; interpret mode off-TPU — slow, tests "
                       "only; unsupported tilings raise instead of "
                       "silently falling back), never (dense replay)"),
    "fused_gemm_blocks": (str, "",
                          "kernel block sizes for fused_gemm_epilogue as "
                          "'m,n,k' (e.g. '128,128,128'); empty defers to "
                          "the autotuner's best-known config "
                          "(FLAGS_autotune=use|measure) and then the "
                          "(128,128,128) default. Part of the compile-"
                          "cache key"),
    "autotune": (str, "off",
                 "persistent autotuner (paddle_tpu.tuning): off (no DB "
                 "access), use (best-known FLAGS_xla_options / fused-"
                 "kernel block sizes from the cost database feed the "
                 "executor compile path automatically; explicit flags "
                 "still win), measure (use + the measure loop may run "
                 "trials and record them). docs/PERF_NOTES.md"),
    "aot_cache_dir": (str, "",
                      "warm-start AOT executable cache directory "
                      "(paddle_tpu.aot_cache): after every successful "
                      "XLA compile the executable is serialized here, "
                      "and later processes load instead of compiling — "
                      "a cold serving replica joins the fleet warm. "
                      "Keyed by program CONTENT fingerprint + arg "
                      "signature + compiler config + backend/versions; "
                      "corrupt or version-mismatched entries degrade to "
                      "a recompile with one warning. Empty disables "
                      "(default). docs/SERVING.md"),
    "autotune_db": (str, "",
                    "path of the autotuner cost database (JSON, atomic "
                    "rewrite); empty = ~/.cache/paddle_tpu/"
                    "autotune_db.json. Keyed by (program content "
                    "fingerprint, shape bucket, backend); entries from a "
                    "different framework/jax version are ignored"),
    "xla_options": (str, "",
                    "XLA compiler options forwarded to jax.jit("
                    "compiler_options=...) on every executor compile; "
                    "JSON object or comma-separated k=v pairs, e.g. "
                    "'{\"xla_tpu_enable_latency_hiding_scheduler\": true}' "
                    "or 'xla_cpu_enable_fast_min_max=true'. Part of the "
                    "compile-cache key; sweep with tools/xla_sweep.py"),
    "paddle_num_threads": (int, 1, "host threads hint (XLA owns scheduling)"),
    "seq_bucket_sizes": (str, "", "override DataFeeder varlen buckets, csv"),
    "conv_use_nhwc": (str, "auto",
                      "conv/pool inner layout: auto (NHWC on TPU — channels "
                      "ride the 128-lane dim; boundary transposes cancel "
                      "between layers), always, never (NCHW as the "
                      "reference)"),
    "use_flash_attention": (str, "auto",
                            "fused_multihead_attention path: auto (Pallas "
                            "kernel on TPU, primitives elsewhere), always "
                            "(force kernel; interpret mode off-TPU — slow, "
                            "tests only), never"),
    # accepted-for-compat, inert on TPU (XLA/PJRT owns memory)
    "fraction_of_gpu_memory_to_use": (float, 0.92, "inert: XLA preallocates"),
    "allocator_strategy": (str, "auto_growth", "inert: XLA buffer assignment"),
    "eager_delete_tensor_gb": (float, 0.0, "inert: no GC, donation instead"),
    "memory_fraction_of_eager_deletion": (float, 1.0, "inert"),
    "init_allocated_mem": (bool, False, "inert"),
    "selected_gpus": (str, "", "inert: device choice is Place/mesh-driven"),
    "selected_tpus": (str, "", "device index hint for TPUPlace"),
    "cudnn_deterministic": (bool, False, "inert: XLA is deterministic"),
}

_overrides: Dict[str, Any] = {}

# bumped on every set_flags call: cheap change-detection for hot-path
# callers that memoize a flag value (paddle_tpu.trace.enabled caches
# FLAGS_trace against this, so the disabled tracing path costs an int
# compare instead of an env read per span). Env-var mutations AFTER the
# first read are not observed — the documented gflags-style contract.
_set_epoch = 0


def _coerce(typ, raw):
    if typ is bool:
        if isinstance(raw, (int, float, bool)):
            return bool(raw)  # gflags semantics: nonzero is true
        s = str(raw).strip().lower()
        if s in ("1", "true", "yes", "on"):
            return True
        if s in ("0", "false", "no", "off", ""):
            return False
        raise ValueError(f"not a boolean flag value: {raw!r}")
    return typ(raw)


def flag(name: str):
    """Current value of one flag."""
    if name not in _DEFS:
        raise KeyError(f"unknown flag '{name}' — known: {sorted(_DEFS)}")
    if name in _overrides:
        return _overrides[name]
    typ, default, _ = _DEFS[name]
    raw = os.environ.get(f"FLAGS_{name}")
    return default if raw is None else _coerce(typ, raw)


def get_flags(names=None) -> Dict[str, Any]:
    """reference fluid.get_flags."""
    if names is None:
        names = list(_DEFS)
    if isinstance(names, str):
        names = [names]
    return {f"FLAGS_{n}": flag(n) for n in (x.replace("FLAGS_", "")
                                            for x in names)}


def _parse_option_value(s: str):
    t = s.strip()
    low = t.lower()
    if low in ("true", "false"):
        return low == "true"
    for conv in (int, float):
        try:
            return conv(t)
        except ValueError:
            pass
    return t


# raw flag string -> parsed dict; the executor consults xla_options() on
# every dispatch to build cache keys, so parsing must not be per-step work
_xla_options_memo: Dict[str, Dict[str, Any]] = {}


def xla_options() -> Dict[str, Any]:
    """``FLAGS_xla_options`` parsed to the dict handed to
    ``jax.jit(compiler_options=...)``: a JSON object, or comma-separated
    ``k=v`` pairs with true/false/number coercion. The executor folds
    ``sorted(items())`` into every compile-cache key, so flipping options
    recompiles instead of silently reusing the old executable. Parses are
    memoized on the raw string (callers must not mutate the result)."""
    raw = str(flag("xla_options")).strip()
    cached = _xla_options_memo.get(raw)
    if cached is not None:
        return cached
    _xla_options_memo[raw] = opts = _parse_xla_options(raw)
    return opts


def _parse_xla_options(raw: str) -> Dict[str, Any]:
    if not raw:
        return {}
    if raw.startswith("{"):
        import json

        opts = json.loads(raw)
        if not isinstance(opts, dict):
            raise ValueError(
                f"FLAGS_xla_options JSON must be an object, got {opts!r}")
        return opts
    out: Dict[str, Any] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"FLAGS_xla_options entry {part!r} is not k=v "
                f"(or pass a JSON object)")
        k, v = part.split("=", 1)
        out[k.strip()] = _parse_option_value(v)
    return out


def set_flags(flags_dict: Dict[str, Any]) -> None:
    """reference fluid.set_flags({'FLAGS_check_nan_inf': 1})."""
    global _set_epoch
    for k, v in flags_dict.items():
        name = k.replace("FLAGS_", "")
        if name not in _DEFS:
            raise KeyError(f"unknown flag '{k}' — known: "
                           f"{sorted('FLAGS_' + n for n in _DEFS)}")
        typ = _DEFS[name][0]
        _overrides[name] = _coerce(typ, v)
    _set_epoch += 1
