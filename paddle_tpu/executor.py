"""Scope + Executor: run programs as compiled XLA executables.

Reference: paddle/fluid/framework/executor.cc (per-op interpreter) and
python/paddle/fluid/executor.py:380 (Executor.run API). The rebuild keeps the
``exe.run(program, feed=..., fetch_list=...)`` contract but the execution model
is inverted: instead of dispatching 1 kernel per op per step, the whole block
is traced once into jax, jit-compiled, and cached keyed on (program version,
feed signature). Per step, the only Python work is a dict lookup + arg packing.

State threading: persistable vars live in a ``Scope`` as jax device arrays.
The compiled step function takes (feeds, state, rng_key) and returns
(fetches, new_state); state buffers PROVEN safe by the static liveness pass
(``analysis.liveness.safe_donation_set`` — every read precedes the last
write, var not fetched) are donated so XLA updates parameters in place —
the role of the reference's buffer-reuse/inplace passes
(ir/memory_optimize_pass/) is played by liveness-gated donation + XLA
buffer assignment.
"""
from __future__ import annotations

import contextlib
import itertools
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import monitor as _monitor
from . import trace as _trace
from .core.types import np_dtype
from .framework import OpRole, Program, Variable, default_main_program
from .lowering import LowerCtx, lower_block, lower_op
from .profiler import RecordEvent
from .resilience import distributed as _dist
from .resilience import faults as _faults
from .resilience import nonfinite as _nonfinite
from .resilience.retry import RetryExhaustedError, call_with_retry

__all__ = ["Executor", "Scope", "global_scope", "scope_guard", "CPUPlace",
           "TPUPlace", "CUDAPlace"]


# ---------------------------------------------------------------------------
# Places (reference: paddle/fluid/platform/place.h). CUDAPlace is accepted as
# an alias for TPUPlace so reference scripts run unmodified.
# ---------------------------------------------------------------------------

class Place:
    def __repr__(self):
        return type(self).__name__ + "()"


class CPUPlace(Place):
    def jax_device(self):
        # local, not global: under multi-process the global list includes
        # other trainers' devices, which are not addressable here. backend=
        # "cpu" because plain local_devices() lists only the default backend
        # (on a TPU host that would silently hand back the TPU).
        try:
            return jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            return jax.local_devices()[0]


class TPUPlace(Place):
    def __init__(self, device_id: int = 0):
        self.device_id = device_id

    def jax_device(self):
        try:
            devs = [d for d in jax.local_devices() if d.platform != "cpu"]
            if devs:
                return devs[self.device_id % len(devs)]
        except RuntimeError:
            pass
        return jax.local_devices()[0]


class CUDAPlace(TPUPlace):
    """Compat alias: reference scripts that say CUDAPlace(0) get the TPU."""


class Scope:
    """name -> device array store (reference: paddle/fluid/framework/scope.h).

    Flat rather than hierarchical: block-local temporaries never materialise
    (they are XLA intermediates), so only persistables and feeds live here.
    """

    # monotonic identity for executor cache keys: id(scope) can alias after
    # GC, silently handing a fresh Scope another scope's compiled step
    _serial_counter = itertools.count()

    def __init__(self, parent: Optional["Scope"] = None):
        self.vars: Dict[str, Any] = {}
        self.parent = parent
        self._serial = next(Scope._serial_counter)
        # serving dispatches from its own thread while user code may keep
        # running the same executor: the var map is lock-guarded so a
        # concurrent set_var can never tear a read (CPython dicts are
        # GIL-atomic per op, but read-modify-write sequences are not)
        self._lock = _monitor.make_rlock("Scope._lock")

    def var(self, name: str):
        with self._lock:
            return self.vars.get(name)

    def find_var(self, name: str):
        s = self
        while s is not None:
            with s._lock:
                if name in s.vars:
                    return s.vars[name]
            s = s.parent
        return None

    def set_var(self, name: str, value) -> None:
        with self._lock:
            self.vars[name] = value

    def drop_var(self, name: str) -> None:
        with self._lock:
            self.vars.pop(name, None)

    def new_scope(self) -> "Scope":
        return Scope(parent=self)

    def numpy(self, name: str) -> np.ndarray:
        v = self.find_var(name)
        return None if v is None else np.asarray(v)


def _shape_dtype_sig(v):
    """(shape, dtype) of a feed WITHOUT materializing it: np.asarray on a
    device-resident jax array forces a full device->host transfer — through
    the axon tunnel that turned each cached-step lookup into a ~77 MB pull
    per run (measured 4.3 s/step on the resnet bench feed)."""
    if hasattr(v, "shape") and hasattr(v, "dtype"):
        return (tuple(v.shape), str(v.dtype))
    a = np.asarray(v)
    return (tuple(a.shape), str(a.dtype))


def _feed_host_bytes(v) -> int:
    """Bytes a feed will move host->device, 0 for device-resident arrays.
    Never calls np.asarray on a jax array (that WOULD be the transfer)."""
    if isinstance(v, np.ndarray):
        return int(v.nbytes)
    if hasattr(v, "devices") or hasattr(v, "device_buffer"):
        return 0  # jax array: already on (some) device
    try:
        return int(np.asarray(v).nbytes)
    except Exception:
        return 0


def _live_bytes(vals) -> int:
    return sum(int(getattr(v, "nbytes", 0) or 0) for v in vals)


def _feed_batch_rows(feed) -> int:
    """Leading feed dim (the cost-model batch); no host transfer."""
    batch = 1
    for v in (feed or {}).values():
        shape, _ = _shape_dtype_sig(v)
        if shape:
            batch = max(batch, int(shape[0]))
    return batch


def _has_nonfinite(v) -> bool:
    """Host-side coarse finite check (run_chained's FLAGS_check_nan_inf —
    a device->host pull per state var, only when the flag is on)."""
    a = np.asarray(v)
    return a.dtype.kind in "fc" and not np.isfinite(a).all()


def _own_donated(vals):
    """Donated step inputs must be jax Arrays the executor OWNS. A host
    numpy array (e.g. a param the user planted with scope.set_var) can be
    zero-copy-aliased by the runtime when alignment allows; donating that
    aliased buffer lets XLA write the step's output INTO the user's array.
    jit dispatch quietly skips donation for non-Array args; the AOT
    executables used since the monitor PR do not, so copy once here — the
    same host->device copy jit would have made."""
    return [v if isinstance(v, jax.Array) else jnp.array(v) for v in vals]


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope: Scope):
    global _global_scope
    old, _global_scope = _global_scope, scope
    try:
        yield
    finally:
        _global_scope = old


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------

class _CompiledStep:
    """One jitted executable for (program, feed signature, fetch list)."""

    def __init__(self, fn, feed_names, donated_names, ro_names,
                 state_out_names, fetch_names):
        self.fn = fn
        self.feed_names = feed_names
        # donated: scope vars both read and re-written whose old buffer is
        # PROVEN dead after the step (analysis.liveness.safe_donation_set);
        # donated so XLA updates in place. ro: every other scope input —
        # read-only vars and donation-unsafe state (e.g. a fetched param);
        # never donated, updates still flow back via state_out.
        self.donated_names = donated_names
        self.ro_names = ro_names
        self.state_out_names = state_out_names
        self.fetch_names = fetch_names
        # ref set by the cache owner. Cache keys use program._serial (never
        # recycled), so this is no longer needed to prevent id() aliasing —
        # it is kept for debugging: step.program names the compiled source
        self.program = None
        # state_out vars that are read but NOT donated (donation-unsafe,
        # e.g. a fetched param): their old buffer is copied, not reused
        self.kept_names: List[str] = []
        # AOT executable: None = not yet lowered, False = AOT unavailable
        # (fall back to jit dispatch), else the jax Compiled object. Set by
        # Executor._ensure_executable on the first call so trace+lower and
        # XLA-compile are timed as separate monitor stages.
        self._aot = None
        # pending monitor CompileRecord awaiting stage timings
        self._compile_event = None
        # durable-identity material for the warm-start executable cache
        # (FLAGS_aot_cache_dir): (kind, program, fetch, xla_opts,
        # gemm_blocks, extras...) stamped by the cache owner; combined
        # with the arg signature at first call (paddle_tpu.aot_cache)
        self._aot_cache_parts: Optional[tuple] = None
        # serializes the one-time AOT build when two threads race the same
        # step (serving dispatcher vs a user thread)
        self._aot_lock = _monitor.make_lock("_CompiledStep._aot_lock")


def analyze_block_io(block, feed_names: set, fetch_names) -> dict:
    """Classify the vars a compiled step reads/writes.

    Returns feed_order, state_in (scope vars read), state_out (persistables
    written), donated (read AND written AND proven safe to donate — see
    ``analysis.liveness.safe_donation_set``), ro (everything else the step
    reads: true read-only vars plus donation-unsafe state, whose buffers
    are never donated; their updates still flow back through state_out).
    Shared by Executor, CompiledProgram and the sharded trainer paths.

    Donation used to be the bare ``state_in ∩ state_out`` heuristic, which
    could hand XLA a buffer the fetch list still observes (a later fetch of
    the same array would then read a consumed buffer) and had no proof the
    old value was dead. The liveness pass supplies that proof; decisions
    are identical or strictly safer on every program.
    """
    from .analysis.liveness import safe_donation_set

    produced: set = set()
    state_in: List[str] = []
    state_out: List[str] = []
    for op in block.ops:
        if op.type in ("feed", "fetch"):
            continue
        for name in op.input_arg_names:
            if (name not in produced and name not in feed_names
                    and name not in state_in and name != "@EMPTY@"):
                state_in.append(name)
        for name in op.output_arg_names:
            if name == "@EMPTY@":
                continue
            produced.add(name)
            is_persistable = block.has_var(name) and block.var(name).persistable
            if is_persistable and name not in state_out:
                state_out.append(name)
    for n in fetch_names:
        if n not in produced and n not in feed_names and n not in state_in:
            state_in.append(n)
    safe = safe_donation_set(block, feed_names, fetch_names)
    donated = [n for n in state_in if n in state_out and n in safe]
    ro = [n for n in state_in if n not in donated]
    return {"feed_order": sorted(feed_names), "state_in": state_in,
            "state_out": state_out, "donated": donated, "ro": ro}


def make_step_fn(block, io: dict, fetch_names, mesh=None,
                 nan_check_meta=None, gemm_blocks=None,
                 num_witness_meta=None):
    """The traced step body shared by all execution paths.

    ``nan_check_meta``: pass a list to enable FLAGS_check_nan_inf — at trace
    time it fills with one label per float op output and the step returns an
    extra bool vector (aligned with the labels) that the executor inspects
    host-side (reference operator.cc fast_check_nan_inf, but one fused
    check vector per step instead of a sync per op).

    ``num_witness_meta``: pass a list to enable FLAGS_numerics_witness — at
    trace time it fills with one var name per float op output and the step
    returns an extra ``(N, 4)`` [absmax, min, max, nonfinite-count] stats
    array as the LAST tuple element (after the nan-check vector when both
    are on); ``strip_witness_stats`` peels it off and merges it into
    ``monitor.numwitness``. One fused device->host stats transfer per step,
    same batching idiom as the nan checks."""

    def step_fn(feed_vals, donated_vals, ro_vals, rng_key):
        env: Dict[str, Any] = {}
        env.update(zip(io["feed_order"], feed_vals))
        env.update(zip(io["donated"], donated_vals))
        env.update(zip(io["ro"], ro_vals))
        checks = None if nan_check_meta is None else []
        taps = None if num_witness_meta is None else []
        ctx = LowerCtx(base_key=rng_key, mesh=mesh,
                       program=getattr(block, "program", None),
                       nan_checks=checks, gemm_blocks=gemm_blocks,
                       num_taps=taps)
        lower_block(block, env, ctx)
        fetches = [env[n] for n in fetch_names]
        new_state = [env[n] for n in io["state_out"]]
        result = [fetches, new_state]
        if checks is not None:
            nan_check_meta.clear()
            nan_check_meta.extend(label for label, _ in checks)
            result.append(jnp.stack([ok for _, ok in checks])
                          if checks else jnp.ones((0,), bool))
        if taps is not None:
            num_witness_meta.clear()
            num_witness_meta.extend(name for name, _ in taps)
            result.append(jnp.stack([s for _, s in taps])
                          if taps else jnp.zeros((0, 4), jnp.float32))
        return tuple(result)

    return step_fn


def strip_witness_stats(step, result, to_host=np.asarray, path="run"):
    """FLAGS_numerics_witness protocol: a witness-instrumented step (one
    with ``step.num_witness_meta`` set) returns its ``(N, 4)`` per-var
    stats array as the LAST tuple element. Peel it off and merge it into
    ``monitor.numwitness`` BEFORE ``unpack_step_result`` runs — recording
    first means the witness attribution (``numwitness.first_offender``)
    is already fresh when a tripped nan check escalates or skips, which
    is what lets the skip counter and the flight recorder name the
    first offending var (docs/OBSERVABILITY.md)."""
    meta = getattr(step, "num_witness_meta", None)
    if meta is None:
        return result
    from .monitor import numwitness

    numwitness.record_step(list(meta), to_host(result[-1]), path=path)
    return result[:-1]


def unpack_step_result(step, result, scope, to_host=np.asarray, *,
                       path="run", exe=None, rollback=None):
    """Shared FLAGS_check_nan_inf protocol for every execution path: a
    3-tuple result carries the per-op finite flags.

    On a tripped check the outcome depends on ``FLAGS_nan_inf_policy``
    (resilience.nonfinite). With a ``rollback`` list of ``(name, pre-step
    value)`` pairs the scope is restored bit-exactly first; policy
    ``raise`` then raises FloatingPointError naming the op (catching it
    leaves a usable session on pre-step state), while ``skip``/
    ``zero_grad`` DROP the step — the skip is counted
    (``steps_skipped_nonfinite_total``) and ``(fetches, None)`` is
    returned, the caller skipping its state writeback. With
    ``rollback=None`` (a path that could not preserve pre-step buffers,
    e.g. multi-process global arrays) the step's outputs are written back
    FIRST (inputs were donated — without this the scope would reference
    deleted buffers and the session would be unusable after catching the
    error), then FloatingPointError names the op."""
    if len(result) != 3:
        return result
    fetches, new_state, ok_vec = result
    ok = np.asarray(to_host(ok_vec))
    if ok.all():
        _nonfinite.record_clean(exe)
        return fetches, new_state
    bad = int(np.argmin(ok))
    meta = getattr(step, "nan_check_meta", None) or []
    label = meta[bad] if bad < len(meta) else f"check #{bad}"
    if rollback is None:
        for n, v in zip(step.state_out_names, new_state):
            scope.set_var(n, v)
        raise FloatingPointError(
            f"FLAGS_check_nan_inf: non-finite value in {label}")
    for n, v in rollback:
        scope.set_var(n, v)
    if _nonfinite.policy() == "raise":
        raise FloatingPointError(
            f"FLAGS_check_nan_inf: non-finite value in {label} "
            f"(scope restored to pre-step values)")
    # counted AFTER the restore so even skip->raise escalation leaves the
    # scope holding the pre-step values
    _nonfinite.record_skip(path, label, exe)
    return fetches, None


def make_pipeline_step_fn(block, io: dict, fetch_names, mesh=None,
                          nan_check_meta=None, gemm_blocks=None):
    """Microbatched step (PipelineOptimizer): the forward+backward ops run
    under a lax.scan over ``M`` microbatch slices of every feed,
    accumulating the parameter gradients; the optimize/lr ops then run ONCE
    on the averaged grads. This is the reference PipelineTrainer /
    SectionWorker schedule collapsed into one XLA program: the per-section
    scope queues (trainer.h:110, device_worker.h:267 SectionWorker) become
    the scan carry, and stage placement is GSPMD's job via sharding
    annotations rather than per-section Places.

    Fetches report the LAST microbatch's values (the reference fetches from
    the final section's scope). Requires batch % M == 0.
    """
    import jax.numpy as jnp

    from .framework import OpRole

    program = block.program
    M = int(getattr(program, "_pipeline_microbatches", 1))
    pgs = list(getattr(program, "_pipeline_param_grads", []))
    fb_ops = [op for op in block.ops
              if op.attrs.get("__op_role__", OpRole.Forward)
              in (OpRole.Forward, OpRole.Backward)]
    tail_ops = [op for op in block.ops
                if op.attrs.get("__op_role__", OpRole.Forward)
                not in (OpRole.Forward, OpRole.Backward)]
    grad_names = [g for _, g in pgs]
    param_names = [p for p, _ in pgs]
    # persistables the fwd/bwd section itself writes (e.g. BN stats) must
    # thread through the scan carry
    fb_written = {n for op in fb_ops for n in op.output_arg_names}
    fb_state = [n for n in io["state_out"] if n in fb_written]

    def step_fn(feed_vals, donated_vals, ro_vals, rng_key):
        base: Dict[str, Any] = {}
        base.update(zip(io["donated"], donated_vals))
        base.update(zip(io["ro"], ro_vals))
        feeds = []
        for n, v in zip(io["feed_order"], feed_vals):
            if v.shape[0] % M:
                raise ValueError(
                    f"pipeline: feed '{n}' batch {v.shape[0]} not divisible"
                    f" by num_microbatches={M}")
            feeds.append(v.reshape((M, v.shape[0] // M) + v.shape[1:]))
        keys = jax.random.split(rng_key, M)

        checks = None if nan_check_meta is None else []
        grads0 = [jnp.zeros(base[p].shape, base[p].dtype)
                  for p in param_names]
        carry0 = (grads0, {n: base[n] for n in fb_state})

        def micro(carry, xs):
            acc, st = carry
            key, slices = xs[0], xs[1:]
            env = dict(base)
            env.update(st)
            env.update(zip(io["feed_order"], slices))
            ctx = LowerCtx(base_key=key, mesh=mesh, program=program,
                           nan_checks=None, gemm_blocks=gemm_blocks)
            for op in fb_ops:
                lower_op(op, env, ctx)
            new_acc = [a + env[g] for a, g in zip(acc, grad_names)]
            new_st = {n: env[n] for n in fb_state}
            # only fb-PRODUCED fetches come from the scan; anything else
            # (params, lr) must read the post-tail env or it would fetch
            # stale pre-update values
            outs = {n: env[n] for n in fetch_names if n in fb_written}
            return (new_acc, new_st), outs

        (acc, st), fetched = jax.lax.scan(
            micro, carry0, (keys,) + tuple(feeds))
        env = dict(base)
        env.update(st)
        avg = bool(getattr(program, "_grad_merge_avg", True))
        for g, a in zip(grad_names, acc):
            env[g] = a / M if avg else a
        if checks is not None:
            # fb ops run inside the scan (their tracers can't escape), so
            # the fwd/bwd sanitizer coverage is the accumulated grads and
            # carried state checked here, plus per-op checks on tail ops
            for g, a in zip(grad_names, acc):
                checks.append((f"accumulated gradient '{g}' "
                               f"(fwd/bwd microbatch scan)",
                               jnp.isfinite(a).all()))
            for n, v in st.items():
                checks.append((f"carried state '{n}' (microbatch scan)",
                               jnp.isfinite(v).all()))
        ctx = LowerCtx(base_key=rng_key, mesh=mesh, program=program,
                       nan_checks=checks, gemm_blocks=gemm_blocks)
        for op in tail_ops:
            lower_op(op, env, ctx)
        fetches = [fetched[n][-1] if n in fetched else env[n]
                   for n in fetch_names]
        new_state = [env[n] for n in io["state_out"]]
        if checks is not None:
            nan_check_meta.clear()
            nan_check_meta.extend(label for label, _ in checks)
            flags_vec = (jnp.stack([ok for _, ok in checks])
                         if checks else jnp.ones((0,), bool))
            return fetches, new_state, flags_vec
        return fetches, new_state

    return step_fn


def pick_step_fn(program):
    """make_step_fn, or the microbatched variant when the program was
    prepared by PipelineOptimizer."""
    if int(getattr(program, "_pipeline_microbatches", 1)) > 1:
        return make_pipeline_step_fn
    return make_step_fn


class Executor:
    """Reference API (executor.py:380): run / close; plus train loop helpers."""

    def __init__(self, place: Optional[Place] = None):
        self.place = place or TPUPlace()
        self._cache: Dict[tuple, _CompiledStep] = {}
        self._step_counter = 0
        # program fingerprints already verified under FLAGS_check_program
        self._verified: set = set()
        # FLAGS_auto_recompute: (program fingerprint, batch, budget) ->
        # transformed program (or the original when the pass refused).
        # The transformed program is a fresh Program with its own _serial,
        # so step-cache keys can never alias remat and plain variants.
        self._remat_cache: Dict[tuple, Program] = {}
        # FLAGS_epilogue_fusion: (program fingerprint, fetch tuple) ->
        # fused program (or the original when the pass refused). Fused
        # programs are fresh clones with their own _serial — cache
        # separation from the plain variant is structural.
        self._fusion_cache: Dict[tuple, Program] = {}
        # the FusionDecision behind each pipeline-run _fusion_cache entry
        # (pass-through entries have none): lets tools read what the
        # executor decided without re-running the pass's eager witness
        self._fusion_decisions: Dict[tuple, Any] = {}
        # FLAGS_autotune=use|measure: (program fingerprint, bucket, mode)
        # -> best-known TunedConfig or None; one DB probe per program,
        # not per step (a fresh process re-reads the database)
        self._tuning_cache: Dict[tuple, Any] = {}
        # guards the three caches + the seed counter: the serving engine
        # runs this executor from its dispatch thread while the owning
        # thread may still call run() — an unguarded dict resize mid-probe
        # or a torn counter would corrupt the compile cache
        self._lock = _monitor.make_rlock("Executor._lock")

    def _maybe_auto_remat(self, program: Program, feed, fetch_names):
        """FLAGS_auto_recompute entry shared by run / run_chained /
        CompiledProgram: swap a training program for its auto-checkpointed
        rebuild (analysis/remat.py). Inference programs, pipeline programs
        and anything the pass cannot faithfully rebuild pass through
        untouched. Decisions are cached per (program, batch, budget)."""
        from .flags import flag

        if not flag("auto_recompute") or not isinstance(program, Program):
            return program
        batch = 1
        for v in (feed or {}).values():
            shape, _ = _shape_dtype_sig(v)
            if shape:
                batch = max(batch, int(shape[0]))
        budget = int(flag("remat_budget_mb"))
        # fetch_names are part of the key: a transform built for one fetch
        # list keeps only THOSE fetches alive across segments, so a later
        # run fetching a different activation needs its own rebuild. The
        # lookup comes before any program scan so steady-state dispatches
        # pay one dict probe, nothing op-count-shaped.
        key = (self._program_fingerprint(program), batch, budget,
               tuple(fetch_names or ()))
        # whole decision under the executor lock: a racing second thread
        # must reuse the SAME transformed program (a duplicate rebuild
        # would fork two serials and recompile everything downstream)
        with self._lock:
            cached = self._remat_cache.get(key)
            if cached is not None:
                return cached
            from .analysis.remat import is_trainable_program

            # startup/inference programs cannot remat by construction; pass
            # through (cached) with no monitor record — a 'refused' count
            # here would read as a training program the pass could not
            # handle
            if not is_trainable_program(program):
                self._remat_cache[key] = program
                return program
            # the transform runs as a registered pass through the manager
            # (ROADMAP item 5): at FLAGS_check_program>=2 the pipeline
            # re-verifies the rebuilt program and refuses a corrupting
            # transform with PassVerificationError
            from .analysis.pass_manager import run_transform_pipeline

            result = run_transform_pipeline(
                program, ("auto_remat",), feed_names=sorted(feed or {}),
                fetch_names=list(fetch_names or ()), batch_size=batch,
                options={"budget_mb": budget})
            decision = result.values["auto_remat"]
            _monitor.record_remat(decision)
            self._remat_cache[key] = decision.program
            return decision.program

    def _maybe_epilogue_fusion(self, program, feed, fetch_names,
                               tuning_program=None):
        """FLAGS_epilogue_fusion entry shared by run / run_chained: swap a
        forward-only program for its GEMM-epilogue-fused rewrite
        (analysis/epilogue_fusion.py). Training programs, programs with no
        mul/matmul, and anything the pass's fidelity witness cannot prove
        pass through untouched. Decisions are cached per (program, fetch
        list, tuned gemm blocks) — the blocks the compile will thread into
        its LowerCtx are part of the witnessed configuration, so a cost-DB
        update re-witnesses; the fused clone has its own _serial so
        compiled-step caches never alias fused and plain variants.
        ``tuning_program`` is the SUBMITTED program the compile path keys
        the cost database on."""
        from .flags import flag

        if not flag("epilogue_fusion") or not isinstance(program, Program):
            return program
        _, _, gemm_blocks = self._tuned_compile_config(
            tuning_program if isinstance(tuning_program, Program)
            else program, feed)
        key = (self._program_fingerprint(program),
               tuple(fetch_names or ()), gemm_blocks)
        with self._lock:
            cached = self._fusion_cache.get(key)
        if cached is not None:
            return cached
        from .analysis.epilogue_fusion import has_fusable_ops

        # training programs / no matmul: pass through (cached) with no
        # monitor record — a 'refused' count here would read as a
        # fusable program the pass could not handle
        if not has_fusable_ops(program):
            with self._lock:
                self._fusion_cache.setdefault(key, program)
            return program
        from .analysis.pass_manager import run_transform_pipeline

        # the pipeline's fidelity witness eagerly executes jax
        # computations per chain signature — run it OUTSIDE the executor
        # lock (run/run_chained/serving dispatch all contend on it) and
        # insert first-wins, like the compiled-step double-check: two
        # racing threads must converge on ONE fused clone, or its _serial
        # would split the compiled-step caches
        result = run_transform_pipeline(
            program, ("epilogue_fusion",),
            feed_names=sorted(feed or {}),
            fetch_names=list(fetch_names or ()),
            batch_size=_feed_batch_rows(feed),
            options={"gemm_blocks": gemm_blocks})
        decision = result.values["epilogue_fusion"]
        with self._lock:
            winner = self._fusion_cache.get(key)
            if winner is None:
                winner = self._fusion_cache[key] = decision.program
                self._fusion_decisions[key] = decision
                record = True
            else:
                record = False
        if record:
            _monitor.record_fusion(decision)
        return winner

    def _tuned_compile_config(self, program, feed):
        """(xla_options dict, sorted key tuple, gemm blocks or None) for
        one compile: explicit FLAGS_xla_options / FLAGS_fused_gemm_blocks
        always win; with FLAGS_autotune=use|measure the cost database
        fills whichever knob is unset (paddle_tpu.tuning), and the chosen
        values join every compile-cache key so a database update
        recompiles instead of silently reusing a stale executable."""
        from .flags import flag, xla_options

        opts = xla_options()
        # an explicitly-set FLAGS_xla_options='{}' means "no options, on
        # purpose" — it must win over the DB like any other explicit value
        opts_explicit = bool(str(flag("xla_options")).strip())
        blocks = None
        if str(flag("fused_gemm_blocks")).strip():
            from .ops.fused_gemm import resolve_gemm_blocks

            blocks = resolve_gemm_blocks(None)
        if (not opts and not opts_explicit) or blocks is None:
            from . import tuning

            mode = tuning.autotune_mode()
            # never fill knobs DURING a measure_candidates trial: the
            # candidate under test must compile exactly as specified, or
            # its time is recorded against the wrong config
            if mode != "off" and not tuning.in_trial() \
                    and isinstance(program, Program):
                batch = _feed_batch_rows(feed)
                tkey = (self._program_fingerprint(program),
                        tuning.shape_bucket(batch), mode)
                with self._lock:
                    probed = tkey in self._tuning_cache
                    cfg = self._tuning_cache.get(tkey)
                if not probed:
                    cfg = tuning.lookup_best(program, batch)
                    with self._lock:
                        self._tuning_cache[tkey] = cfg
                if cfg is not None:
                    if not opts and not opts_explicit:
                        opts = cfg.options_dict()
                    if blocks is None and cfg.gemm_blocks:
                        blocks = cfg.gemm_blocks
        # the blocks tuple is threaded into the step fn's LowerCtx by the
        # caller (never stamped on the shared Program): the values the
        # fused_gemm_epilogue lowering traces with are exactly the values
        # in this compile's cache key, even when concurrent compiles of
        # the same program resolve different tuned configs
        return opts, tuple(sorted(opts.items())), \
            tuple(blocks) if blocks else None

    def _verify_once(self, program: Program, fetch_names) -> None:
        """FLAGS_check_program pre-run hook: static-verify each program
        version once before it compiles (the build-time role of the
        reference's op_registry.h checks). Raises ProgramVerificationError
        with build-site diagnostics on error-severity findings. Runs the
        verifier passes through ``PassManager.run_pipeline`` (ROADMAP item
        5), so per-pass timings land on the monitor registry."""
        from .flags import flag

        if not int(flag("check_program")):
            return
        fp = self._program_fingerprint(program)
        with self._lock:
            if fp in self._verified:
                return
        from .analysis.pass_manager import run_verify_pipeline

        run_verify_pipeline(program, fetch_names=fetch_names)
        with self._lock:
            self._verified.add(fp)

    # -- public API ------------------------------------------------------
    def run(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[Sequence[Union[str, Variable]]] = None,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        use_program_cache: bool = True,
    ):
        from .parallel.compiled_program import CompiledProgram

        if isinstance(program, CompiledProgram):
            self._verify_once(program.program,
                              [f.name if isinstance(f, Variable) else f
                               for f in (fetch_list or [])])
            return program._run(self, feed, fetch_list, scope, return_numpy)

        # pserver-role program from the DistributeTranspiler shim: nothing
        # to serve on TPU (params live on-chip), return immediately so 2019
        # PS launch scripts complete cleanly
        if getattr(program, "_is_pserver_noop", False):
            return []

        program = program or default_main_program()
        scope = scope or global_scope()
        feed = feed or {}
        fetch_names = [f.name if isinstance(f, Variable) else f
                       for f in (fetch_list or [])]

        submitted = program
        program = self._maybe_auto_remat(program, feed, fetch_names)
        program = self._maybe_epilogue_fusion(program, feed, fetch_names,
                                              tuning_program=submitted)
        self._verify_once(program, fetch_names)
        mrec = _monitor.step_begin("run", program)
        # child of whatever request/step trace is ambient on this thread
        # (serving attaches the request root; the Trainer its step root)
        with _trace.span("executor.run",
                         program=int(getattr(program, "_serial", -1))):
            try:
                return self._run_body(program, feed, fetch_names, scope,
                                      return_numpy, use_program_cache, mrec,
                                      tuning_program=submitted)
            finally:
                # always paired with step_begin — a step that raises (e.g.
                # FLAGS_check_nan_inf) still counts and hooks stay in sync
                _monitor.step_end(mrec)

    def _run_body(self, program, feed, fetch_names, scope, return_numpy,
                  use_program_cache, mrec, tuning_program=None):
        step = self._get_compiled(program, feed, fetch_names, scope,
                                  use_cache=use_program_cache, mrec=mrec,
                                  tuning_program=tuning_program)
        if mrec is not None:
            mrec.fetch_names = tuple(fetch_names)
            mrec.feed_bytes = sum(_feed_host_bytes(v) for v in feed.values())
            mrec.batch_rows = _feed_batch_rows(feed)
        feed_vals = [self._to_device_array(feed[n], program, n)
                     for n in step.feed_names]

        def read_state(names):
            vals = []
            blk = program.global_block
            for n in names:
                v = scope.find_var(n)
                if v is None:
                    if blk.has_var(n) and blk.var(n).is_data:
                        raise RuntimeError(
                            f"Input variable '{n}' is declared as data but was "
                            f"not passed in feed={{...}}")
                    raise RuntimeError(
                        f"Variable '{n}' is not initialized in scope — run the "
                        f"startup program first (reference: executor.cc var-init check)"
                    )
                vals.append(v)
            return vals

        donated_vals = read_state(step.donated_names)
        ro_vals = read_state(step.ro_names)
        # step-site fault probe fires BEFORE any buffer is donated, so an
        # injected step failure leaves the scope fully usable
        _faults.fault_point("step")
        if mrec is not None:
            mrec.donated_buffers = len(step.donated_names)
            mrec.kept_buffers = len(step.kept_names)
            mrec.donated_bytes = _live_bytes(donated_vals)
        key = jax.random.key(self._next_seed(program))
        rollback = None
        with jax.default_device(self.place.jax_device()):
            if step.nan_check_meta is not None \
                    and _nonfinite.rollback_active():
                # nan_inf_policy=skip|zero_grad must be able to restore the
                # EXACT pre-step bits, but donation consumes the inputs —
                # so donate fresh device copies and keep the originals
                rollback = list(zip(step.donated_names, donated_vals))
                donated_vals = [jnp.array(v) for v in donated_vals]
            else:
                # inside default_device so the one-time host->device copy
                # of planted numpy state lands on THIS executor's device
                donated_vals = _own_donated(donated_vals)
            fn = self._ensure_executable(
                step, (feed_vals, donated_vals, ro_vals, key))
            # watchdog-armed dispatch: a hang here (injected via the
            # 'hang' fault site, or a real stuck collective) is dumped +
            # raised as WatchdogTimeout under FLAGS_step_timeout_s
            with _trace.span("executor.step",
                             cache_hit=bool(mrec.cache_hit)
                             if mrec is not None else None), \
                    RecordEvent("executor::step"), \
                    _dist.watchdog_section("step", program=program) as tok:
                _faults.fault_point("hang")
                try:
                    result = fn(feed_vals, donated_vals, ro_vals, key)
                except (TypeError, ValueError):
                    if fn is step.fn:
                        raise
                    # the AOT executable is stricter than jit dispatch:
                    # structure mismatches raise TypeError, committed-to-
                    # another-device shardings raise ValueError — both are
                    # checked before any buffer is donated, so retry
                    # through jit (which adapts) and stop using the AOT
                    # fast path for this step
                    step._aot = False
                    result = step.fn(feed_vals, donated_vals, ro_vals, key)
                if tok is not None:
                    # dispatch is async — without this the section would
                    # disarm before a stuck device computation ever ran.
                    # Only under FLAGS_step_timeout_s, which opts into
                    # deadline-over-overlap
                    jax.block_until_ready(result)
        result = strip_witness_stats(step, result, path="run")
        fetches, new_state = unpack_step_result(step, result, scope,
                                                path="run", exe=self,
                                                rollback=rollback)
        if new_state is not None:
            for n, v in zip(step.state_out_names, new_state):
                scope.set_var(n, v)
        if return_numpy:
            outs = [np.asarray(v) for v in fetches]
            if mrec is not None:
                mrec.fetch_bytes = _live_bytes(outs)
            return outs
        return list(fetches)

    def run_chained(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[Sequence[Union[str, Variable]]] = None,
        steps: int = 1,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
    ):
        """Run ``steps`` iterations of ``program`` as ONE compiled dispatch:
        a ``lax.scan`` over the step body with the parameter state threaded
        through the carry. Returns fetches stacked along a leading ``steps``
        axis; the scope holds the final-step state, exactly as if ``run``
        had been called ``steps`` times with the same feed.

        This is the reference's run-the-loop-in-C++ role (trainer.cc
        multi-iteration RunFromDataset) done the XLA way — and the honest
        way to measure step time through a high-RTT dev tunnel: iterations
        are data-dependent by construction (while-loop semantics serialize
        the bodies), so wall time divided by ``steps`` is compute, not
        dispatch rate. ``tools/perf_probe.py`` documents the protocol.

        The same feed batch is used for every iteration (perf measurement /
        overfit-one-batch semantics); real input pipelines stream via
        DataLoader + ``run``. FLAGS_check_nan_inf here is a COARSE whole-
        dispatch check (per-op flags would have to be stacked across
        steps): the final carried state is checked host-side after the
        scan, and a trip raises/skips the entire ``steps``-iteration
        dispatch per FLAGS_nan_inf_policy — use ``run`` for per-op
        provenance.
        """
        program = program or default_main_program()
        scope = scope or global_scope()
        feed = feed or {}
        fetch_names = [f.name if isinstance(f, Variable) else f
                       for f in (fetch_list or [])]
        if int(getattr(program, "_pipeline_microbatches", 1)) > 1:
            raise NotImplementedError(
                "run_chained with PipelineOptimizer programs: the pipeline "
                "step is already a scan; nest via GradientMergeOptimizer")

        submitted = program
        program = self._maybe_auto_remat(program, feed, fetch_names)
        program = self._maybe_epilogue_fusion(program, feed, fetch_names,
                                              tuning_program=submitted)
        self._verify_once(program, fetch_names)
        # tuning keys on the SUBMITTED program: measure_candidates records
        # trials under its content fingerprint, before the auto-remat /
        # fusion clones (whose fingerprints differ) are swapped in
        opts, xla_opts, gemm_blocks = self._tuned_compile_config(submitted,
                                                                 feed)
        feed_sig = tuple(sorted(
            (n,) + _shape_dtype_sig(v) for n, v in feed.items()))
        key = ("chained", self._program_fingerprint(program), feed_sig,
               tuple(fetch_names), int(steps), scope._serial, xla_opts,
               gemm_blocks)
        with self._lock:
            step = self._cache.get(key)
        mrec = _monitor.step_begin("chained", program)
        if mrec is not None:
            mrec.cache_hit = step is not None
            mrec.iterations = int(steps)
            mrec.fetch_names = tuple(fetch_names)
            mrec.feed_bytes = sum(_feed_host_bytes(v) for v in feed.values())
            mrec.batch_rows = _feed_batch_rows(feed)
        _monitor.record_cache_lookup("chained", step is not None)
        with _trace.span("executor.run_chained",
                         program=int(getattr(program, "_serial", -1)),
                         steps=int(steps)):
            try:
                return self._run_chained_body(program, feed, fetch_names,
                                              steps, scope, return_numpy,
                                              key, step, feed_sig, mrec,
                                              (opts, xla_opts, gemm_blocks))
            finally:
                _monitor.step_end(mrec)

    def _run_chained_body(self, program, feed, fetch_names, steps, scope,
                          return_numpy, key, step, feed_sig, mrec,
                          compile_cfg):
        if step is None:
            step = self._build_chained_step(program, feed, fetch_names,
                                            steps, scope, key, feed_sig,
                                            compile_cfg)
        return self._dispatch_chained(program, feed, steps, scope,
                                      return_numpy, step, mrec)

    def _build_chained_step(self, program, feed, fetch_names, steps, scope,
                            key, feed_sig, compile_cfg):
        # under the executor lock with a double-check: a racing thread
        # must reuse the same scan wrapper, not fork a second compile
        with self._lock:
            step = self._cache.get(key)
            if step is not None:
                return step
            block = program.global_block
            io = analyze_block_io(block, set(feed.keys()), fetch_names)
            # carried: ALL read+written state threads through the scan carry
            # (a donation-unsafe var — e.g. a fetched param — must still
            # chain step to step; reading it as a loop-invariant would hand
            # every iteration the stale pre-run value). donated ⊆ carried is
            # the subset whose INPUT buffers may be donated at the jit
            # boundary.
            kept = [n for n in io["ro"] if n in io["state_out"]]
            carried = list(io["donated"]) + kept
            carried_set = set(carried)
            ro_names = [n for n in io["ro"] if n not in carried_set]
            io2 = dict(io, donated=carried, ro=ro_names)
            base_step = make_step_fn(block, io2, fetch_names,
                                     gemm_blocks=compile_cfg[2])
            idx = {n: i for i, n in enumerate(io["state_out"])}
            wo_names = [n for n in io["state_out"] if n not in carried_set]

            # Inference programs would let XLA's loop-invariant code motion
            # hoist the whole body out of the scan, so a timing of K
            # iterations would measure ONE. Feed a runtime-zero perturbation
            # chained off each step's first fetch into the first float feed
            # (falling back to the smallest float read-only input, then the
            # smallest float carried input, for feed-less programs like GPT
            # decode — the source falls back from fetches to the smallest
            # float carried output): exact results (the scalar IS zero at
            # runtime), but the compiler cannot prove it, so the bodies
            # stay serialized.
            # The old trigger was `not carried` — which missed for_test
            # clones whose only carried state is identity-written
            # batch_norm statistics (use_global_stats writes MeanOut=Mean):
            # XLA's while-loop simplifier sees the fixed-point carry,
            # hoists the body, and the chained infer "per-step" time
            # differences to ~zero (the r03->r05 ResNet-50 infer
            # discontinuity in the bench trajectory — docs/PERF_NOTES.md
            # "The r05 infer discontinuity"). Training programs genuinely
            # chain through the optimizer's parameter updates; everything
            # else gets the explicit chain.
            is_training = any(
                op.attrs.get("__op_role__", OpRole.Forward)
                != OpRole.Forward for op in block.ops)
            needs_chain = not is_training

            def _is_float(v) -> bool:
                return jnp.issubdtype(jnp.result_type(v), jnp.inexact)

            def _smallest_float_i(vals):
                cands = [(v.size, i) for i, v in enumerate(vals)
                         if _is_float(v) and v.size]
                return min(cands)[1] if cands else None

            def multi_fn(feed_vals, donated_vals, kept_vals, ro_vals, keys,
                         wo_init, chain_eps):
                # perturbation target: float feed first (the original
                # protocol), else the SMALLEST float ro / carried input so
                # a feed-less decode program pays one tiny add per step,
                # not a KV-cache-sized one
                float_i = ro_i = carry_i = None
                carried_init = list(donated_vals) + list(kept_vals)
                if needs_chain:
                    float_i = next((i for i, v in enumerate(feed_vals)
                                    if _is_float(v)), None)
                    if float_i is None:
                        ro_i = _smallest_float_i(ro_vals)
                    if float_i is None and ro_i is None:
                        carry_i = _smallest_float_i(carried_init)
                chained = (float_i is not None or ro_i is not None
                           or carry_i is not None)

                def body(carry, k):
                    cur, _, s = carry
                    fv = list(feed_vals)
                    rv = ro_vals
                    cv = cur
                    if float_i is not None:
                        fv[float_i] = fv[float_i] + (
                            chain_eps * s).astype(fv[float_i].dtype)
                    elif ro_i is not None:
                        rv = list(ro_vals)
                        rv[ro_i] = rv[ro_i] + (
                            chain_eps * s).astype(rv[ro_i].dtype)
                    elif carry_i is not None:
                        cv = list(cur)
                        cv[carry_i] = cv[carry_i] + (
                            chain_eps * s).astype(cv[carry_i].dtype)
                    fetches, new_state = base_step(fv, cv, rv, k)
                    new_carried = [new_state[idx[n]] for n in carried]
                    new_wo = [new_state[idx[n]] for n in wo_names]
                    s_next = s
                    if chained:
                        # chain source: first float fetch (the original
                        # protocol), else any non-empty fetch (int token
                        # ids chain just as well — they depend on the
                        # perturbed input), else the smallest float
                        # carried output
                        src = next((f for f in fetches
                                    if _is_float(f) and f.size), None)
                        if src is None:
                            src = next((f for f in fetches if f.size),
                                       None)
                        if src is None:
                            j = _smallest_float_i(new_carried)
                            src = new_carried[j] if j is not None else None
                        if src is not None:
                            s_next = src.ravel()[0].astype(jnp.float32)
                    return (new_carried, new_wo, s_next), fetches

                (fin_carried, fin_wo, _), stacked = jax.lax.scan(
                    body, (carried_init, wo_init, jnp.float32(0)), keys)
                return stacked, fin_carried, fin_wo

            opts, xla_opts, gemm_blocks = compile_cfg
            jitted = jax.jit(multi_fn, donate_argnums=(1,),
                             compiler_options=opts or None)
            step = _CompiledStep(jitted, io["feed_order"], io["donated"],
                                 ro_names, io["state_out"],
                                 tuple(fetch_names))
            step.program = program
            step.needs_chain = needs_chain
            step._aot_cache_parts = ("chained", program,
                                     tuple(fetch_names), xla_opts,
                                     gemm_blocks, int(steps))
            step._compile_event = _monitor.observe_compile(
                "chained", program,
                components={
                    "program": self._program_fingerprint(program)[1:],
                    "feed_signature": feed_sig,
                    "fetch_list": tuple(fetch_names),
                    "scope": scope._serial,
                    "steps": int(steps),
                    "xla_options": xla_opts,
                    "gemm_blocks": gemm_blocks,
                },
                donated_names=io["donated"])
            step.kept_names = kept
            step.carried_names = carried
            step.wo_names = wo_names
            step.io = io
            step.base_step = base_step
            step.wo_shapes = None
            self._cache[key] = step
            return step

    def _dispatch_chained(self, program, feed, steps, scope,
                          return_numpy, step, mrec):
        feed_vals = [self._to_device_array(feed[n], program, n)
                     for n in step.feed_names]
        donated_vals = [scope.find_var(n) for n in step.donated_names]
        kept_vals = [scope.find_var(n) for n in step.kept_names]
        ro_vals = [scope.find_var(n) for n in step.ro_names]
        for n, v in zip(step.carried_names + step.ro_names,
                        donated_vals + kept_vals + ro_vals):
            if v is None:
                raise RuntimeError(
                    f"Variable '{n}' is not initialized in scope — run the "
                    f"startup program first")
        keys = jax.random.split(
            jax.random.key(self._next_seed(program)), steps)
        # write-only persistables (produced fresh each step, never read):
        # shape them abstractly so the scan carry can thread them
        if step.wo_shapes is None:
            out_shapes = jax.eval_shape(step.base_step, feed_vals,
                                        donated_vals + kept_vals, ro_vals,
                                        keys[0])
            wo_idx = {n: i for i, n in enumerate(step.io["state_out"])}
            step.wo_shapes = [(out_shapes[1][wo_idx[n]].shape,
                               out_shapes[1][wo_idx[n]].dtype)
                              for n in step.wo_names]
            if getattr(step, "needs_chain", not step.carried_names):
                # chained measurement honesty: the anti-hoisting chain (see
                # multi_fn) needs a float input to perturb (feed, or for
                # feed-less programs like GPT decode a read-only/carried
                # input) AND a non-empty output to carry the chain through
                # (any fetch, or a float carried output); without both, XLA
                # hoists the loop-invariant body and a timing of K steps
                # measures ONE — warn loudly rather than let a benchmark
                # silently report K x real throughput
                def _inexact(v):
                    return jnp.issubdtype(jnp.result_type(v), jnp.inexact)

                can_perturb = any(
                    _inexact(v) for v in feed_vals) or any(
                    _inexact(v) and v.size
                    for v in ro_vals + donated_vals + kept_vals)
                can_carry = any(
                    s.size for s in out_shapes[0]) or any(
                    _inexact(v) and v.size
                    for v in donated_vals + kept_vals)
                if not (can_perturb and can_carry):
                    import warnings

                    warnings.warn(
                        "run_chained: program has no trainable state and "
                        "no float input / non-empty output pair to chain "
                        "iterations through — XLA may hoist the body and "
                        "execute it ONCE; do not use this timing as a "
                        "per-step measurement",
                        RuntimeWarning, stacklevel=3)
        wo_init = [jnp.zeros(s, d) for s, d in step.wo_shapes]
        # step-site fault probe fires BEFORE donation, scope stays usable
        _faults.fault_point("step")
        from .flags import flag

        check = flag("check_nan_inf")
        rollback = None
        if mrec is not None:
            mrec.donated_buffers = len(step.donated_names)
            mrec.kept_buffers = len(step.kept_names)
            mrec.donated_bytes = _live_bytes(donated_vals)
        with jax.default_device(self.place.jax_device()):
            if check and _nonfinite.rollback_active():
                # pre-dispatch image of the donated carry so a tripped scan
                # can be dropped bit-exactly (see unpack_step_result)
                rollback = list(zip(step.donated_names, donated_vals))
                donated_vals = [jnp.array(v) for v in donated_vals]
            else:
                # inside default_device so the one-time host->device copy
                # of planted numpy state lands on THIS executor's device
                donated_vals = _own_donated(donated_vals)
            args = (feed_vals, donated_vals, kept_vals, ro_vals, keys,
                    wo_init, jnp.float32(0))
            fn = self._ensure_executable(step, args)
            with RecordEvent("executor::run_chained"), \
                    _dist.watchdog_section("chained",
                                           program=program) as tok:
                _faults.fault_point("hang")
                try:
                    stacked, fin_carried, fin_wo = fn(*args)
                except (TypeError, ValueError):
                    if fn is step.fn:
                        raise
                    step._aot = False
                    stacked, fin_carried, fin_wo = step.fn(*args)
                if tok is not None:
                    # async dispatch: keep the section armed until the
                    # scanned computation actually finished on device
                    jax.block_until_ready((stacked, fin_carried, fin_wo))
        if check:
            bad = next((n for n, v in
                        list(zip(step.carried_names, fin_carried))
                        + list(zip(step.wo_names, fin_wo))
                        if _has_nonfinite(v)), None)
            if bad is not None:
                label = (f"final state '{bad}' after {steps} scanned "
                         f"iteration(s)")
                if rollback is None:
                    for n, v in zip(step.carried_names, fin_carried):
                        scope.set_var(n, v)
                    for n, v in zip(step.wo_names, fin_wo):
                        scope.set_var(n, v)
                    raise FloatingPointError(
                        f"FLAGS_check_nan_inf: non-finite value in {label} "
                        f"(run_chained coarse check; use run for per-op "
                        f"provenance)")
                for n, v in rollback:
                    scope.set_var(n, v)
                if _nonfinite.policy() == "raise":
                    raise FloatingPointError(
                        f"FLAGS_check_nan_inf: non-finite value in {label} "
                        f"(run_chained coarse check, scope restored to "
                        f"pre-scan values; use run for per-op provenance)")
                _nonfinite.record_skip("chained", label, self)
                if return_numpy:
                    return [np.asarray(v) for v in stacked]
                return list(stacked)
            _nonfinite.record_clean(self)
        for n, v in zip(step.carried_names, fin_carried):
            scope.set_var(n, v)
        for n, v in zip(step.wo_names, fin_wo):
            scope.set_var(n, v)
        if return_numpy:
            outs = [np.asarray(v) for v in stacked]
            if mrec is not None:
                mrec.fetch_bytes = _live_bytes(outs)
            return outs
        return list(stacked)

    def close(self):
        with self._lock:
            self._cache.clear()
            self._verified.clear()
            self._remat_cache.clear()
            self._fusion_cache.clear()
            self._fusion_decisions.clear()
            self._tuning_cache.clear()

    # -- internals -------------------------------------------------------
    def _next_seed(self, program: Program) -> int:
        with self._lock:
            self._step_counter += 1
            counter = self._step_counter
        base = program.random_seed or 0
        return (base * 1_000_003 + counter) & 0x7FFFFFFF

    def _to_device_array(self, value, program, name):
        if isinstance(value, (np.ndarray, list, tuple, int, float)):
            arr = np.asarray(value)
            blk = program.global_block
            if blk.has_var(name):
                want = np_dtype(blk.var(name).dtype)
                if arr.dtype != want and arr.dtype.kind == want.kind:
                    arr = arr.astype(want)

            def _put():
                # transient-site: host->device transfer can fail for
                # infrastructure reasons (preempted device, RPC hiccup);
                # retry with backoff, never for shape/dtype errors
                _faults.fault_point("device_put")
                return jnp.asarray(arr)
            return call_with_retry("device_put", _put)
        return value

    def _program_fingerprint(self, program: Program) -> tuple:
        # _version counts op appends AND Operator.set_attr mutations, so
        # flipping e.g. is_test on a cached program recompiles (the reference
        # invalidates via desc version); op count catches op removal, which
        # bumps no counter. _serial (not id()) so GC can never alias two
        # programs onto one cache entry.
        return (program._serial, getattr(program, "_version", 0),
                sum(len(b.ops) for b in program.blocks))

    def _get_compiled(self, program, feed, fetch_names, scope,
                      use_cache: bool = True, mrec=None,
                      tuning_program=None) -> _CompiledStep:
        feed_sig = tuple(sorted(
            (n,) + _shape_dtype_sig(v) for n, v in feed.items()
        ))
        from .flags import flag

        # tuning_program: the program as the CALLER submitted it, before
        # auto-remat / epilogue-fusion swapped in a rewritten clone.
        # tuning.measure_candidates records trials under the submitted
        # program's content fingerprint, so lookups must key on the same
        # object or a fused program could never reuse its own trials
        opts, xla_opts, gemm_blocks = self._tuned_compile_config(
            tuning_program if tuning_program is not None else program, feed)
        key = (self._program_fingerprint(program), feed_sig,
               tuple(fetch_names), scope._serial, flag("check_nan_inf"),
               flag("numerics_witness"), xla_opts, gemm_blocks)
        # the whole lookup-or-build runs under the executor lock: two
        # threads racing the same key must share ONE step (and one monitor
        # compile record); _compile only builds the jit wrapper — the
        # expensive XLA build happens later under the step's own _aot_lock,
        # so unrelated steps still compile in parallel
        with self._lock:
            hit = use_cache and key in self._cache
            _monitor.record_cache_lookup("run", hit)
            if mrec is not None:
                mrec.cache_hit = hit
            if hit:
                return self._cache[key]
            with RecordEvent("executor::build_step"):
                step = self._compile(program, set(feed.keys()), fetch_names,
                                     scope, xla_opts=opts,
                                     gemm_blocks=gemm_blocks)
            step.program = program
            if not flag("check_nan_inf") and not flag("numerics_witness"):
                # nan-checked steps are NOT disk-cached: their per-op
                # provenance labels (nan_check_meta) are filled at trace
                # time, which a loaded executable skips — a tripped
                # check would lose the op attribution that is the
                # flag's whole point. (The chained path's coarse
                # host-side check carries no meta, so it stays cached.)
                # Witness-instrumented steps skip it for the same reason:
                # num_witness_meta's var names are filled at trace time.
                step._aot_cache_parts = ("run", program,
                                         tuple(fetch_names), xla_opts,
                                         gemm_blocks)
            step._compile_event = _monitor.observe_compile(
                "run", program,
                components={
                    "program": self._program_fingerprint(program)[1:],
                    "feed_signature": feed_sig,
                    "fetch_list": tuple(fetch_names),
                    "scope": scope._serial,
                    "flags": (("check_nan_inf", flag("check_nan_inf")),),
                    "xla_options": xla_opts,
                    "gemm_blocks": gemm_blocks,
                },
                donated_names=step.donated_names)
            self._cache[key] = step
            return step

    def _compile(self, program: Program, feed_names: set, fetch_names,
                 scope, xla_opts=None, gemm_blocks=None):
        from .flags import flag, xla_options

        if xla_opts is None:
            xla_opts = xla_options()
        block = program.global_block
        io = analyze_block_io(block, feed_names, fetch_names)
        meta = [] if flag("check_nan_inf") else None
        maker = pick_step_fn(program)
        # numerics witness: make_step_fn path only — the microbatched
        # pipeline body runs under lax.scan, where per-op taps would be
        # tracer escapes (same reason its nan checks are the coarse kind)
        wmeta = ([] if flag("numerics_witness") and maker is make_step_fn
                 else None)
        kwargs = dict(nan_check_meta=meta, gemm_blocks=gemm_blocks)
        if wmeta is not None:
            kwargs["num_witness_meta"] = wmeta
        step_fn = maker(block, io, fetch_names, **kwargs)
        jitted = jax.jit(step_fn, donate_argnums=(1,),
                         compiler_options=xla_opts or None)
        step = _CompiledStep(jitted, io["feed_order"], io["donated"],
                             io["ro"], io["state_out"], tuple(fetch_names))
        step.kept_names = [n for n in io["ro"] if n in io["state_out"]]
        step.nan_check_meta = meta  # filled lazily at first trace
        step.num_witness_meta = wmeta  # ditto
        return step

    def _ensure_executable(self, step: _CompiledStep, args):
        """First call of a freshly compiled step: run the AOT pipeline
        explicitly so jaxpr-trace+StableHLO-lower and XLA-compile are
        measured as separate monitor stages (TVM's lesson in PAPERS.md:
        treat compile and execute cost as first-class, separately measured
        quantities). The compiled executable is kept on the step — later
        calls through it also skip jit dispatch overhead. If lowering
        raises (user shape errors surface at trace time) the jit path is
        used instead so the original diagnostic is what the user sees.

        Serialized per step under ``_aot_lock`` (double-checked): when the
        serving dispatcher and a user thread race the first call of one
        step, exactly one of them builds and the other waits for the
        finished executable instead of burning a duplicate XLA compile."""
        if step._aot is None:
            with step._aot_lock:
                return self._ensure_executable_locked(step, args)
        return step._aot or step.fn

    def _ensure_executable_locked(self, step: _CompiledStep, args):
        if step._aot is None:
            ev, step._compile_event = step._compile_event, None
            t_trace = t_compile = None

            # warm-start probe (FLAGS_aot_cache_dir): a serialized
            # executable for this exact (program content, arg signature,
            # compiler config, backend/version) identity loads instead of
            # compiling — the fleet tier's cold-replica path. Loads never
            # raise; a miss falls through to the normal build, which then
            # publishes its executable for the next process.
            from . import aot_cache as _aot_cache

            cache_dir = _aot_cache.cache_dir_flag()
            cache_key = None
            if cache_dir and step._aot_cache_parts is not None:
                cache_key = _aot_cache.executable_key(
                    step._aot_cache_parts, args)
                t0 = time.perf_counter()
                loaded = _aot_cache.load_executable(cache_dir, cache_key)
                if loaded is not None:
                    step._aot = loaded
                    # the monitor's compile record stays paired: the
                    # "xla compile" stage is the deserialize+load time
                    _monitor.complete_compile(ev, 0.0,
                                              time.perf_counter() - t0)
                    return step._aot

            def _build():
                # transient-site: compiles hit flaky infra (preempted
                # backend, cache-server hiccups) — retried with backoff.
                # Watchdog-armed: a hung compile is dumped + raised, not
                # waited on forever
                _faults.fault_point("compile")
                with _dist.watchdog_section("compile",
                                            program=step.program):
                    t0 = time.perf_counter()
                    with RecordEvent("executor::trace_lower"):
                        lowered = step.fn.lower(*args)
                    t1 = time.perf_counter()
                    with RecordEvent("executor::xla_compile"):
                        compiled = lowered.compile()
                    return compiled, t1 - t0, time.perf_counter() - t1

            try:
                with _trace.span(
                        "executor.compile",
                        program=int(getattr(step.program, "_serial", -1))):
                    step._aot, t_trace, t_compile = \
                        call_with_retry("compile", _build)
                if cache_key is not None and step._aot:
                    # publish for the next cold process (atomic; failures
                    # warn once and never break the step)
                    _aot_cache.save_executable(cache_dir, cache_key,
                                               step._aot)
            except RetryExhaustedError as e:
                if isinstance(e.last_error, _faults.InjectedFault):
                    # a scripted fault outlasting the retry budget must
                    # ABORT (the chaos gate's negative control), not fall
                    # back to a jit path the plan never faulted
                    raise
                step._aot = False   # real persistent failure: jit fallback
            except _dist.WatchdogTimeout:
                # a diagnosed hang must FAIL, never silently fall back to
                # a jit retry of the same hung build
                raise
            except Exception:
                # user trace/shape errors surface through the jit path so
                # the original diagnostic is what the user sees
                step._aot = False
            finally:
                # always paired with the popped record — even a
                # KeyboardInterrupt mid-compile must not leave the
                # on_compile hooks waiting forever
                _monitor.complete_compile(ev, t_trace, t_compile)
        return step._aot or step.fn
