"""paddle_tpu.trace — propagated span/trace-context tracing.

The monitor registry (docs/OBSERVABILITY.md) answers "how many / how fast
on average"; this package answers "what happened to THIS request / THIS
step". It is the rebuild's causally-linked host timeline — the role the
reference stack gives ``platform/profiler.h`` ``RecordEvent`` + the CUPTI
``device_tracer``, except spans here carry identity and parentage instead
of being flat anonymous intervals:

* a **trace** is one request's (or one training step's) whole story: a
  tree of spans sharing a ``trace_id``. ``ServingEngine.submit`` mints a
  trace per request; ``contrib.Trainer`` mints one per step.
* a **span** has a name, a parent, structured attributes (bucket,
  program serial, outcome, attempt #), a monotonic duration AND a
  wall-clock epoch anchor (so host-profiler events and spans merge onto
  one Chrome timeline — ``tools/timeline.py``).
* **context propagation** is explicit where threads change hands (the
  serving dispatch thread adopts the submit thread's context via
  :func:`attach` / a carried :class:`Span`) and ambient (thread-local)
  within a thread, so executor/retry spans nest under whatever request
  or step is in flight with no plumbing through call signatures.
* the **flight recorder** keeps the last N finished spans in a ring; on
  a ``WatchdogTimeout``, ``DeviceLostError``, replica divergence or
  ``BatchFailed`` the failure path calls :func:`record_incident` and the
  diagnosis ships WITH the request's span chain instead of a bare stack
  dump (``incidents()`` / the watchdog's stderr dump).

Overhead contract (the CI gate ``tools/trace_check.py`` asserts it):
tracing is OFF by default (``FLAGS_trace``); when off, :func:`span`
returns a module-level no-op singleton — no allocation, no lock, no
clock read on the hot path. Exporters: Chrome trace-event JSON
(mergeable with profiler host events) and JSONL.
"""
from __future__ import annotations

import contextlib
import itertools
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from .. import flags as _flags
from ..monitor.lockwitness import make_lock

__all__ = [
    "Span", "SpanContext", "enabled", "span", "root_span", "start_span",
    "current_span", "current_context", "attach", "get_collector",
    "SpanCollector", "spans", "clear", "to_chrome_events", "export_chrome",
    "export_jsonl", "record_incident", "incidents", "clear_incidents",
    "flight_recorder_spans", "trace_tree",
]

logger = logging.getLogger("paddle_tpu.trace")

# session prefix keeps ids unique across processes (the chaos gates fork
# workers whose dumps land in one artifact dir)
_SESSION = f"{os.getpid() & 0xFFFF:04x}{int(time.time()) & 0xFFFF:04x}"
_ids = itertools.count(1)


def _new_id() -> str:
    return f"{_SESSION}{next(_ids):08x}"


_enabled_cached: Optional[bool] = None
_enabled_epoch = -1


def enabled() -> bool:
    """``FLAGS_trace`` (default off — tracing is opt-in; the monitor
    registry stays the always-on layer). Memoized against the flags
    ``set_flags`` epoch so the disabled hot path costs an int compare,
    not an env read — the overhead contract ``tools/trace_check.py``
    gates on."""
    global _enabled_cached, _enabled_epoch
    if _flags._set_epoch != _enabled_epoch:
        _enabled_cached = bool(_flags.flag("trace"))
        _enabled_epoch = _flags._set_epoch
    return _enabled_cached


class SpanContext:
    """The propagatable identity of a span: ``(trace_id, span_id)``.
    Hand this (or the :class:`Span` itself) across threads/queues and
    open children with ``span(name, parent=ctx)``. For crossing a
    PROCESS boundary (the fleet tier's HTTP wire) use
    :meth:`to_wire`/:meth:`from_wire` — ids are plain strings, so a
    request admitted on a remote replica joins the caller's trace and
    the flight recorder on either side names the same ``trace_id``."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def to_wire(self) -> str:
        """``"<trace_id>/<span_id>"`` — the header/body value the fleet
        front-end ships (docs/SERVING.md wire schema)."""
        return f"{self.trace_id}/{self.span_id}"

    @staticmethod
    def from_wire(value: Optional[str]) -> Optional["SpanContext"]:
        """Parse :meth:`to_wire` output; None/empty/malformed values
        return None (an untraced caller costs nothing)."""
        if not value or "/" not in value:
            return None
        tid, sid = value.split("/", 1)
        if not tid:
            return None
        return SpanContext(tid, sid)

    def __repr__(self):
        return f"SpanContext(trace={self.trace_id}, span={self.span_id})"


class Span:
    """One named, timed, attributed interval in a trace. Context manager
    (closes on exit, recording the error type as ``status=error``) or
    closed explicitly with :meth:`end` — the serving engine carries
    request root spans across threads and settles them with the typed
    terminal outcome."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs",
                 "t0_mono", "t0_epoch", "duration_s", "status", "error",
                 "thread", "thread_name", "_ended", "_token")

    def __init__(self, name: str, trace_id: str, parent_id: Optional[str],
                 attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        # monotonic for durations, epoch for the shared wall-clock anchor
        # tools/timeline.py merges on (profiler RecordEvent carries the
        # same pair since this PR)
        self.t0_mono = time.perf_counter()
        self.t0_epoch = time.time()
        self.duration_s: Optional[float] = None
        self.status = "open"
        self.error: Optional[str] = None
        t = threading.current_thread()
        self.thread = t.ident or 0
        self.thread_name = t.name
        self._ended = False
        self._token = None          # ambient-stack entry while current

    # -- identity ---------------------------------------------------------
    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    # -- mutation ---------------------------------------------------------
    def set_attribute(self, key: str, value) -> "Span":
        self.attrs[key] = value
        return self

    def set_attributes(self, **kwargs) -> "Span":
        self.attrs.update(kwargs)
        return self

    def end(self, status: str = "ok",
            error: Optional[BaseException] = None) -> None:
        """Close the span exactly once (later calls no-op: a request span
        settled by the dispatch thread must not be re-closed by a racing
        sweep). Closed spans land in the collector and flight recorder."""
        if self._ended:
            return
        self._ended = True
        self.duration_s = time.perf_counter() - self.t0_mono
        if error is not None:
            self.status = "error"
            self.error = f"{type(error).__name__}: {error}"
        else:
            self.status = status
        _collector.record(self)

    # -- context manager / ambient stack ----------------------------------
    def __enter__(self) -> "Span":
        _push(self)
        self._token = True
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._token:
            _pop(self)
            self._token = None
        self.end(error=exc if isinstance(exc, BaseException) else None)
        return False

    def to_dict(self) -> dict:
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "t0_epoch": self.t0_epoch, "duration_s": self.duration_s,
                "status": self.status, "error": self.error,
                "thread": self.thread, "thread_name": self.thread_name,
                "attrs": dict(self.attrs)}

    def __repr__(self):
        return (f"Span({self.name!r}, trace={self.trace_id}, "
                f"status={self.status}, attrs={self.attrs})")


class _NoopSpan:
    """The disabled-path singleton: every operation is a no-op; entering
    it allocates nothing and touches no lock — the ``FLAGS_trace=0``
    hot-path cost is one flag read and one identity return."""

    __slots__ = ()
    name = ""
    trace_id = ""
    span_id = ""
    parent_id = None
    attrs: Dict[str, Any] = {}
    duration_s = None
    status = "noop"
    error = None
    t0_epoch = 0.0

    @property
    def context(self):
        return _NOOP_CONTEXT

    def set_attribute(self, key, value):
        return self

    def set_attributes(self, **kwargs):
        return self

    def end(self, status="ok", error=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def to_dict(self):
        return {}

    def __bool__(self):
        # `if request.span:` reads naturally at wiring sites
        return False


NOOP_SPAN = _NoopSpan()
_NOOP_CONTEXT = SpanContext("", "")


# ---------------------------------------------------------------------------
# ambient (thread-local) context
# ---------------------------------------------------------------------------

_tls = threading.local()


def _stack() -> List[Span]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _push(s: Span) -> None:
    _stack().append(s)


def _pop(s: Span) -> None:
    st = _stack()
    if st and st[-1] is s:
        st.pop()
    elif s in st:       # mis-nested exit: drop it wherever it sits
        st.remove(s)


def current_span() -> Optional[Span]:
    """The innermost open span on THIS thread (ambient context), or an
    attached foreign parent, or None."""
    st = _stack()
    if st:
        return st[-1]
    return getattr(_tls, "attached", None)


def current_context() -> Optional[SpanContext]:
    cur = current_span()
    if cur is None:
        return None
    return cur if isinstance(cur, SpanContext) else cur.context


@contextlib.contextmanager
def attach(parent):
    """Adopt ``parent`` (a :class:`Span` or :class:`SpanContext` carried
    from another thread) as this thread's ambient context for the block —
    the cross-thread propagation primitive: the serving dispatch thread
    attaches each request's root span while running its batch, so
    executor/retry spans parent correctly."""
    if not enabled() or parent is None or parent is NOOP_SPAN:
        yield
        return
    old = getattr(_tls, "attached", None)
    # only meaningful when the thread has no open span of its own
    _tls.attached = parent
    try:
        yield
    finally:
        _tls.attached = old


def start_span(name: str, parent=None, **attrs) -> Span:
    """Open (and return) a span WITHOUT entering it as ambient context —
    for spans whose lifetime crosses threads (the serving request root).
    ``parent``: a Span/SpanContext, or None to parent under the ambient
    current span; pass ``parent=False`` to force a new root trace."""
    if not enabled():
        return NOOP_SPAN
    return _make_span(name, parent, attrs)


def span(name: str, parent=None, **attrs) -> "Span":
    """Context-manager form: ``with trace.span("executor.step", ...)``.
    No-op singleton when tracing is off."""
    if not enabled():
        return NOOP_SPAN
    return _make_span(name, parent, attrs)


def root_span(name: str, **attrs) -> Span:
    """Open a new root span minting a fresh ``trace_id`` (ignores any
    ambient context — the serving/trainer trace entry points)."""
    if not enabled():
        return NOOP_SPAN
    return _make_span(name, False, attrs)


def _make_span(name, parent, attrs) -> Span:
    if parent is False:
        return Span(name, _new_id(), None, attrs)
    if parent is None:
        parent = current_span()
    if parent is None or parent is NOOP_SPAN:
        return Span(name, _new_id(), None, attrs)
    if isinstance(parent, Span):
        return Span(name, parent.trace_id, parent.span_id, attrs)
    if isinstance(parent, SpanContext):
        if not parent.trace_id:
            return Span(name, _new_id(), None, attrs)
        return Span(name, parent.trace_id, parent.span_id, attrs)
    raise TypeError(f"span parent must be a Span/SpanContext/None/False, "
                    f"got {type(parent).__name__}")


# ---------------------------------------------------------------------------
# collector + flight recorder
# ---------------------------------------------------------------------------

class SpanCollector:
    """Bounded store of finished spans (``FLAGS_trace_buffer_size``) plus
    the flight-recorder ring (``FLAGS_flight_recorder_size``) and the
    incident list. One module-level instance; thread-safe."""

    def __init__(self):
        self._lock = make_lock("SpanCollector._lock")
        self._spans: Optional[deque] = None
        self._flight: Optional[deque] = None
        self._incidents: deque = deque(maxlen=32)

    def _ensure(self) -> None:
        if self._spans is None:
            from ..flags import flag

            self._spans = deque(maxlen=max(64,
                                           int(flag("trace_buffer_size"))))
            n = int(flag("flight_recorder_size"))
            self._flight = deque(maxlen=max(1, n)) if n > 0 else None

    def record(self, s: Span) -> None:
        with self._lock:
            self._ensure()
            self._spans.append(s)
            if self._flight is not None:
                self._flight.append(s)

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans or ())

    def flight_spans(self) -> List[Span]:
        with self._lock:
            return list(self._flight or ())

    def record_incident(self, kind: str, error: Optional[BaseException]
                        = None, context=None, detail: str = "") -> dict:
        """Snapshot the flight recorder into one incident record: the
        last N finished spans, every still-open span on the calling
        thread, and (when ``context`` names a trace) that trace's full
        chain pulled from the ring. Returns the incident dict (also kept
        in :func:`incidents` and logged)."""
        trace_id = ""
        if context is not None:
            trace_id = getattr(context, "trace_id", "") or ""
        open_spans = [s.to_dict() for s in _stack()]
        with self._lock:
            ring = list(self._flight or ())
        recent = [s.to_dict() for s in ring]
        chain = [d for d in recent if trace_id and d["trace_id"] == trace_id]
        incident = {
            "kind": kind, "time_epoch": time.time(),
            "error": f"{type(error).__name__}: {error}" if error else "",
            "detail": detail, "trace_id": trace_id,
            "trace_chain": chain, "open_spans": open_spans,
            "recent_spans": recent,
            "flight_recorder_enabled": self._flight is not None,
        }
        with self._lock:
            self._incidents.append(incident)
        logger.error(
            "flight recorder: incident '%s'%s — %d recent span(s), "
            "%d in the failing trace%s", kind,
            f" ({incident['error']})" if incident["error"] else "",
            len(recent), len(chain),
            "" if self._flight is not None else
            " [flight recorder DISABLED — span context lost]")
        return incident

    def incidents(self) -> List[dict]:
        with self._lock:
            return list(self._incidents)

    def clear(self) -> None:
        with self._lock:
            if self._spans is not None:
                self._spans.clear()
            if self._flight is not None:
                self._flight.clear()

    def reset(self) -> None:
        """Drop spans, incidents AND the flag-derived sizing (test
        isolation: a test flipping FLAGS_flight_recorder_size gets a
        fresh ring)."""
        with self._lock:
            self._spans = None
            self._flight = None
            self._incidents.clear()


_collector = SpanCollector()


def get_collector() -> SpanCollector:
    return _collector


def spans() -> List[Span]:
    """Every finished span still in the bounded buffer (oldest first)."""
    return _collector.spans()


def clear() -> None:
    _collector.clear()


def flight_recorder_spans() -> List[Span]:
    return _collector.flight_spans()


def record_incident(kind: str, error: Optional[BaseException] = None,
                    context=None, detail: str = "") -> dict:
    """Dump the flight recorder for a failure (see module docstring for
    the trigger list). Safe to call with tracing off — the incident then
    records ``flight_recorder_enabled: False`` and no spans (the
    negative control ``tools/trace_check.py`` asserts exactly that)."""
    return _collector.record_incident(kind, error=error, context=context,
                                      detail=detail)


def incidents() -> List[dict]:
    return _collector.incidents()


def clear_incidents() -> None:
    with _collector._lock:
        _collector._incidents.clear()


def trace_tree(trace_id: str) -> List[Span]:
    """Finished spans of one trace, parents before children (stable
    within one parent by start time)."""
    members = [s for s in _collector.spans() if s.trace_id == trace_id]
    by_parent: Dict[Optional[str], List[Span]] = {}
    for s in members:
        by_parent.setdefault(s.parent_id, []).append(s)
    ids = {s.span_id for s in members}
    out: List[Span] = []

    def walk(pid):
        for s in sorted(by_parent.get(pid, ()), key=lambda x: x.t0_epoch):
            out.append(s)
            walk(s.span_id)

    # roots: no parent, or parent not in the buffer (evicted)
    walk(None)
    for s in sorted(members, key=lambda x: x.t0_epoch):
        if s.parent_id and s.parent_id not in ids and s not in out:
            out.append(s)
            walk(s.span_id)
    return out


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def to_chrome_events(span_list: Optional[List[Span]] = None,
                     pid: int = 1) -> List[dict]:
    """Chrome trace-event dicts (``ph: X``) with ``ts`` on the EPOCH
    wall clock in microseconds — the shared anchor that lets
    ``tools/timeline.py`` merge these with profiler host events.
    NOTE: ``tools/timeline.py`` carries a stdlib-only copy of this
    mapping (it must not import the framework); change the event schema
    in both places."""
    out = []
    for s in (span_list if span_list is not None else spans()):
        if s.duration_s is None:
            continue
        args = {"trace_id": s.trace_id, "span_id": s.span_id,
                "status": s.status}
        if s.parent_id:
            args["parent_id"] = s.parent_id
        if s.error:
            args["error"] = s.error
        args.update({k: _jsonable(v) for k, v in s.attrs.items()})
        out.append({"name": s.name, "ph": "X",
                    "ts": s.t0_epoch * 1e6,
                    "dur": s.duration_s * 1e6,
                    "pid": pid, "tid": s.thread,
                    "cat": "trace", "args": args})
    return out


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return repr(v)


def export_chrome(path: str,
                  span_list: Optional[List[Span]] = None) -> int:
    """Write a self-contained Chrome trace (open in Perfetto /
    chrome://tracing). Returns the event count. For a merged view with
    profiler RecordEvent host spans use ``tools/timeline.py``."""
    events = to_chrome_events(span_list)
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return len(events)


def export_jsonl(path: str,
                 span_list: Optional[List[Span]] = None) -> int:
    """One JSON object per line per finished span (ingestion-friendly).
    Returns the span count."""
    sl = span_list if span_list is not None else spans()
    with open(path, "w") as f:
        for s in sl:
            f.write(json.dumps(s.to_dict()) + "\n")
    return len(sl)
