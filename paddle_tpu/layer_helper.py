"""LayerHelper: shared machinery for layer functions.

Reference: python/paddle/fluid/layer_helper.py + layer_helper_base.py —
creates parameters (with initializer ops in the startup program), temp output
vars, and appends ops to the main program.
"""
from __future__ import annotations

from typing import Optional

from . import initializer as init_mod
from . import unique_name
from .framework import default_main_program, default_startup_program
from .param_attr import ParamAttr


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name if name is not None else unique_name.generate(layer_type)

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block()

    @property
    def param_attr(self) -> ParamAttr:
        return ParamAttr._to_attr(self.kwargs.get("param_attr"))

    @property
    def bias_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("bias_attr"))

    def multiple_param_attr(self, length: int):
        pa = self.param_attr
        if isinstance(pa, ParamAttr):
            pa = [pa] * length
        return pa

    def create_parameter(self, attr: ParamAttr, shape, dtype,
                         is_bias: bool = False, default_initializer=None):
        attr = attr if isinstance(attr, ParamAttr) else ParamAttr._to_attr(attr)
        if attr is False:
            return None
        suffix = "b" if is_bias else "w"
        name = attr.name if attr.name else unique_name.generate(
            ".".join([self.name, suffix]))
        initializer = attr.initializer or default_initializer
        if initializer is None:
            initializer = (init_mod.Constant(0.0) if is_bias
                           else init_mod.Xavier())
        shape = [int(s) for s in shape]
        kwargs = attr._to_kwargs()
        kwargs.pop("name", None)
        # param in main program's global block...
        param = self.main_program.global_block.create_parameter(
            name, shape, dtype, **kwargs)
        # ...and a twin + init op in the startup program (reference
        # layer_helper_base.py: startup gets the initializer op).
        startup_blk = self.startup_program.global_block
        if not startup_blk.has_var(name):
            sp = startup_blk.create_parameter(name, shape, dtype, **kwargs)
            initializer(sp, startup_blk)
        return param

    def create_variable_for_type_inference(self, dtype, stop_gradient=False):
        return self.block.create_var(
            name=unique_name.generate(".".join([self.name, "tmp"])),
            dtype=dtype, stop_gradient=stop_gradient)

    # reference alias
    create_tmp_variable = create_variable_for_type_inference

    def create_global_variable(self, shape, dtype, persistable=False,
                               stop_gradient=True, name=None):
        return self.main_program.global_block.create_var(
            name=name or unique_name.generate(".".join([self.name, "global"])),
            shape=shape, dtype=dtype, persistable=persistable,
            stop_gradient=stop_gradient)

    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        return self.block.append_op(type, inputs=inputs, outputs=outputs,
                                    attrs=attrs)

    def append_bias_op(self, input_var, dim_start: int = 1, dim_end=None):
        """Reference layer_helper.py append_bias_op: bias covers dims
        [dim_start, dim_end) — conv passes (1, 2) for a per-channel bias."""
        bias_attr = self.bias_attr
        if bias_attr is False or bias_attr is None:
            return input_var
        size = list(input_var.shape[dim_start:dim_end])
        b = self.create_parameter(bias_attr, shape=size, dtype=input_var.dtype,
                                  is_bias=True)
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op("elementwise_add", inputs={"X": input_var, "Y": b},
                       outputs={"Out": tmp}, attrs={"axis": dim_start})
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act_type = act.pop("type")
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(act_type, inputs={"X": input_var}, outputs={"Out": tmp},
                       attrs=act)
        return tmp
