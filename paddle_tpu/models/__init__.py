"""Model zoo: the five BASELINE.json configs built with the fluid-style API.

These mirror the reference's book/ test models and benchmark configs
(reference: python/paddle/fluid/tests/book/, BASELINE.md):
MNIST MLP, ResNet-50, BERT, Transformer NMT, DeepFM CTR.
"""
from .mlp import build_mnist_mlp  # noqa: F401
from .resnet import build_resnet  # noqa: F401
from .bert import BertConfig, build_bert_pretrain  # noqa: F401
from .deepfm import build_deepfm  # noqa: F401
from .gpt import (GptConfig, build_gpt_decode,  # noqa: F401
                  build_gpt_generative, build_gpt_prefill)
from .seq2seq import (build_seq2seq_infer, build_seq2seq_train,  # noqa: F401
                      build_seq2seq_train_varlen)
