"""BERT pretraining model (BASELINE config #3) built with fluid-style layers.

Transformer encoder with learned position embeddings, masked-LM +
next-sentence losses, Adam with linear warmup — the reference-era BERT recipe,
expressed as a Program whose whole train step compiles to one XLA executable.
All matmuls are batch-major and padded to MXU-friendly sizes by construction
(hidden % 128 == 0 for the standard configs).
"""
from __future__ import annotations

import dataclasses
import math

from .. import layers, optimizer as opt_mod
from ..framework import Program, program_guard
from ..initializer import Normal, TruncatedNormal
from ..param_attr import ParamAttr


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    initializer_range: float = 0.02

    @staticmethod
    def base():
        return BertConfig()

    @staticmethod
    def tiny():
        return BertConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                          num_heads=2, intermediate_size=512, max_position=128)


def _attention(x, mask, cfg: BertConfig, prefix: str, is_test: bool = False):
    """Multi-head self-attention via the fused_multihead_attention op —
    a Pallas flash kernel on TPU, softmax primitives elsewhere
    (ops/fused_attention.py). x: [B, S, H]; mask: [B, 1, 1, S] additive
    (-10000 on pads)."""
    B, S, H = -1, x.shape[1], cfg.hidden_size
    nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads

    def proj(name):
        return layers.fc(x, H, num_flatten_dims=2,
                         param_attr=ParamAttr(
                             name=f"{prefix}_{name}_w",
                             initializer=TruncatedNormal(0.0, cfg.initializer_range)),
                         bias_attr=ParamAttr(name=f"{prefix}_{name}_b"))

    q, k, v = proj("q"), proj("k"), proj("v")
    # [B,S,H] -> [B,nh,S,hd]
    def split_heads(t):
        t = layers.reshape(t, [0, S, nh, hd])
        return layers.transpose(t, [0, 2, 1, 3])

    q, k, v = split_heads(q), split_heads(k), split_heads(v)
    ctxv = layers.fused_multihead_attention(
        q, k, v, bias_qk=mask, scale=1.0 / math.sqrt(hd),
        attn_dropout=cfg.attention_dropout, is_test=is_test)
    ctxv = layers.transpose(ctxv, [0, 2, 1, 3])
    ctxv = layers.reshape(ctxv, [0, S, H])
    out = layers.fc(ctxv, H, num_flatten_dims=2,
                    param_attr=ParamAttr(
                        name=f"{prefix}_out_w",
                        initializer=TruncatedNormal(0.0, cfg.initializer_range)),
                    bias_attr=ParamAttr(name=f"{prefix}_out_b"))
    return out


def _encoder_layer(x, mask, cfg: BertConfig, prefix: str, is_test: bool = False):
    att = _attention(x, mask, cfg, prefix + "_att", is_test=is_test)
    att = layers.dropout(att, cfg.hidden_dropout, is_test=is_test,
                         dropout_implementation="upscale_in_train")
    x = layers.layer_norm(layers.elementwise_add(x, att), begin_norm_axis=2)
    ffn = layers.fc(x, cfg.intermediate_size, num_flatten_dims=2, act="gelu",
                    param_attr=ParamAttr(
                        name=f"{prefix}_ffn1_w",
                        initializer=TruncatedNormal(0.0, cfg.initializer_range)),
                    bias_attr=ParamAttr(name=f"{prefix}_ffn1_b"))
    ffn = layers.fc(ffn, cfg.hidden_size, num_flatten_dims=2,
                    param_attr=ParamAttr(
                        name=f"{prefix}_ffn2_w",
                        initializer=TruncatedNormal(0.0, cfg.initializer_range)),
                    bias_attr=ParamAttr(name=f"{prefix}_ffn2_b"))
    ffn = layers.dropout(ffn, cfg.hidden_dropout, is_test=is_test,
                         dropout_implementation="upscale_in_train")
    return layers.layer_norm(layers.elementwise_add(x, ffn), begin_norm_axis=2)


def build_bert_pretrain(cfg: BertConfig = None, seq_len: int = 128,
                        lr: float = 1e-4, build_optimizer: bool = True,
                        is_test: bool = False, amp: bool = False):
    """Returns the pretraining Program: feeds are
    src_ids/pos_ids/sent_ids/input_mask [B,S], mask_label [B,S] (with -100 on
    unmasked positions), next_sent_label [B,1]."""
    cfg = cfg or BertConfig.base()
    main, startup = Program(), Program()
    with program_guard(main, startup):
        src = layers.data("src_ids", shape=[seq_len], dtype="int64")
        pos = layers.data("pos_ids", shape=[seq_len], dtype="int64")
        sent = layers.data("sent_ids", shape=[seq_len], dtype="int64")
        input_mask = layers.data("input_mask", shape=[seq_len],
                                 dtype="float32")
        mask_label = layers.data("mask_label", shape=[seq_len], dtype="int64")
        nsp_label = layers.data("next_sent_label", shape=[1], dtype="int64")

        emb_init = ParamAttr(name="word_embedding",
                             initializer=TruncatedNormal(
                                 0.0, cfg.initializer_range))
        x = layers.embedding(src, (cfg.vocab_size, cfg.hidden_size),
                             param_attr=emb_init)
        x = layers.elementwise_add(
            x, layers.embedding(pos, (cfg.max_position, cfg.hidden_size),
                                param_attr=ParamAttr(
                                    name="pos_embedding",
                                    initializer=TruncatedNormal(
                                        0.0, cfg.initializer_range))))
        x = layers.elementwise_add(
            x, layers.embedding(sent, (cfg.type_vocab_size, cfg.hidden_size),
                                param_attr=ParamAttr(
                                    name="sent_embedding",
                                    initializer=TruncatedNormal(
                                        0.0, cfg.initializer_range))))
        x = layers.layer_norm(x, begin_norm_axis=2)
        x = layers.dropout(x, cfg.hidden_dropout, is_test=is_test,
                           dropout_implementation="upscale_in_train")

        # additive attention mask [B,1,1,S]: (mask-1)*10000
        m = layers.scale(input_mask, scale=10000.0, bias=-10000.0)
        m = layers.unsqueeze(m, [1, 2])

        for i in range(cfg.num_layers):
            x = _encoder_layer(x, m, cfg, f"layer{i}", is_test=is_test)

        # -- masked LM head: full-seq vocab logits, ignore_index=-100
        mlm = layers.fc(x, cfg.hidden_size, num_flatten_dims=2, act="gelu",
                        param_attr=ParamAttr(name="mlm_trans_w",
                                             initializer=TruncatedNormal(
                                                 0.0, cfg.initializer_range)),
                        bias_attr=ParamAttr(name="mlm_trans_b"))
        mlm = layers.layer_norm(mlm, begin_norm_axis=2)
        word_emb = main.global_block.var("word_embedding")
        vocab_logits = layers.matmul(mlm, word_emb, transpose_y=True)
        mlm_loss = layers.softmax_with_cross_entropy(
            vocab_logits, layers.unsqueeze(mask_label, [2]),
            ignore_index=-100)
        # mean over the actually-masked tokens
        is_masked = layers.cast(
            layers.not_equal(layers.unsqueeze(mask_label, [2]),
                             layers.fill_constant([1], "int64", -100)),
            "float32")
        # the masked-token count is a label statistic, not a differentiable
        # quantity: fence it so append_backward doesn't emit a dead grad
        # chain (max_grad/reduce_sum_grad with no consumer — PT720)
        is_masked.stop_gradient = True
        masked_count = layers.reduce_sum(is_masked)
        masked_count.stop_gradient = True
        denom = layers.elementwise_max(
            masked_count,
            layers.fill_constant([1], "float32", 1.0))
        denom.stop_gradient = True
        mlm_loss = layers.elementwise_div(layers.reduce_sum(mlm_loss), denom)

        # -- next-sentence head on [CLS]
        cls = layers.slice(x, axes=[1], starts=[0], ends=[1])
        cls = layers.reshape(cls, [0, cfg.hidden_size])
        pooled = layers.fc(cls, cfg.hidden_size, act="tanh",
                           param_attr=ParamAttr(name="pooler_w",
                                                initializer=TruncatedNormal(
                                                    0.0, cfg.initializer_range)),
                           bias_attr=ParamAttr(name="pooler_b"))
        nsp_logits = layers.fc(pooled, 2,
                               param_attr=ParamAttr(name="nsp_w",
                                                    initializer=TruncatedNormal(
                                                        0.0, cfg.initializer_range)),
                               bias_attr=ParamAttr(name="nsp_b"))
        nsp_loss = layers.mean(
            layers.softmax_with_cross_entropy(nsp_logits, nsp_label))

        loss = layers.elementwise_add(mlm_loss, nsp_loss)
        if build_optimizer:
            opt = opt_mod.Adam(learning_rate=lr)
            if amp:
                from ..contrib import mixed_precision as _mp

                opt = _mp.decorate(opt)
            opt.minimize(loss)
    return {"main": main, "startup": startup, "loss": loss,
            "mlm_loss": mlm_loss, "nsp_loss": nsp_loss,
            "feeds": ("src_ids", "pos_ids", "sent_ids", "input_mask",
                      "mask_label", "next_sent_label")}
