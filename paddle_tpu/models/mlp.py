"""MNIST MLP (BASELINE config #1; reference book/test_recognize_digits.py)."""
from __future__ import annotations

from .. import layers, optimizer as opt_mod
from ..framework import Program, program_guard


def build_mnist_mlp(hidden=(200, 200), lr=0.01, optimizer="sgd"):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        img = layers.data("img", shape=[784], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        h = img
        for width in hidden:
            h = layers.fc(h, width, act="relu")
        logits = layers.fc(h, 10)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(logits, label)
        if optimizer == "sgd":
            opt = opt_mod.SGD(learning_rate=lr)
        else:
            opt = opt_mod.Adam(learning_rate=lr)
        opt.minimize(loss)
    return {"main": main, "startup": startup, "loss": loss, "acc": acc,
            "feeds": ("img", "label"), "logits": logits}
