"""ResNet for ImageNet/CIFAR (BASELINE config #2).

Reference analogue: the ResNet-50 used by Paddle's fp16 benchmarks
(paddle/contrib/float16/float16_benchmark.md) and
tests/book/test_image_classification. Built entirely from fluid-style layers
(conv2d/batch_norm/pool2d), NCHW layout; XLA lays it out for the MXU.
"""
from __future__ import annotations

from .. import layers, optimizer as opt_mod
from ..framework import Program, program_guard

_DEPTH_CFG = {
    18: ("basic", (2, 2, 2, 2)),
    34: ("basic", (3, 4, 6, 3)),
    50: ("bottleneck", (3, 4, 6, 3)),
    101: ("bottleneck", (3, 4, 23, 3)),
    152: ("bottleneck", (3, 8, 36, 3)),
}


def _conv_bn(x, filters, ksize, stride=1, act=None):
    c = layers.conv2d(x, filters, ksize, stride=stride,
                      padding=(ksize - 1) // 2, bias_attr=False)
    return layers.batch_norm(c, act=act)


def _bottleneck(x, filters, stride):
    c = _conv_bn(x, filters, 1, act="relu")
    c = _conv_bn(c, filters, 3, stride=stride, act="relu")
    c = _conv_bn(c, filters * 4, 1)
    if x.shape[1] != filters * 4 or stride != 1:
        x = _conv_bn(x, filters * 4, 1, stride=stride)
    return layers.relu(layers.elementwise_add(c, x))


def _basic(x, filters, stride):
    c = _conv_bn(x, filters, 3, stride=stride, act="relu")
    c = _conv_bn(c, filters, 3)
    if x.shape[1] != filters or stride != 1:
        x = _conv_bn(x, filters, 1, stride=stride)
    return layers.relu(layers.elementwise_add(c, x))


def build_resnet(depth=50, class_num=1000, image_shape=(3, 224, 224),
                 lr=0.1, momentum=0.9, build_optimizer=True, amp=False):
    block_fn_name, counts = _DEPTH_CFG[depth]
    block_fn = _bottleneck if block_fn_name == "bottleneck" else _basic
    main, startup = Program(), Program()
    with program_guard(main, startup):
        img = layers.data("img", shape=list(image_shape), dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        x = _conv_bn(img, 64, 7, stride=2, act="relu")
        x = layers.pool2d(x, 3, "max", pool_stride=2, pool_padding=1)
        for stage, n in enumerate(counts):
            filters = 64 * (2 ** stage)
            for i in range(n):
                stride = 2 if (i == 0 and stage > 0) else 1
                x = block_fn(x, filters, stride)
        x = layers.pool2d(x, 1, "avg", global_pooling=True)
        logits = layers.fc(x, class_num)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(logits, label)
        if build_optimizer:
            opt = opt_mod.Momentum(learning_rate=lr, momentum=momentum)
            if amp:
                from ..contrib import mixed_precision as _mp

                opt = _mp.decorate(opt)
            opt.minimize(loss)
    return {"main": main, "startup": startup, "loss": loss, "acc": acc,
            "feeds": ("img", "label"), "logits": logits}
