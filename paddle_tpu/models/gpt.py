"""GPT: causal decoder-only transformer for generative serving.

The autoregressive workload class (ROADMAP item 1): a pre-LN GPT-2-style
decoder expressed as fluid Programs, built TWICE over one shared weight set:

* **prefill** — full-sequence causal forward over a padded prompt bucket.
  Runs once per admitted request batch: computes every layer's K/V for the
  whole prompt, bulk-writes them into the paged KV caches
  (``layers.kv_cache_append``), samples the FIRST generated token from the
  last real prompt position, and merges the per-sequence generation state
  (current token, position) under a slot mask so a refill touches only the
  slots being prefilled while their neighbours keep decoding.
* **decode** — one token for every sequence in the batch, at per-sequence
  positions. No feeds at all: the current token, position and paged KV
  caches are persistable state threaded through the executor — which is
  what lets a whole decode chunk run as ONE ``run_chained`` scan dispatch
  with the caches donated (liveness-proven in-place update) through the
  carry. Sampling happens in-program (``layers.sample_token``), so the
  sampled token feeds the next scan iteration without a host round-trip.

Weight sharing: both builders name every parameter explicitly
(``gpt_*``), so the two programs resolve to the same scope entries; only
the prefill builder's startup program initializes them (the decode builder
discards its startup). State-var shapes are returned for the serving
layer's reset path (``serving.generate``).
"""
from __future__ import annotations

import dataclasses
import math

from .. import layers
from ..framework import Program, program_guard
from ..initializer import TruncatedNormal
from ..param_attr import ParamAttr

__all__ = ["GptConfig", "build_gpt_prefill", "build_gpt_decode",
           "build_gpt_chunk", "build_gpt_generative"]


@dataclasses.dataclass
class GptConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 1024
    initializer_range: float = 0.02

    @staticmethod
    def base():
        return GptConfig()

    @staticmethod
    def tiny():
        """CI-sized config (the load_check --decode probe)."""
        return GptConfig(vocab_size=128, hidden_size=64, num_layers=2,
                         num_heads=2, intermediate_size=128,
                         max_position=128)


def _attr(name: str, rng: float):
    return ParamAttr(name=name, initializer=TruncatedNormal(0.0, rng))


def _embed(ids, cfg: GptConfig):
    """Token + (separately applied) position embeddings share one builder
    so prefill and decode stay bit-identical."""
    return layers.embedding(ids, (cfg.vocab_size, cfg.hidden_size),
                            param_attr=_attr("gpt_word_emb",
                                             cfg.initializer_range))


def _pos_embed(pos_ids, cfg: GptConfig):
    return layers.embedding(pos_ids, (cfg.max_position, cfg.hidden_size),
                            param_attr=_attr("gpt_pos_emb",
                                             cfg.initializer_range))


def _ln(x, prefix: str, axis: int = 2):
    return layers.layer_norm(x, begin_norm_axis=axis,
                             param_attr=ParamAttr(name=f"{prefix}_scale"),
                             bias_attr=ParamAttr(name=f"{prefix}_bias"))


def _proj(x, size, name, cfg: GptConfig, act=None):
    return layers.fc(x, size, num_flatten_dims=2, act=act,
                     param_attr=_attr(f"{name}_w", cfg.initializer_range),
                     bias_attr=ParamAttr(name=f"{name}_b"))


def _split_heads(t, seq_len, cfg: GptConfig):
    """[B, S, H] -> [B, nh, S, hd]."""
    t = layers.reshape(t, [0, seq_len, cfg.num_heads,
                           cfg.hidden_size // cfg.num_heads])
    return layers.transpose(t, [0, 2, 1, 3])


def _merge_heads(t, seq_len, cfg: GptConfig):
    """[B, nh, S, hd] -> [B, S, H]."""
    t = layers.transpose(t, [0, 2, 1, 3])
    return layers.reshape(t, [0, seq_len, cfg.hidden_size])


def _mlp(x, prefix: str, cfg: GptConfig):
    h = _proj(x, cfg.intermediate_size, f"{prefix}_ffn1", cfg, act="gelu")
    return _proj(h, cfg.hidden_size, f"{prefix}_ffn2", cfg)


def _logits(h2d, cfg: GptConfig, block):
    """[B|BS, H] hidden rows -> vocab logits via the tied word embedding."""
    word_emb = block.var("gpt_word_emb")
    return layers.matmul(h2d, word_emb, transpose_y=True)


def _state_vars(block, cfg: GptConfig, batch_slots: int, max_seq: int):
    """Declare (or re-declare, in the sibling program) the generation
    state: current token, current position, the per-slot ACTIVE mask, and
    one paged K/V cache pair per layer. Persistable — the executor
    threads them step to step, and the liveness pass proves them
    donatable (each is read and written by ops that never observe a
    pre-write value after the write).

    ``gpt_gen_active`` [B, 1] float32 is 1 while a slot is mid-stream
    (set in-program when a prefill/chunk commits a slot's first token,
    zeroed host-side on retire/reset): the decode program gates its cache
    appends and state merges on it, so retired slots and slots still
    inside a chunked prefill neither advance nor write K/V rows while
    their neighbours decode."""
    hd = cfg.hidden_size // cfg.num_heads
    sv = {}

    def mk(name, shape, dtype):
        block.create_var(name=name, shape=tuple(shape), dtype=dtype,
                         persistable=True, stop_gradient=True)
        sv[name] = (tuple(shape), dtype)
        return block.var(name)

    tok = mk("gpt_gen_tokens", (batch_slots, 1), "int64")
    pos = mk("gpt_gen_pos", (batch_slots, 1), "int64")
    active = mk("gpt_gen_active", (batch_slots, 1), "float32")
    caches = []
    for i in range(cfg.num_layers):
        ck = mk(f"gpt_kv_k_{i}", (batch_slots, cfg.num_heads, max_seq, hd),
                "float32")
        cv = mk(f"gpt_kv_v_{i}", (batch_slots, cfg.num_heads, max_seq, hd),
                "float32")
        caches.append((ck, cv))
    return tok, pos, active, caches, sv


def _merge_state(new, old, mask_i64, inv_mask_i64):
    """masked select: new where the slot mask is set, old elsewhere; the
    reads of ``old`` precede the caller's write-back, keeping the state
    var donation-safe."""
    return layers.elementwise_add(layers.elementwise_mul(new, mask_i64),
                                  layers.elementwise_mul(old, inv_mask_i64))


def _activate_slots(active, mask_f32, one_f32):
    """active := 1 where ``mask_f32`` is set, unchanged elsewhere (the
    float face of :func:`_merge_state`): a prefill/chunk that commits a
    slot's first token flips that slot's decode gate in-program."""
    inv = layers.elementwise_sub(one_f32, mask_f32)
    layers.assign(layers.elementwise_add(
        mask_f32, layers.elementwise_mul(active, inv)), output=active)


def build_gpt_prefill(cfg: GptConfig, batch_slots: int, prompt_bucket: int,
                      max_seq: int, page_size: int = 8,
                      strategy: str = "greedy", temperature: float = 1.0,
                      top_k: int = 0, fetch_logits: bool = False,
                      startup: Program = None):
    """The full-sequence phase for ONE prompt bucket (prompts padded to
    ``prompt_bucket`` tokens). Feeds (all with the static ``batch_slots``
    leading dim — every dispatch carries the full slot batch):

    * ``prompt_ids``  [B, S] int64 — padded prompt tokens;
    * ``prompt_pos``  [B, S] int64 — position ids (0..S-1);
    * ``prompt_mask`` [B, S] float32 — 1 on real tokens, 0 on pads;
    * ``prompt_len``  [B, 1] int64 — real prompt length per slot;
    * ``slot_mask``   [B, 1] float32 — 1 on slots being (re)filled; other
      slots' caches and generation state pass through untouched.

    Pass ``startup`` to share one startup program across buckets (only
    the first call's parameter initializers land there)."""
    if prompt_bucket > max_seq:
        raise ValueError(f"prompt_bucket {prompt_bucket} exceeds the KV "
                         f"capacity max_seq {max_seq}")
    if max_seq % page_size:
        raise ValueError(f"max_seq {max_seq} must be a whole number of "
                         f"pages of page_size {page_size}")
    B, S = batch_slots, prompt_bucket
    nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
    main = Program()
    own_startup = startup is None
    startup = startup if startup is not None else Program()
    throwaway = Program()
    with program_guard(main, startup if own_startup else throwaway):
        ids = layers.data("prompt_ids", shape=[B, S], dtype="int64",
                          append_batch_size=False)
        pos_ids = layers.data("prompt_pos", shape=[B, S], dtype="int64",
                              append_batch_size=False)
        pmask = layers.data("prompt_mask", shape=[B, S], dtype="float32",
                            append_batch_size=False)
        plen = layers.data("prompt_len", shape=[B, 1], dtype="int64",
                           append_batch_size=False)
        smask = layers.data("slot_mask", shape=[B, 1], dtype="float32",
                            append_batch_size=False)
        tok, pos, active, caches, sv = _state_vars(main.global_block, cfg,
                                                   B, max_seq)

        x = layers.elementwise_add(_embed(ids, cfg), _pos_embed(pos_ids, cfg))
        # additive key-padding bias [B,1,1,S]: (mask-1)*10000, bert idiom
        bias = layers.unsqueeze(
            layers.scale(pmask, scale=10000.0, bias=-10000.0), [1, 2])
        zero_pos = layers.fill_constant([B, 1], "int64", 0)
        for i in range(cfg.num_layers):
            p = f"gpt_l{i}"
            h = _ln(x, f"{p}_ln1")
            q = _split_heads(_proj(h, cfg.hidden_size, f"{p}_q", cfg), S, cfg)
            k = _split_heads(_proj(h, cfg.hidden_size, f"{p}_k", cfg), S, cfg)
            v = _split_heads(_proj(h, cfg.hidden_size, f"{p}_v", cfg), S, cfg)
            ck, cv = caches[i]
            # bulk KV write: whole prompt at position 0, slot-masked so
            # neighbouring sequences' pages survive a refill
            layers.kv_cache_append(ck, k, zero_pos, slot_mask=smask)
            layers.kv_cache_append(cv, v, zero_pos, slot_mask=smask)
            ctx = layers.fused_multihead_attention(
                q, k, v, bias_qk=bias, causal=True,
                scale=1.0 / math.sqrt(hd), is_test=True)
            att = _proj(_merge_heads(ctx, S, cfg), cfg.hidden_size,
                        f"{p}_out", cfg)
            x = layers.elementwise_add(x, att)
            h = _ln(x, f"{p}_ln2")
            x = layers.elementwise_add(x, _mlp(h, p, cfg))
        h = _ln(x, "gpt_lnf")

        one = layers.fill_constant([B, 1], "int64", 1)
        last = layers.elementwise_sub(plen, one)
        last_h = layers.sequence_gather(h, last)            # [B, H]
        logits = _logits(last_h, cfg, main.global_block)    # [B, V]
        first_tok = layers.sample_token(logits, strategy=strategy,
                                        temperature=temperature, top_k=top_k)

        mask_i64 = layers.cast(smask, "int64")
        inv = layers.elementwise_sub(one, mask_i64)
        layers.assign(_merge_state(first_tok, tok, mask_i64, inv),
                      output=tok)
        layers.assign(_merge_state(plen, pos, mask_i64, inv), output=pos)
        one_f = layers.fill_constant([B, 1], "float32", 1.0)
        _activate_slots(active, smask, one_f)

        out = {"main": main, "startup": startup,
               "first_token": first_tok, "state_vars": sv,
               "feeds": ("prompt_ids", "prompt_pos", "prompt_mask",
                         "prompt_len", "slot_mask")}
        if fetch_logits:
            # all-position logits for the continuity tests
            flat = layers.reshape(h, [0, S * cfg.hidden_size])
            flat = layers.reshape(flat, [B * S, cfg.hidden_size])
            all_logits = layers.reshape(
                _logits(flat, cfg, main.global_block),
                [B, S, cfg.vocab_size])
            out["logits"] = all_logits
            out["last_logits"] = logits
    return out


def build_gpt_decode(cfg: GptConfig, batch_slots: int, max_seq: int,
                     page_size: int = 8, strategy: str = "greedy",
                     temperature: float = 1.0, top_k: int = 0,
                     fetch_logits: bool = False):
    """The per-token phase: no feeds — everything (current token, position,
    paged KV caches) is persistable state, so ``run_chained`` scans whole
    decode chunks with the caches donated through the carry. Fetch
    ``next_token`` ([B, 1] int64; stacked [steps, B, 1] under
    ``run_chained``). Sequences at different positions batch together: the
    position is data, not shape, so every chunk reuses one executable."""
    if max_seq % page_size:
        raise ValueError(f"max_seq {max_seq} must be a whole number of "
                         f"pages of page_size {page_size}")
    B = batch_slots
    nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
    main, throwaway = Program(), Program()
    with program_guard(main, throwaway):
        tok, pos, active, caches, sv = _state_vars(main.global_block, cfg,
                                                   B, max_seq)
        pos_cap = layers.fill_constant([B, 1], "int64",
                                       cfg.max_position - 1)
        pos_emb_ids = layers.elementwise_min(pos, pos_cap)
        # lookup_table squeezes the trailing ids dim ([B,1] -> [B,H]);
        # restore the length-1 sequence axis the layer stack expects
        x = layers.unsqueeze(
            layers.elementwise_add(_embed(tok, cfg),
                                   _pos_embed(pos_emb_ids, cfg)), [1])
        for i in range(cfg.num_layers):
            p = f"gpt_l{i}"
            h = _ln(x, f"{p}_ln1")
            q = _split_heads(_proj(h, cfg.hidden_size, f"{p}_q", cfg), 1, cfg)
            k = _split_heads(_proj(h, cfg.hidden_size, f"{p}_k", cfg), 1, cfg)
            v = _split_heads(_proj(h, cfg.hidden_size, f"{p}_v", cfg), 1, cfg)
            ck, cv = caches[i]
            # append + attend in ONE op: the caches' only read+write site,
            # which is what keeps them donation-provable (PT710-clean);
            # the active gate keeps retired / mid-chunk-prefill slots'
            # caches bit-untouched while their neighbours decode
            ctx = layers.fused_decode_attention(
                q, k, v, ck, cv, pos, scale=1.0 / math.sqrt(hd),
                page_size=page_size, slot_mask=active)
            att = _proj(_merge_heads(ctx, 1, cfg), cfg.hidden_size,
                        f"{p}_out", cfg)
            x = layers.elementwise_add(x, att)
            h = _ln(x, f"{p}_ln2")
            x = layers.elementwise_add(x, _mlp(h, p, cfg))
        h = _ln(x, "gpt_lnf")
        last_h = layers.reshape(h, [0, cfg.hidden_size])     # [B, H]
        logits = _logits(last_h, cfg, main.global_block)     # [B, V]
        next_tok = layers.sample_token(logits, strategy=strategy,
                                       temperature=temperature, top_k=top_k)
        one = layers.fill_constant([B, 1], "int64", 1)
        seq_cap = layers.fill_constant([B, 1], "int64", max_seq)
        # inactive slots neither advance their token nor their position
        # (position would otherwise saturate at max_seq overwriting the
        # last cache row; with the gate it simply freezes)
        act_i64 = layers.cast(active, "int64")
        inv = layers.elementwise_sub(one, act_i64)
        layers.assign(_merge_state(next_tok, tok, act_i64, inv), output=tok)
        new_pos = layers.elementwise_min(
            layers.elementwise_add(pos, one), seq_cap)
        layers.assign(_merge_state(new_pos, pos, act_i64, inv), output=pos)
        out = {"main": main, "next_token": next_tok, "state_vars": sv}
        if fetch_logits:
            out["logits"] = logits
    return out


def build_gpt_chunk(cfg: GptConfig, batch_slots: int, chunk: int,
                    max_seq: int, page_size: int = 8,
                    strategy: str = "greedy", temperature: float = 1.0,
                    top_k: int = 0, mode: str = "prefill"):
    """The q_len=C chunk phase over the paged cache — one program serves
    two schedulers (ISSUE 20):

    * ``mode='prefill'`` — one C-token slice of a chunked prefill: a long
      cold prompt (or the un-cached suffix after a prefix-cache hit) is
      admitted slice by slice between decode chunks, so resident decoders
      never stall behind a monolithic prefill. Feeds:

      - ``chunk_ids``   [B, C] int64 — this slice's tokens (padded);
      - ``chunk_pos``   [B, C] int64 — absolute position ids (host-fed,
        clamped to the position table);
      - ``chunk_start`` [B, 1] int64 — cache rows already written (the
        slice's append position);
      - ``chunk_len``   [B, 1] int64 — real tokens in this slice (1..C);
      - ``slot_mask``   [B, 1] float32 — slots in this dispatch;
      - ``sample_mask`` [B, 1] float32 — 1 on a prompt's FINAL slice:
        sample the first generated token from position ``chunk_len - 1``,
        commit it to the token state and flip the slot's decode gate.

      Position state advances by ``chunk_len`` on every slice (slot-
      masked); padding rows past ``chunk_len`` write K/V at positions the
      next slice overwrites, and the per-row causal mask keeps them out
      of every real query's softmax.

    * ``mode='verify'`` — the speculative-decoding verify step
      (C = 1 + draft length): ``chunk_ids`` carries the last committed
      token followed by the draft's proposals, the target scores every
      position in ONE dispatch, and ``layers.spec_accept`` commits the
      longest agreeing prefix + bonus token wholly in-program. Extra
      feed ``draft_ids`` [B, C-1] int64; no ``chunk_len``/``sample_mask``
      (a verify chunk is always full). Fetches ``sampled`` [B, C] (the
      target's token at every chunk position — the host streams
      ``sampled[:m+1]``) and ``accept_len`` [B, 1].
    """
    if mode not in ("prefill", "verify"):
        raise ValueError(f"build_gpt_chunk: mode must be 'prefill' or "
                         f"'verify', got {mode!r}")
    if chunk < 1:
        raise ValueError(f"build_gpt_chunk: chunk must be >= 1, got {chunk}")
    if mode == "verify" and chunk < 2:
        raise ValueError("build_gpt_chunk: a verify chunk needs >= 2 "
                         "positions (one committed token + >= 1 draft)")
    if max_seq % page_size:
        raise ValueError(f"max_seq {max_seq} must be a whole number of "
                         f"pages of page_size {page_size}")
    B, C = batch_slots, chunk
    nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
    main, throwaway = Program(), Program()
    with program_guard(main, throwaway):
        ids = layers.data("chunk_ids", shape=[B, C], dtype="int64",
                          append_batch_size=False)
        pos_ids = layers.data("chunk_pos", shape=[B, C], dtype="int64",
                              append_batch_size=False)
        start = layers.data("chunk_start", shape=[B, 1], dtype="int64",
                            append_batch_size=False)
        smask = layers.data("slot_mask", shape=[B, 1], dtype="float32",
                            append_batch_size=False)
        if mode == "prefill":
            clen = layers.data("chunk_len", shape=[B, 1], dtype="int64",
                               append_batch_size=False)
            sample_mask = layers.data("sample_mask", shape=[B, 1],
                                      dtype="float32",
                                      append_batch_size=False)
            feeds = ("chunk_ids", "chunk_pos", "chunk_start", "chunk_len",
                     "slot_mask", "sample_mask")
        else:
            drafts = layers.data("draft_ids", shape=[B, C - 1],
                                 dtype="int64", append_batch_size=False)
            feeds = ("chunk_ids", "chunk_pos", "chunk_start", "slot_mask",
                     "draft_ids")
        tok, pos, active, caches, sv = _state_vars(main.global_block, cfg,
                                                   B, max_seq)

        x = layers.elementwise_add(_embed(ids, cfg), _pos_embed(pos_ids, cfg))
        for i in range(cfg.num_layers):
            p = f"gpt_l{i}"
            h = _ln(x, f"{p}_ln1")
            q = _split_heads(_proj(h, cfg.hidden_size, f"{p}_q", cfg), C, cfg)
            k = _split_heads(_proj(h, cfg.hidden_size, f"{p}_k", cfg), C, cfg)
            v = _split_heads(_proj(h, cfg.hidden_size, f"{p}_v", cfg), C, cfg)
            ck, cv = caches[i]
            # C-row append + chunk-causal attend in ONE op (donation-
            # provable, like decode); the slot mask keeps every other
            # slot's pages bit-untouched
            ctx = layers.fused_decode_attention(
                q, k, v, ck, cv, start, scale=1.0 / math.sqrt(hd),
                page_size=page_size, slot_mask=smask)
            att = _proj(_merge_heads(ctx, C, cfg), cfg.hidden_size,
                        f"{p}_out", cfg)
            x = layers.elementwise_add(x, att)
            h = _ln(x, f"{p}_ln2")
            x = layers.elementwise_add(x, _mlp(h, p, cfg))
        h = _ln(x, "gpt_lnf")

        one = layers.fill_constant([B, 1], "int64", 1)
        out = {"main": main, "state_vars": sv, "feeds": feeds,
               "chunk": C, "mode": mode}
        if mode == "prefill":
            last = layers.elementwise_sub(clen, one)
            last_h = layers.sequence_gather(h, last)          # [B, H]
            logits = _logits(last_h, cfg, main.global_block)  # [B, V]
            first_tok = layers.sample_token(logits, strategy=strategy,
                                            temperature=temperature,
                                            top_k=top_k)
            # position advances by the slice length on EVERY slice; the
            # token + decode gate commit only on the final slice
            smask_i64 = layers.cast(smask, "int64")
            inv_s = layers.elementwise_sub(one, smask_i64)
            new_pos = layers.elementwise_add(start, clen)
            layers.assign(_merge_state(new_pos, pos, smask_i64, inv_s),
                          output=pos)
            eff = layers.elementwise_mul(smask, sample_mask)
            eff_i64 = layers.cast(eff, "int64")
            inv_e = layers.elementwise_sub(one, eff_i64)
            layers.assign(_merge_state(first_tok, tok, eff_i64, inv_e),
                          output=tok)
            one_f = layers.fill_constant([B, 1], "float32", 1.0)
            _activate_slots(active, eff, one_f)
            out["first_token"] = first_tok
        else:
            flat = layers.reshape(h, [0, C * cfg.hidden_size])
            flat = layers.reshape(flat, [B * C, cfg.hidden_size])
            logits = _logits(flat, cfg, main.global_block)    # [B*C, V]
            sampled = layers.sample_token(logits, strategy=strategy,
                                          temperature=temperature,
                                          top_k=top_k)         # [B*C, 1]
            sampled_bc = layers.reshape(sampled, [B, C])
            accept, new_tok, new_pos = layers.spec_accept(
                sampled_bc, drafts, start)
            smask_i64 = layers.cast(smask, "int64")
            inv_s = layers.elementwise_sub(one, smask_i64)
            layers.assign(_merge_state(new_tok, tok, smask_i64, inv_s),
                          output=tok)
            layers.assign(_merge_state(new_pos, pos, smask_i64, inv_s),
                          output=pos)
            out["sampled"] = sampled_bc
            out["accept_len"] = accept
            out["next_token"] = new_tok
    return out


def build_gpt_generative(cfg: GptConfig = None, batch_slots: int = 4,
                         max_seq: int = 64, page_size: int = 8,
                         prompt_buckets=(16,), strategy: str = "greedy",
                         temperature: float = 1.0, top_k: int = 0,
                         fetch_logits: bool = False,
                         prefill_chunk: int = None, spec_k: int = 4):
    """Everything the generative serving engine needs: one prefill program
    per prompt bucket + one decode program + the chunked-prefill and
    speculative-verify chunk programs (ISSUE 20) over shared weights, one
    startup program (parameters only — generation state is reset
    host-side by the engine), and the state-var table.

    ``prefill_chunk`` (default: one page) sizes the chunked-prefill
    slice; ``spec_k`` sizes the speculative chunk (1 committed token +
    ``spec_k - 1`` drafts per verify dispatch; ``spec_k < 2`` skips
    building the verify program)."""
    cfg = cfg or GptConfig.tiny()
    if cfg.max_position < max_seq:
        raise ValueError(f"max_seq {max_seq} exceeds the position table "
                         f"max_position {cfg.max_position}")
    prompt_buckets = tuple(sorted(set(int(b) for b in prompt_buckets)))
    if not prompt_buckets:
        raise ValueError("need at least one prompt bucket")
    prefill_chunk = int(prefill_chunk or page_size)
    prefill = {}
    startup = None
    for S in prompt_buckets:
        net = build_gpt_prefill(cfg, batch_slots, S, max_seq,
                                page_size=page_size, strategy=strategy,
                                temperature=temperature, top_k=top_k,
                                fetch_logits=fetch_logits, startup=startup)
        startup = net["startup"]
        prefill[S] = net
    decode = build_gpt_decode(cfg, batch_slots, max_seq,
                              page_size=page_size, strategy=strategy,
                              temperature=temperature, top_k=top_k,
                              fetch_logits=fetch_logits)
    chunk = build_gpt_chunk(cfg, batch_slots, prefill_chunk, max_seq,
                            page_size=page_size, strategy=strategy,
                            temperature=temperature, top_k=top_k,
                            mode="prefill")
    verify = None
    if spec_k >= 2:
        verify = build_gpt_chunk(cfg, batch_slots, spec_k, max_seq,
                                 page_size=page_size, strategy=strategy,
                                 temperature=temperature, top_k=top_k,
                                 mode="verify")
    return {"config": cfg, "startup": startup, "prefill": prefill,
            "decode": decode, "chunk": chunk, "verify": verify,
            "state_vars": decode["state_vars"],
            "batch_slots": batch_slots, "max_seq": max_seq,
            "page_size": page_size, "prompt_buckets": prompt_buckets,
            "prefill_chunk": prefill_chunk, "spec_k": int(spec_k),
            "strategy": strategy}
