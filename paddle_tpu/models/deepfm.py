"""DeepFM CTR model (BASELINE config #5).

Reference shape: the PSLib/Downpour CTR path — sparse id features pulled
from parameter-server embedding tables per batch
(paddle/fluid/framework/fleet/fleet_wrapper.h PullSparse,
operators/distributed/parameter_prefetch.cc remote lookup), dense+sparse
DeepFM as in the public PaddleRec deepfm config.

TPU-native: the tables are ordinary mesh-sharded embedding params
(``is_distributed=True`` row-shards them over the mesh in CompiledProgram);
the "pull" is an XLA gather with GSPMD-placed collectives, the "push" is the
reduce-scattered gradient — no parameter server.

Model: y = sigmoid(first_order + second_order + dnn).
 - first_order: sum_f w[x_f]                    (w: [vocab, 1] table)
 - second_order: 0.5 * ((sum_f v_f)^2 - sum_f v_f^2) summed over k
 - dnn: MLP over the concatenated field embeddings
"""
from __future__ import annotations

from .. import layers, optimizer as opt_mod
from ..framework import Program, program_guard
from ..param_attr import ParamAttr


def build_deepfm(vocab=1024, num_fields=8, emb_dim=8, hidden=(32, 32),
                 lr=1e-3, sharded=True, optimizer="adam"):
    """Feeds: feat_ids int64 [batch, num_fields], label float32 [batch, 1]."""
    main, startup = Program(), Program()
    with program_guard(main, startup):
        ids = layers.data("feat_ids", shape=[num_fields], dtype="int64")
        label = layers.data("label", shape=[1], dtype="float32")

        first = layers.embedding(ids, size=[vocab, 1],
                                 is_distributed=sharded,
                                 param_attr=ParamAttr(name="fm_w"))  # [B,F,1]
        first_order = layers.reshape(
            layers.reduce_sum(first, dim=[1, 2]), [-1, 1])         # [B,1]

        emb = layers.embedding(ids, size=[vocab, emb_dim],
                               is_distributed=sharded,
                               param_attr=ParamAttr(name="fm_v"))  # [B,F,K]
        sum_v = layers.reduce_sum(emb, dim=[1])                    # [B,K]
        sum_sq = layers.square(sum_v)
        sq_sum = layers.reduce_sum(layers.square(emb), dim=[1])
        second_order = layers.scale(
            layers.reduce_sum(layers.elementwise_sub(sum_sq, sq_sum),
                              dim=[1], keep_dim=True), scale=0.5)  # [B,1]

        h = layers.reshape(emb, [-1, int(num_fields * emb_dim)])
        for i, width in enumerate(hidden):
            h = layers.fc(h, width, act="relu", name=f"deep_fc{i}")
        dnn_out = layers.fc(h, 1, name="deep_out")                 # [B,1]

        logit = layers.elementwise_add(
            layers.elementwise_add(first_order, second_order), dnn_out)
        loss = layers.mean(
            layers.sigmoid_cross_entropy_with_logits(logit, label))
        pred = layers.sigmoid(logit)
        if optimizer == "adam":
            opt = opt_mod.Adam(learning_rate=lr)
        else:
            opt = opt_mod.SGD(learning_rate=lr)
        opt.minimize(loss)
    return {"main": main, "startup": startup, "loss": loss, "pred": pred,
            "feeds": ["feat_ids", "label"]}
