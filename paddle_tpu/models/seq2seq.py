"""Seq2seq (encoder-decoder RNN) with beam-search decoding.

Reference: the machine_translation book test
(python/paddle/fluid/tests/book/test_machine_translation.py) — encoder RNN,
teacher-forced decoder RNN for training, While-loop beam-search decoder for
inference (layers/control_flow.py While + beam_search ops).

TPU deltas: StaticRNN lowers to lax.scan (single fused loop, differentiable);
the decode loop is a bounded While (max_len) over dense [batch*beam] state —
the reference's LoD-based shrinking beams become masked fixed-width beams.
"""
from __future__ import annotations

import numpy as np

from .. import layers, optimizer
from ..framework import Program, program_guard
from ..param_attr import ParamAttr


def _cell(x_t, h_prev, hidden, name):
    """tanh RNN cell with shared (named) parameters."""
    merged = layers.concat([x_t, h_prev], axis=1)
    return layers.tanh(layers.fc(
        merged, hidden, bias_attr=False,
        param_attr=ParamAttr(name=f"{name}_w"), name=name))


def build_seq2seq_train(src_vocab, tgt_vocab, emb_dim=32, hidden=64,
                        src_len=8, tgt_len=8, batch=16, lr=1e-3):
    """Training program: returns dict with programs, feeds, loss."""
    main, startup = Program(), Program()
    with program_guard(main, startup):
        src = layers.data("src_ids", shape=[batch, src_len], dtype="int64",
                          append_batch_size=False)
        tgt_in = layers.data("tgt_in_ids", shape=[batch, tgt_len],
                             dtype="int64", append_batch_size=False)
        tgt_out = layers.data("tgt_out_ids", shape=[batch, tgt_len],
                              dtype="int64", append_batch_size=False)

        src_emb = layers.embedding(
            src, size=[src_vocab, emb_dim],
            param_attr=ParamAttr(name="src_emb_w"))      # [B, S, E]
        src_tm = layers.transpose(src_emb, [1, 0, 2])    # time-major

        enc = layers.StaticRNN()
        with enc.step():
            x_t = enc.step_input(src_tm)
            h_p = enc.memory(shape=[hidden], batch_ref=src_tm)
            h = _cell(x_t, h_p, hidden, "enc_cell")
            enc.update_memory(h_p, h)
            enc.step_output(h)
        enc_states = enc()                                # [S, B, H]
        enc_final = layers.reshape(
            layers.slice(enc_states, axes=[0], starts=[src_len - 1],
                         ends=[src_len]), [batch, hidden])

        tgt_emb = layers.embedding(
            tgt_in, size=[tgt_vocab, emb_dim],
            param_attr=ParamAttr(name="tgt_emb_w"))
        tgt_tm = layers.transpose(tgt_emb, [1, 0, 2])

        dec = layers.StaticRNN()
        with dec.step():
            x_t = dec.step_input(tgt_tm)
            h_p = dec.memory(init=enc_final)
            h = _cell(x_t, h_p, hidden, "dec_cell")
            dec.update_memory(h_p, h)
            dec.step_output(h)
        dec_states = dec()                                # [T, B, H]
        flat = layers.reshape(dec_states, [tgt_len * batch, hidden])
        logits = layers.fc(flat, tgt_vocab,
                           param_attr=ParamAttr(name="proj_w"),
                           bias_attr=False, name="proj")
        labels_tm = layers.transpose(tgt_out, [1, 0])     # [T, B]
        labels = layers.reshape(labels_tm, [tgt_len * batch, 1])
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, labels))
        optimizer.Adam(lr).minimize(loss)
    return {"main": main, "startup": startup, "loss": loss,
            "feeds": ["src_ids", "tgt_in_ids", "tgt_out_ids"]}


def build_seq2seq_train_varlen(src_vocab, tgt_vocab, emb_dim=32, hidden=64,
                               lr=1e-3):
    """Variable-length training path (BASELINE config #4): src/tgt are
    lod_level-1 feeds in the padded+lengths encoding; the encoder's final
    state is the LAST valid step (sequence_pool), and the token loss is
    masked by the target lengths (sequence_pool SUM / total tokens) so pad
    positions contribute nothing. Batches of different bucketed max_len
    compile separate executables (bounded by the feeder's bucket table)."""
    main, startup = Program(), Program()
    with program_guard(main, startup):
        src = layers.data("src_ids", shape=[1], dtype="int64", lod_level=1)
        tgt_in = layers.data("tgt_in_ids", shape=[1], dtype="int64",
                             lod_level=1)
        tgt_out = layers.data("tgt_out_ids", shape=[1], dtype="int64",
                              lod_level=1)

        src_emb = layers.embedding(
            src, size=[src_vocab, emb_dim],
            param_attr=ParamAttr(name="src_emb_w"))       # [B, S, E]
        src_tm = layers.transpose(src_emb, [1, 0, 2])     # time-major

        enc = layers.StaticRNN()
        with enc.step():
            x_t = enc.step_input(src_tm)
            h_p = enc.memory(shape=[hidden], batch_ref=src_tm)
            h = _cell(x_t, h_p, hidden, "enc_cell")
            enc.update_memory(h_p, h)
            enc.step_output(h)
        enc_bm = layers.transpose(enc(), [1, 0, 2])       # [B, S, H]
        # last VALID state per source sequence (not the padded final step);
        # lengths are inferred through the transpose/scan/embedding chain
        enc_final = layers.sequence_pool(enc_bm, "last")

        tgt_emb = layers.embedding(
            tgt_in, size=[tgt_vocab, emb_dim],
            param_attr=ParamAttr(name="tgt_emb_w"))
        tgt_tm = layers.transpose(tgt_emb, [1, 0, 2])

        dec = layers.StaticRNN()
        with dec.step():
            x_t = dec.step_input(tgt_tm)
            h_p = dec.memory(init=enc_final)
            h = _cell(x_t, h_p, hidden, "dec_cell")
            dec.update_memory(h_p, h)
            dec.step_output(h)
        dec_bm = layers.transpose(dec(), [1, 0, 2])       # [B, T, H]
        logits = layers.fc(dec_bm, tgt_vocab, num_flatten_dims=2,
                           param_attr=ParamAttr(name="proj_w"),
                           bias_attr=False, name="proj")  # [B, T, V]
        ce = layers.softmax_with_cross_entropy(logits, tgt_out)  # [B, T, 1]
        ce = layers.squeeze(ce, axes=[2])                 # [B, T]
        seq_loss = layers.sequence_pool(ce, "sum")        # masked per-seq sum
        n_tokens = layers.cast(layers.reduce_sum(
            layers.sequence.seq_len_var(tgt_out)), "float32")
        loss = layers.elementwise_div(layers.reduce_sum(seq_loss), n_tokens)
        optimizer.Adam(lr).minimize(loss)
    return {"main": main, "startup": startup, "loss": loss,
            "feeds": ["src_ids", "tgt_in_ids", "tgt_out_ids"],
            "feed_vars": [src, tgt_in, tgt_out]}


def build_seq2seq_infer(src_vocab, tgt_vocab, emb_dim=32, hidden=64,
                        src_len=8, batch=4, beam_size=4, max_len=8,
                        bos_id=0, eos_id=1):
    """Beam-search decode program sharing parameter names with training.

    Returns dict with program, feed name, fetches [ids, scores]:
    SentenceIds is [max_len, batch*beam] chronological tokens."""
    main, startup = Program(), Program()
    nbk = batch * beam_size
    with program_guard(main, startup):
        src = layers.data("src_ids", shape=[batch, src_len], dtype="int64",
                          append_batch_size=False)
        src_emb = layers.embedding(
            src, size=[src_vocab, emb_dim],
            param_attr=ParamAttr(name="src_emb_w"))
        src_tm = layers.transpose(src_emb, [1, 0, 2])
        enc = layers.StaticRNN()
        with enc.step():
            x_t = enc.step_input(src_tm)
            h_p = enc.memory(shape=[hidden], batch_ref=src_tm)
            h = _cell(x_t, h_p, hidden, "enc_cell")
            enc.update_memory(h_p, h)
            enc.step_output(h)
        enc_states = enc()
        enc_final = layers.reshape(
            layers.slice(enc_states, axes=[0], starts=[src_len - 1],
                         ends=[src_len]), [batch, hidden])
        # tile beam copies: [B, H] -> [B*beam, H]
        state = layers.reshape(
            layers.expand(layers.unsqueeze(enc_final, axes=[1]),
                          expand_times=[1, beam_size, 1]), [nbk, hidden])

        ids_arr = layers.create_array("int64")
        sc_arr = layers.create_array("float32")
        par_arr = layers.create_array("int64")

        i = layers.fill_constant([1], "int64", 0)
        # seed entries fix the element shapes so the arrays can enter the
        # While loop as fixed-capacity buffers; step 0 overwrites them
        layers.array_write(layers.fill_constant([nbk, 1], "int64", bos_id),
                           i, ids_arr)
        layers.array_write(layers.fill_constant([nbk, 1], "float32", 0.0),
                           i, sc_arr)
        layers.array_write(layers.fill_constant([nbk], "int64", 0),
                           i, par_arr)
        n = layers.fill_constant([1], "int64", max_len)
        pre_ids = layers.fill_constant([nbk, 1], "int64", bos_id)
        # Only beam slot 0 of each source enters step 0 live; slots 1..K-1
        # start at -1e9 so top-k doesn't select K identical candidates from
        # the K duplicated parent rows (the reference starts with one beam
        # per source via LoD; with dense fixed-width beams the mask does it).
        init_scores = np.where(
            (np.arange(nbk) % beam_size == 0)[:, None], 0.0, -1e9
        ).astype(np.float32)
        pre_scores = layers.assign(init_scores)
        cond = layers.less_than(i, n)
        w = layers.While(cond, max_len=max_len + 1)
        with w.block():
            emb = layers.embedding(
                layers.reshape(pre_ids, [nbk]),
                size=[tgt_vocab, emb_dim],
                param_attr=ParamAttr(name="tgt_emb_w"))
            h = _cell(emb, state, hidden, "dec_cell")
            logits = layers.fc(h, tgt_vocab,
                               param_attr=ParamAttr(name="proj_w"),
                               bias_attr=False, name="proj_infer")
            logprob = layers.log_softmax(logits)          # [nbk, V]
            acc = layers.elementwise_add(logprob, pre_scores)
            blk = main.current_block()
            sel_ids = blk.create_var(
                name=f"bs_sel_ids_{id(main)}", shape=(nbk, 1), dtype="int64")
            sel_sc = blk.create_var(
                name=f"bs_sel_sc_{id(main)}", shape=(nbk, 1), dtype="float32")
            parent = blk.create_var(
                name=f"bs_parent_{id(main)}", shape=(nbk,), dtype="int64")
            blk.append_op("beam_search",
                          inputs={"pre_ids": pre_ids,
                                  "pre_scores": pre_scores, "scores": acc},
                          outputs={"selected_ids": sel_ids,
                                   "selected_scores": sel_sc,
                                   "parent_idx": parent},
                          attrs={"beam_size": beam_size, "end_id": eos_id})
            # reorder decoder state by parent beam
            new_h = layers.gather(h, parent)
            layers.assign(new_h, state)
            layers.assign(sel_ids, pre_ids)
            layers.assign(sel_sc, pre_scores)
            layers.array_write(sel_ids, i, ids_arr)
            layers.array_write(sel_sc, i, sc_arr)
            layers.array_write(parent, i, par_arr)
            layers.increment(i, value=1)
            layers.assign(layers.less_than(i, n), cond)

        blk = main.global_block
        s_ids = blk.create_var(name="decoded_ids",
                               shape=(max_len + 1, nbk), dtype="int64")
        s_sc = blk.create_var(name="decoded_scores",
                              shape=(max_len + 1, nbk), dtype="float32")
        blk.append_op("beam_search_decode",
                      inputs={"Ids": ids_arr, "Scores": sc_arr,
                              "ParentIdx": par_arr},
                      outputs={"SentenceIds": s_ids, "SentenceScores": s_sc},
                      attrs={"beam_size": beam_size, "end_id": eos_id})
    return {"main": main, "startup": startup,
            "feeds": ["src_ids"], "fetches": ["decoded_ids",
                                              "decoded_scores"]}
